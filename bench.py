"""Driver benchmark entry: one JSON line {metric, value, unit, vs_baseline}.

Thin wrapper; the implementation lives in p2pmicrogrid_tpu.benchmarks so the
installed package exposes the same benchmark via the CLI (`... bench`).
"""

from p2pmicrogrid_tpu.benchmarks import main

if __name__ == "__main__":
    main()
