"""Calibrate the chunked shared-DDPG learning-rate scale against scale.

Round 3 measured (artifacts/LEARNING_chunked_r03.json) that the DDPG default
lrs (1e-4/2e-4) diverge in chunked aggregate-scenario mode at 100 agents
(pooled update batch = batch*S*A = 25.6k transitions) while lr/4 is stable.
To turn that observation into a default RULE (scale lrs automatically with
the pooled batch, round-3 VERDICT item 1) we need the stable lr at more than
one pooled-batch size.  This tool trains the chunked shared-critic community
at a given (A, S_chunk, K) for several lr scales and records the greedy
held-out community cost curve per scale; the cross-scale fit picks the rule.

Usage::

    PYTHONPATH=/root/repo python tools/lr_calibration.py \
        --agents 1000 --chunk-scenarios 128 --chunks 4 \
        --episodes 120 --eval-every 20 --scales 0.25,0.125,0.056 \
        --out artifacts/lr_probe_a1000.json

Emits incremental progress on stderr and one JSON document on --out.
"""

from __future__ import annotations

import argparse
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np

from p2pmicrogrid_tpu.config import (
    BatteryConfig,
    DDPGConfig,
    SimConfig,
    TrainConfig,
    default_config,
)
from p2pmicrogrid_tpu.envs import init_physical, make_ratings
from p2pmicrogrid_tpu.envs.community import AgentRatings, slot_dynamics_batched
from p2pmicrogrid_tpu.models.ddpg import ddpg_shared_act
from p2pmicrogrid_tpu.parallel import init_shared_pol_state
from p2pmicrogrid_tpu.parallel.device_gen import device_episode_arrays
from p2pmicrogrid_tpu.parallel.scenarios import (
    make_chunked_episode_runner,
    make_shared_episode_fn,
    train_scenarios_chunked,
)
from p2pmicrogrid_tpu.train import make_policy


def build_cfg(args, scale: float):
    return default_config(
        sim=SimConfig(
            n_agents=args.agents,
            n_scenarios=args.chunk_scenarios,
            market_dtype=args.market_dtype,
        ),
        battery=BatteryConfig(enabled=True),
        train=TrainConfig(implementation="ddpg"),
        ddpg=DDPGConfig(
            buffer_size=96,
            batch_size=4,
            share_across_agents=True,
            actor_lr=1e-4 * scale,
            critic_lr=2e-4 * scale,
            lr_auto_scale=False,  # this tool IS the calibration of that rule
        ),
    )


def run_scale(args, scale: float) -> list:
    cfg = build_cfg(args, scale)
    ratings = make_ratings(cfg, np.random.default_rng(42))
    ratings_j = AgentRatings(*(jnp.asarray(a) for a in ratings))
    policy = make_policy(cfg)
    params = init_shared_pol_state(cfg, jax.random.PRNGKey(0))
    S_eval = args.eval_scenarios

    eval_arrays = device_episode_arrays(
        cfg, jax.random.PRNGKey(10_000), ratings, S_eval
    )

    @jax.jit
    def greedy_cost(params, key):
        def act_fn(p, obs_s, prev, round_key, ex):
            frac, q, _ = ddpg_shared_act(
                cfg.ddpg, p, obs_s, jnp.zeros(obs_s.shape[:2]),
                round_key, explore=False,
            )
            return frac, frac, q, ex

        k_phys, k_scan = jax.random.split(key)
        phys = jax.vmap(lambda k: init_physical(cfg, k))(
            jax.random.split(k_phys, S_eval)
        )
        xs = jax.tree_util.tree_map(lambda x: jnp.swapaxes(x, 0, 1), eval_arrays)
        xs = (xs.time, xs.t_out, xs.load_w, xs.pv_w,
              xs.next_time, xs.next_load_w, xs.next_pv_w)

        def slot(carry, xs_t):
            phys_s, kk = carry
            kk, k_act = jax.random.split(kk)
            phys_s, _, out, _, _ = slot_dynamics_batched(
                cfg, policy, params, phys_s, xs_t, k_act, ratings_j,
                explore=False, act_fn=act_fn,
            )
            return (phys_s, kk), (out.cost, out.reward)

        (_, _), (cost, reward) = jax.lax.scan(slot, (phys, k_scan), xs)
        return jnp.sum(cost, axis=(0, 2)).mean(), jnp.sum(
            jnp.mean(reward, axis=-1), axis=0
        ).mean()

    episode_fn = make_shared_episode_fn(
        cfg, policy, None, ratings,
        arrays_fn=lambda k: device_episode_arrays(
            cfg, k, ratings, args.chunk_scenarios
        ),
        n_scenarios=args.chunk_scenarios,
    )
    runner = make_chunked_episode_runner(cfg, episode_fn, args.chunks)

    curve = []

    def record(ep, extra=None):
        c, r = greedy_cost(params, jax.random.PRNGKey(1))
        row = {"episode": ep, "greedy_cost_eur": round(float(c), 2),
               "greedy_reward": round(float(r), 1)}
        row.update(extra or {})
        curve.append(row)
        print(f"scale={scale}", row, file=sys.stderr, flush=True)

    record(0)
    key = jax.random.PRNGKey(7)
    for start in range(0, args.episodes, args.eval_every):
        params, rewards, _, secs = train_scenarios_chunked(
            cfg, policy, params, ratings, key,
            n_episodes=args.eval_every, n_chunks=args.chunks, episode0=start,
            episode_fn=episode_fn, runner=runner,
        )
        record(start + args.eval_every, {
            "train_reward_mean": round(float(np.mean(rewards[-5:])), 1),
            "train_secs": round(secs, 1),
        })
    return curve


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--agents", type=int, default=100)
    ap.add_argument("--chunk-scenarios", type=int, default=64)
    ap.add_argument("--chunks", type=int, default=4)
    ap.add_argument("--episodes", type=int, default=120)
    ap.add_argument("--eval-every", type=int, default=20)
    ap.add_argument("--eval-scenarios", type=int, default=8)
    ap.add_argument("--scales", default="0.25,0.125")
    ap.add_argument("--market-dtype", default="float32")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    scales = [float(s) for s in args.scales.split(",")]
    pooled = 4 * args.chunk_scenarios * args.agents
    doc = {
        "what": (
            "Greedy held-out cost curves for chunked shared-critic DDPG at "
            "several lr scales (x the 1e-4/2e-4 defaults) — calibration data "
            "for the automatic pooled-batch lr rule."
        ),
        "config": {
            "n_agents": args.agents,
            "chunk_scenarios": args.chunk_scenarios,
            "chunks": args.chunks,
            "pooled_batch": pooled,
            "episodes": args.episodes,
            "eval_scenarios": args.eval_scenarios,
            "market_dtype": args.market_dtype,
            "device": jax.devices()[0].device_kind,
        },
        "scales": {},
    }
    for s in scales:
        doc["scales"][str(s)] = run_scale(args, s)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(doc, f, indent=2)
    print(json.dumps(doc, indent=2) if not args.out else f"wrote {args.out}")


if __name__ == "__main__":
    main()
