#!/bin/sh
# Duty-cycle throttle for long trainings on a shared machine: every PERIOD
# seconds, SIGSTOP the target PID, wait PAUSE seconds, SIGCONT it.
# (Ops-utility parity with the reference's monitor.sh:5-11.)
#
# Usage: tools/monitor.sh PID [PERIOD=600] [PAUSE=60]

PID=${1:?usage: monitor.sh PID [PERIOD] [PAUSE]}
PERIOD=${2:-600}
PAUSE=${3:-60}

while kill -0 "$PID" 2>/dev/null; do
    sleep "$PERIOD"
    kill -STOP "$PID" 2>/dev/null || break
    sleep "$PAUSE"
    kill -CONT "$PID" 2>/dev/null || break
done
