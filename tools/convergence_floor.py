"""Measured floor argument for the reference-defaults convergence metric.

``episodes_to_converged_mean_price_2agent_tabular`` sits at ~935 of the
reference's 1000-episode budget (BENCH_r03) and round-3's VERDICT asked for
either ≤800 at reference defaults or a measured argument that ~935 is the
schedule's floor. This tool runs the ablations that make that argument:

1. **defaults** — the bench's exact configuration (anchor).
2. **alpha0** — learning OFF (alpha=0), everything else default: any
   "convergence" is pure estimator noise + the epsilon schedule. Measured
   round 4: fires at ~988 — LATER than with learning, so the detector
   cannot fire early even when there is nothing to converge.
3. **eps_floor** — epsilon pinned at its floor (0.1) from episode 0, so the
   behavior policy is stationary modulo learning: still ~969.
4. **greedy_estimator** — per-episode price measured from the GREEDY policy
   on a fixed evaluation draw (deterministic estimator, zero exploration
   noise): still ~942, and the raw greedy price remains spread ~±20% late
   in training — the alpha=1e-5 tabular policy itself keeps flipping
   argmaxes for the whole budget.

Why this is a floor: the detector (benchmarks.converged_episode) fires at
the first window that stays within band=max(0.002 EUR/kWh, 2%) of the FINAL
window. The ablations show the 50-episode-window price series has
window-to-window variation of the band's order under EVERY noise source
removal that leaves the reference's alpha/epsilon/rounds schedule intact —
so the first window that stays within band of the final one is necessarily
near the end of ANY run of this schedule. Beating ~935 at strict reference
defaults would require changing the learner's step size or schedule, which
is exactly what the opt-in accelerated line does (7.14x, BENCH).

Writes ``artifacts/CONVERGENCE_FLOOR_r04.json``.

Usage: ``JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python
tools/convergence_floor.py`` (single-scenario 2-agent tabular is host-XLA
fast; artifacts/CROSSOVER_r03.json).
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np

from p2pmicrogrid_tpu.benchmarks import _convergence_prices, converged_episode
from p2pmicrogrid_tpu.config import (
    QLearningConfig,
    SimConfig,
    TrainConfig,
    default_config,
)

OUT = "artifacts/CONVERGENCE_FLOOR_r05.json"
WINDOW = 50


def greedy_prices(
    cfg, episodes: int = 1000, block: int = 10, seed: int = 0
) -> np.ndarray:
    """Training at defaults, but the per-episode price comes from a greedy
    (training=False) episode on a FIXED draw — the deterministic estimator
    ablation. ``seed`` varies init + episode keys (seed 0 = round-4 run)."""
    import jax
    import jax.numpy as jnp

    from p2pmicrogrid_tpu.data import synthetic_traces
    from p2pmicrogrid_tpu.envs import (
        build_episode_arrays,
        init_physical,
        make_ratings,
        run_episode,
    )
    from p2pmicrogrid_tpu.train import init_policy_state, make_policy

    decay_every = cfg.train.min_episodes_criterion
    traces = synthetic_traces(n_days=1, start_day=11).normalized()
    ratings = make_ratings(cfg, np.random.default_rng(42))
    arrays = build_episode_arrays(cfg, traces, ratings)
    policy = make_policy(cfg)
    ps = init_policy_state(cfg, jax.random.PRNGKey(seed))

    @jax.jit
    def price_block(ps, episode0, key):
        def body(ps, xs):
            i, k = xs
            k_phys, k_ep = jax.random.split(k)
            phys = init_physical(cfg, k_phys)
            _, ps, _ = run_episode(
                cfg, policy, ps, phys, arrays, ratings, k_ep, training=True
            )
            phys_e = init_physical(cfg, jax.random.PRNGKey(123))
            _, _, out = run_episode(
                cfg, policy, ps, phys_e, arrays, ratings,
                jax.random.PRNGKey(7), training=False,
            )
            e = jnp.sum(jnp.maximum(out.p_p2p, 0.0), axis=-1)
            tot = jnp.sum(e)
            price = jnp.where(
                tot > 0, jnp.sum(out.trade_price * e) / tot, jnp.nan
            )
            ps = jax.lax.cond(
                (episode0 + i) % decay_every == 0, policy.decay, lambda s: s, ps
            )
            return ps, price

        return jax.lax.scan(
            body, ps, (jnp.arange(block), jax.random.split(key, block))
        )

    key = (
        jax.random.PRNGKey(42)
        if seed == 0
        else jax.random.fold_in(jax.random.PRNGKey(42), seed)
    )
    prices = np.empty(episodes)
    for b in range(0, episodes, block):
        key, k = jax.random.split(key)
        ps, p = price_block(ps, b, k)
        prices[b:b + block] = np.asarray(p)
    return prices


def summarize(prices: np.ndarray) -> dict:
    ma = np.convolve(prices, np.ones(WINDOW) / WINDOW, mode="valid")
    final = float(ma[-1])
    band = max(0.002, 0.02 * abs(final))
    # Window-to-window variation on non-overlapping windows: the noise the
    # detector must wait out.
    strides = ma[::WINDOW]
    return {
        "converged_episode": int(converged_episode(prices, WINDOW)),
        "final_windowed_price": round(final, 5),
        "band": round(band, 5),
        "windowed_price_range": [round(float(ma.min()), 5),
                                 round(float(ma.max()), 5)],
        "stride_window_std": round(float(np.std(strides)), 5),
        "raw_price_std_last_100": round(float(np.std(prices[-100:])), 5),
    }


SEEDS = (0, 1, 2)


def main() -> None:
    base = default_config(
        sim=SimConfig(n_agents=2, slot_unroll=4),
        train=TrainConfig(implementation="tabular"),
    )
    cfgs = {
        "defaults": base,
        "alpha0_no_learning": dataclasses.replace(
            base, qlearning=QLearningConfig(alpha=0.0)
        ),
        "eps_floor_from_start": dataclasses.replace(
            base, qlearning=QLearningConfig(epsilon=0.1, epsilon_decay=1.0)
        ),
    }

    variants = {}
    for name, cfg in cfgs.items():
        per_seed = {
            f"seed{s}": summarize(_convergence_prices(cfg, seed=s))
            for s in SEEDS
        }
        per_seed["converged_episodes"] = [
            per_seed[f"seed{s}"]["converged_episode"] for s in SEEDS
        ]
        variants[name] = per_seed
        print(name, per_seed["converged_episodes"], flush=True)
    per_seed = {
        f"seed{s}": summarize(greedy_prices(base, seed=s)) for s in SEEDS
    }
    per_seed["converged_episodes"] = [
        per_seed[f"seed{s}"]["converged_episode"] for s in SEEDS
    ]
    variants["greedy_estimator"] = per_seed
    print("greedy_estimator", per_seed["converged_episodes"], flush=True)

    doc = {
        "round": 5,
        "what": (
            "Floor argument for episodes_to_converged_mean_price at strict "
            "reference defaults, now on 3 seeds per variant (round-4 ran "
            "one): the detector's band (0.002 EUR/kWh) is of the same order "
            "as the 50-episode-window price noise under every "
            "schedule-preserving ablation — including NO LEARNING — so it "
            "can only fire near the end of any run, for every seed. See "
            "module docstring of tools/convergence_floor.py."
        ),
        "window": WINDOW,
        "seeds": list(SEEDS),
        "variants": variants,
    }
    with open(OUT, "w") as f:
        json.dump(doc, f, indent=2)
    print(json.dumps(doc, indent=2))


if __name__ == "__main__":
    main()
