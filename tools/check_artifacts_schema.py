#!/usr/bin/env python
"""Validate benchmark captures and telemetry runs against their schemas.

Silent format drift has already cost this repo real signal: BENCH_r05.json
carried stray non-JSON fragments ("d!" tails) interleaved with metric lines,
and nothing noticed until a reviewer read the raw capture. This checker makes
the contracts executable:

* Root ``BENCH_*.json`` (driver captures): a JSON object with ``n`` (int),
  ``cmd`` (str), ``rc`` (int), ``tail`` (str) and ``parsed``; ``parsed``
  must be a metric row. With ``--strict-tail`` (opt-in), noise interleaved
  BETWEEN metric lines in the tail is also reported; the default skips that
  check because pre-telemetry captures are historical — new captures go
  through the guarded stdout sink and must pass strict.

* Metric rows (``parsed``, and each line of ``artifacts/BENCH_*.jsonl``):
  JSON objects with ``metric`` (str), ``value`` (number), ``unit`` (str)
  and ``vs_baseline`` (number). Extra context keys are allowed.

* Telemetry run directories (``artifacts/runs/<run_id>/``, the layout
  documented in telemetry/registry.py): ``manifest.json`` must be an object
  with ``run_id`` and ``created``; every non-empty ``metrics.jsonl`` line
  must be a JSON object with numeric ``ts`` and string ``kind``;
  ``summary.json`` (when present) must carry ``counters``/``gauges``/
  ``histograms``/``spans`` objects; ``trace.json`` (when present) must be a
  Chrome trace object with a ``traceEvents`` list.

* Policy bundles (``bundles/*/`` and ``artifacts/bundles/*/``, the serving
  export format of serve/export.py): ``manifest.json`` must declare
  ``kind: "policy_bundle"`` with an integer ``format_version``, a known
  ``implementation``, the obs/action spec objects and a ``params_file``
  that exists next to it.

* Serve-bench captures (``artifacts/SERVE_*.jsonl``): metric rows, same
  schema as the bench captures.

* Gateway bench captures (``artifacts/SERVE_GATEWAY_*.jsonl``): metric
  rows, and any ``serve_bench_network`` headline row must carry the wire
  percentiles (``p50_ms``/``p95_ms``/``p99_ms``), ``throughput_rps`` and
  ``shed_rate`` as numbers.

* Gateway stats snapshots (``artifacts/GATEWAY_STATS_*.json``, the
  ``GET /stats`` document of serve/gateway.py): ``kind: "gateway_stats"``
  with a non-empty ``bundles`` object, the ``default`` hash present in it,
  and ``gateway``/``admission`` counter objects.

* Fleet chaos captures (``artifacts/FLEET_*.jsonl``, serve-bench --fleet):
  metric rows, and any ``serve_bench_fleet`` headline row must carry the
  resilience SLO contract — numeric ``p50_ms``/``p95_ms``/``p99_ms``/
  ``throughput_rps``/``availability``/``failover_count``/``retry_rate``/
  ``shed_rate`` — with ``availability`` in [0, 1].

* Resilience captures (``artifacts/RESILIENCE_*.jsonl``, `train
  --supervise` / rollback runs): metric rows, any ``train_supervised``
  headline must carry numeric ``kills``/``resumes``/``rollbacks``/
  ``final_episode`` and a boolean ``bit_exact``; ``train_rollback_total``
  rows must carry a boolean ``converged``.

* Checkpoint integrity manifests (``models*/models_*/<setting>/ep_*/
  p2p_manifest.json``, the atomic-save record of train/checkpoint.py):
  ``kind: "checkpoint_manifest"`` with integer format_version/episode, a
  ``sha256:`` digest, a non-empty tree spec (shape/dtype per leaf) and
  ``payload_keys`` including ``pol_state``, next to actual payload files.

* Regime captures (``artifacts/REGIME_*.jsonl``, `regime-bench` —
  p2pmicrogrid_tpu/regimes/): metric rows; every ``regime_eval`` row must
  carry a string ``regime``, numeric ``cost_eur`` and boolean
  ``held_out``; ``regime_gate_case`` rows boolean ``blocked``/
  ``mean_improved`` + string ``regressed_regime``; any
  ``regime_generalization`` row (here or in any bench sweep) numeric
  train/held-out costs + gap, non-empty string regime-id lists, boolean
  ``held_out``/``single_compile`` and a numeric ``per_regime_cost``
  object — and the capture's LAST row must be that headline.

* Results databases (``*.db``/``*.sqlite`` at the root and under
  ``artifacts/``): when a DB carries telemetry warehouse tables
  (``data/results.py``), its ``PRAGMA user_version`` must match the
  expected telemetry schema version, the three telemetry tables must all
  exist together, and every ``telemetry_points``/``telemetry_spans`` row
  must reference a ``telemetry_runs`` row (orphan-free foreign keys —
  SQLite does not enforce them unless asked, so drift is silent).

Exit status: 0 when everything validates, 1 with one problem per line on
stderr otherwise. Stdlib-only — runs with the accelerator stack down.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

METRIC_ROW_KEYS = {
    "metric": str,
    "value": (int, float),
    "unit": str,
    "vs_baseline": (int, float),
}


def check_metric_row(row, where: str, problems: list) -> None:
    if not isinstance(row, dict):
        problems.append(f"{where}: metric row is {type(row).__name__}, not object")
        return
    for key, typ in METRIC_ROW_KEYS.items():
        if key not in row:
            problems.append(f"{where}: metric row missing key {key!r}")
        elif not isinstance(row[key], typ) or isinstance(row[key], bool):
            problems.append(
                f"{where}: metric row key {key!r} has type "
                f"{type(row[key]).__name__}"
            )


def check_bench_capture(path: str, problems: list, strict_tail: bool) -> None:
    where = os.path.basename(path)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as err:
        problems.append(f"{where}: unreadable ({err})")
        return
    if not isinstance(doc, dict):
        problems.append(f"{where}: top level is {type(doc).__name__}, not object")
        return
    for key, typ in (("n", int), ("cmd", str), ("rc", int), ("tail", str)):
        if key not in doc:
            problems.append(f"{where}: missing key {key!r}")
        elif not isinstance(doc[key], typ) or isinstance(doc[key], bool):
            problems.append(f"{where}: key {key!r} has type {type(doc[key]).__name__}")
    if "parsed" in doc and doc["parsed"] is not None:
        check_metric_row(doc["parsed"], f"{where}:parsed", problems)
    if strict_tail and isinstance(doc.get("tail"), str):
        lines = [l for l in doc["tail"].splitlines() if l.strip()]
        # Noise check only applies between/after metric lines: the capture
        # window may open mid-line, so a leading fragment before the first
        # JSON line is a truncation artifact, not emitted noise.
        seen_metric = False
        for i, line in enumerate(lines):
            try:
                json.loads(line)
                seen_metric = True
            except json.JSONDecodeError:
                if seen_metric:
                    problems.append(
                        f"{where}: non-JSON noise in tail line {i}: {line[:60]!r}"
                    )


def _iter_jsonl_rows(path: str, problems: list):
    """Yield (row, "relpath:lineno") for each JSON line; parse problems are
    reported once here so every per-row checker shares one read."""
    where = os.path.relpath(path)
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError as err:
        problems.append(f"{where}: unreadable ({err})")
        return
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            problems.append(f"{where}:{i + 1}: not valid JSON: {line[:60]!r}")
            continue
        yield row, f"{where}:{i + 1}"


def check_metric_jsonl(path: str, problems: list) -> None:
    for row, where in _iter_jsonl_rows(path, problems):
        check_metric_row(row, where, problems)
        check_rawspeed_row(row, where, problems)
        check_regime_row(row, where, problems)


# Raw-speed rows (ISSUE 12): the three bench families the megakernel /
# quantized-serving round added. Validated in EVERY metric jsonl sweep —
# a slot_fused row without its bit-exactness verdict, or a serve_quantized
# row with an unknown dtype, measured nothing the raw-speed pass promises.
QUANT_DTYPES = ("float32", "float16", "int8")


def _require_numeric(row, keys, where, problems, label):
    for key in keys:
        v = row.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            problems.append(f"{where}: {label} row missing numeric {key!r}")


def _require_bool(row, keys, where, problems, label):
    for key in keys:
        if not isinstance(row.get(key), bool):
            problems.append(f"{where}: {label} row missing boolean {key!r}")


def check_rawspeed_rows(path: str, problems: list) -> None:
    """slot_fused / serve_quantized / pipeline_depth row contracts inside a
    metric jsonl capture, without the general metric-row checks (rows of
    other metrics are ignored; check_all reaches check_rawspeed_row through
    check_metric_jsonl's single parse instead)."""
    parse_problems: list = []
    for row, where in _iter_jsonl_rows(path, parse_problems):
        if isinstance(row, dict):
            check_rawspeed_row(row, where, problems)


def check_rawspeed_row(row: dict, where: str, problems: list) -> None:
    """One row's raw-speed contract (no-op for rows of other metrics)."""
    if not isinstance(row, dict):
        return
    metric = row.get("metric")
    if not isinstance(metric, str):
        return
    if metric.startswith("slot_fused"):
        _require_numeric(
            row,
            ("speedup", "fused_env_steps_per_sec",
             "unfused_env_steps_per_sec"),
            where, problems, "slot_fused",
        )
        _require_bool(row, ("bit_exact",), where, problems, "slot_fused")
    elif metric.startswith("serve_quantized"):
        _require_numeric(
            row,
            ("p50_ms", "p99_ms", "cold_start_s", "swap_warmup_s"),
            where, problems, "serve_quantized",
        )
        _require_bool(
            row, ("bit_exact",), where, problems, "serve_quantized"
        )
        if row.get("dtype") not in QUANT_DTYPES:
            problems.append(
                f"{where}: serve_quantized row dtype "
                f"{row.get('dtype')!r} not in {QUANT_DTYPES}"
            )
    elif metric.startswith("pipeline_depth"):
        _require_numeric(
            row,
            ("speedup", "depth_1_env_steps_per_sec",
             "depth_2_env_steps_per_sec", "depth_4_env_steps_per_sec"),
            where, problems, "pipeline_depth",
        )


# Regime rows (ISSUE 13, p2pmicrogrid_tpu/regimes/): the scenario-regime
# engine's three row families. Validated in every metric jsonl sweep — a
# regime_generalization row without its per-regime costs or single-compile
# verdict, or a gate-case row without its blocked/mean_improved verdicts,
# measured nothing the regime engine promises.


def check_regime_row(row: dict, where: str, problems: list) -> None:
    """One row's regime contract (no-op for rows of other metrics)."""
    if not isinstance(row, dict):
        return
    metric = row.get("metric")
    if not isinstance(metric, str):
        return
    if metric.startswith("regime_generalization"):
        _require_numeric(
            row,
            ("train_cost_eur", "held_out_cost_eur", "generalization_gap"),
            where, problems, "regime_generalization",
        )
        _require_bool(
            row, ("held_out", "single_compile"),
            where, problems, "regime_generalization",
        )
        for key in ("train_regimes", "held_out_regimes"):
            v = row.get(key)
            if not isinstance(v, list) or not v or not all(
                isinstance(r, str) for r in v
            ):
                problems.append(
                    f"{where}: regime_generalization row needs a non-empty "
                    f"string list {key!r}"
                )
        prc = row.get("per_regime_cost")
        if not isinstance(prc, dict) or not prc or not all(
            isinstance(k, str)
            and isinstance(v, (int, float))
            and not isinstance(v, bool)
            for k, v in prc.items()
        ):
            problems.append(
                f"{where}: regime_generalization row needs per_regime_cost "
                "as a non-empty {regime: numeric cost} object"
            )
    elif metric == "regime_eval":
        _require_numeric(row, ("cost_eur",), where, problems, "regime_eval")
        _require_bool(row, ("held_out",), where, problems, "regime_eval")
        if not isinstance(row.get("regime"), str) or not row.get("regime"):
            problems.append(
                f"{where}: regime_eval row missing string 'regime'"
            )
    elif metric == "regime_gate_case":
        _require_bool(
            row, ("blocked", "mean_improved"),
            where, problems, "regime_gate_case",
        )
        if not isinstance(row.get("regressed_regime"), str):
            problems.append(
                f"{where}: regime_gate_case row missing string "
                "'regressed_regime'"
            )


def check_regime_jsonl(path: str, problems: list) -> None:
    """REGIME_*.jsonl: metric rows + the capture contract — at least one
    per-regime eval row, and the ``regime_generalization`` headline as the
    LAST row (the driver parses the final stdout line)."""
    where = os.path.relpath(path)
    check_metric_jsonl(path, problems)
    rows = [row for row, _ in _iter_jsonl_rows(path, [])]
    evals = [
        r for r in rows
        if isinstance(r, dict) and r.get("metric") == "regime_eval"
    ]
    if not evals:
        problems.append(f"{where}: no regime_eval row (per-regime table)")
    headlines = [
        (i, r) for i, r in enumerate(rows)
        if isinstance(r, dict)
        and isinstance(r.get("metric"), str)
        and r["metric"].startswith("regime_generalization")
    ]
    if not headlines:
        problems.append(f"{where}: no regime_generalization headline row")
    elif headlines[-1][0] != len(rows) - 1:
        problems.append(
            f"{where}: regime_generalization headline must be the last row"
        )


# Numeric stats every serve_bench_network headline row must carry — the
# wire-level SLO contract of serve/loadgen.py:serve_bench_network.
GATEWAY_HEADLINE_KEYS = (
    "p50_ms", "p95_ms", "p99_ms", "throughput_rps", "shed_rate",
)


def check_gateway_jsonl(path: str, problems: list) -> None:
    """SERVE_GATEWAY_*.jsonl: metric rows + the network-headline contract."""
    where = os.path.relpath(path)
    check_metric_jsonl(path, problems)
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return  # already reported by check_metric_jsonl
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue  # already reported
        if not isinstance(row, dict):
            continue
        if row.get("metric") != "serve_bench_network":
            continue
        for key in GATEWAY_HEADLINE_KEYS:
            v = row.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                problems.append(
                    f"{where}:{i + 1}: serve_bench_network headline "
                    f"missing numeric {key!r}"
                )


# Continuous-batching captures (serve-bench --continuous-compare /
# bench_serve_continuous, ISSUE 14): the headline must carry both arms'
# percentiles, the occupancy/slot-wait distributions and the
# continuous-vs-microbatch verdicts — a row without them measured nothing
# the slot-level batcher promises.
SERVE_CB_HEADLINE_NUMERIC = (
    "p50_ms", "p95_ms", "p99_ms",
    "micro_p50_ms", "micro_p95_ms", "micro_p99_ms",
    "vs_microbatch", "occupancy_mean", "occupancy_p95",
    "slot_wait_p50_ms", "slot_wait_p95_ms", "throughput_rps",
)


def check_serve_cb_jsonl(path: str, problems: list) -> None:
    """SERVE_CB_*.jsonl: metric rows + the ``serve_continuous`` headline
    contract (numeric percentile/occupancy stats, boolean
    ``bit_exact_stateless``, a ``burst_config`` object, headline LAST)."""
    where = os.path.relpath(path)
    check_metric_jsonl(path, problems)
    rows = [
        (row, rw) for row, rw in _iter_jsonl_rows(path, [])
        if isinstance(row, dict)
    ]
    headlines = [
        (i, row, rw) for i, (row, rw) in enumerate(rows)
        if row.get("metric") == "serve_continuous"
    ]
    if not headlines:
        problems.append(f"{where}: no serve_continuous headline row")
        return
    if headlines[-1][0] != len(rows) - 1:
        problems.append(
            f"{where}: serve_continuous headline must be the last row"
        )
    for _i, row, rw in headlines:
        _require_numeric(
            row, SERVE_CB_HEADLINE_NUMERIC, rw, problems, "serve_continuous"
        )
        _require_bool(
            row, ("bit_exact_stateless",), rw, problems, "serve_continuous"
        )
        bc = row.get("burst_config")
        if not isinstance(bc, dict) or "mode" not in bc:
            problems.append(
                f"{rw}: serve_continuous headline needs a burst_config "
                "object with a 'mode'"
            )


# Numeric keys every serve_bench_scale headline must carry — the scale
# tier's claims (scale/bench.py, ISSUE 17): sustained rps/replica, tail
# latency and warehouse ingest lag at a million-household population.
SCALE_HEADLINE_NUMERIC = (
    "households", "n_requests", "rate_hz",
    "rps_per_replica", "offered_rps_per_replica",
    "p50_ms", "p99_ms", "ingest_lag_ms", "load_spread", "vnodes",
)

SCALE_MIN_HOUSEHOLDS = 1_000_000


def check_scale_jsonl(path: str, problems: list) -> None:
    """SCALE_*.jsonl: metric rows + the ``serve_bench_scale`` headline
    contract (numeric rps/p99/ingest-lag, >= 1e6 households, a
    ``scale_scaling`` row sweeping >= 3 replica counts, headline LAST)."""
    where = os.path.relpath(path)
    check_metric_jsonl(path, problems)
    rows = [
        (row, rw) for row, rw in _iter_jsonl_rows(path, [])
        if isinstance(row, dict)
    ]
    headlines = [
        (i, row, rw) for i, (row, rw) in enumerate(rows)
        if row.get("metric") == "serve_bench_scale"
    ]
    if not headlines:
        problems.append(f"{where}: no serve_bench_scale headline row")
        return
    if headlines[-1][0] != len(rows) - 1:
        problems.append(
            f"{where}: serve_bench_scale headline must be the last row"
        )
    for _i, row, rw in headlines:
        _require_numeric(
            row, SCALE_HEADLINE_NUMERIC, rw, problems, "serve_bench_scale"
        )
        _require_bool(row, ("saturated",), rw, problems, "serve_bench_scale")
        households = row.get("households")
        if (
            isinstance(households, (int, float))
            and not isinstance(households, bool)
            and households < SCALE_MIN_HOUSEHOLDS
        ):
            problems.append(
                f"{rw}: scale headline covers {households} households — a "
                f"committed capture must cover >= {SCALE_MIN_HOUSEHOLDS}"
            )
    scaling = [
        (row, rw) for row, rw in rows
        if row.get("metric") == "scale_scaling"
    ]
    if not scaling:
        problems.append(
            f"{where}: no scale_scaling row (replica-scaling sweep)"
        )
    for row, rw in scaling:
        counts = row.get("replica_counts")
        if not isinstance(counts, list) or len(counts) < 3:
            problems.append(
                f"{rw}: scale_scaling needs >= 3 replica counts, got "
                f"{counts!r}"
            )
        _require_numeric(
            row, ("max_load_spread",), rw, problems, "scale_scaling"
        )
        by_count = row.get("load_spread_by_count")
        if not isinstance(by_count, dict) or not all(
            isinstance(v, (int, float)) and not isinstance(v, bool)
            for v in by_count.values()
        ):
            problems.append(
                f"{rw}: scale_scaling needs a numeric-valued "
                "load_spread_by_count object"
            )


# Numeric SLO keys every serve_bench_fleet headline row must carry — the
# chaos-run contract of serve/router.py:serve_bench_fleet. Availability,
# failover count and retry rate are the point of a fleet capture: a row
# without them measured nothing the fleet tier promises.
FLEET_HEADLINE_KEYS = (
    "p50_ms", "p95_ms", "p99_ms", "throughput_rps",
    "availability", "failover_count", "retry_rate", "shed_rate",
)


def check_fleet_jsonl(path: str, problems: list) -> None:
    """FLEET_*.jsonl: metric rows + the fleet-headline SLO contract."""
    where = os.path.relpath(path)
    check_metric_jsonl(path, problems)
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return  # already reported by check_metric_jsonl
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue  # already reported
        if not isinstance(row, dict):
            continue
        if row.get("metric") != "serve_bench_fleet":
            continue
        for key in FLEET_HEADLINE_KEYS:
            v = row.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                problems.append(
                    f"{where}:{i + 1}: serve_bench_fleet headline "
                    f"missing numeric {key!r}"
                )
        availability = row.get("availability")
        if (
            isinstance(availability, (int, float))
            and not isinstance(availability, bool)
            and not 0.0 <= availability <= 1.0
        ):
            problems.append(
                f"{where}:{i + 1}: availability {availability} outside "
                "[0, 1]"
            )


# Process-fleet captures (serve-bench --fleet --process, TLS + auth on)
# additionally promise the wire/trust SLOs: reconnect counts on the
# persistent mux wire, the auth-shed rate, and a bit-exactness verdict
# measured THROUGH real process boundaries.
FLEET_PROC_HEADLINE_KEYS = FLEET_HEADLINE_KEYS + (
    "reconnects", "auth_shed_rate",
)


def check_fleet_proc_jsonl(path: str, problems: list) -> None:
    """FLEET_PROC_*.jsonl: the fleet contract + wire/trust headline keys
    + a boolean bit_exact verdict."""
    where = os.path.relpath(path)
    check_fleet_jsonl(path, problems)
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return  # already reported
    saw_headline = False
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue  # already reported
        if not isinstance(row, dict) or row.get("metric") != "serve_bench_fleet":
            continue
        saw_headline = True
        for key in ("reconnects", "auth_shed_rate"):
            v = row.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                problems.append(
                    f"{where}:{i + 1}: serve_bench_fleet headline "
                    f"missing numeric {key!r} (process-fleet contract)"
                )
        if not isinstance(row.get("bit_exact"), bool):
            problems.append(
                f"{where}:{i + 1}: serve_bench_fleet headline missing "
                "boolean 'bit_exact' (process-fleet contract)"
            )
    if not saw_headline:
        problems.append(
            f"{where}: no serve_bench_fleet headline row"
        )


# Distributed-trace captures (serve-bench --fleet --trace, ISSUE 16):
# the committed tree must actually be the cross-process failover tree the
# capture promises — complete (every parent id resolves), spanning >= 3
# processes, with an ADDITIVE critical path (segments sum to the root's
# measured wall time within 5%) and the serve_bench_trace headline LAST,
# so downstream tail-parsers read the decomposition, not a mid-run row.
TRACE_SEGMENT_KEYS = (
    "wire_ms", "queue_wait_ms", "padding_ms", "execute_ms", "retry_ms",
)


def check_trace_jsonl(path: str, problems: list) -> None:
    where = os.path.relpath(path)
    rows = list(_iter_jsonl_rows(path, problems))
    trees = [(r, w) for r, w in rows
             if isinstance(r, dict) and r.get("kind") == "trace_tree"]
    headlines = [(r, w) for r, w in rows
                 if isinstance(r, dict)
                 and r.get("metric") == "serve_bench_trace"]
    for row, w in rows:
        if isinstance(row, dict) and row.get("kind") == "trace_tree":
            continue  # span rows are not metric rows
        check_metric_row(row, w, problems)
    if not trees:
        problems.append(f"{where}: no trace_tree row")
    if not headlines:
        problems.append(f"{where}: no serve_bench_trace headline row")
        return
    headline, hw = headlines[-1]
    if rows and rows[-1][0] is not headline:
        problems.append(
            f"{where}: serve_bench_trace headline must be the LAST row"
        )
    _require_bool(headline, ("tree_complete", "failover"), hw, problems,
                  "serve_bench_trace")
    if headline.get("tree_complete") is False:
        problems.append(f"{hw}: committed trace tree is incomplete")
    n_proc = headline.get("n_processes")
    if not isinstance(n_proc, (int, float)) or isinstance(n_proc, bool):
        problems.append(
            f"{hw}: serve_bench_trace missing numeric 'n_processes'"
        )
    elif n_proc < 3:
        problems.append(
            f"{hw}: trace spans {n_proc} process(es); the capture "
            "contract is >= 3 (router + both failover replicas)"
        )
    cp = headline.get("critical_path")
    if not isinstance(cp, dict):
        problems.append(
            f"{hw}: serve_bench_trace missing 'critical_path' object"
        )
    else:
        _require_numeric(cp, TRACE_SEGMENT_KEYS + ("total_ms",),
                         hw, problems, "critical_path")
        total = cp.get("total_ms")
        segments = [cp.get(k) for k in TRACE_SEGMENT_KEYS]
        if (
            isinstance(total, (int, float)) and not isinstance(total, bool)
            and total > 0
            and all(isinstance(s, (int, float)) and not isinstance(s, bool)
                    for s in segments)
        ):
            drift = abs(sum(segments) - total) / total
            if drift > 0.05:
                problems.append(
                    f"{hw}: critical-path segments sum to "
                    f"{sum(segments):.3f} ms vs total {total:.3f} ms "
                    f"({drift:.1%} off; contract is 5%)"
                )
    for tree, tw in trees:
        spans = tree.get("spans")
        if not isinstance(spans, list) or not spans:
            problems.append(f"{tw}: trace_tree row has no spans")
            continue
        ids = {s.get("span_id") for s in spans if isinstance(s, dict)}
        for s in spans:
            if not isinstance(s, dict) or not s.get("span_id"):
                problems.append(f"{tw}: span without span_id")
                continue
            parent = s.get("parent_span_id")
            if parent is not None and parent not in ids:
                problems.append(
                    f"{tw}: span {s['span_id']} parent {parent} not in "
                    "the tree (orphan — the stitch is incomplete)"
                )
    tree_ids = {t.get("trace_id") for t, _ in trees}
    if headline.get("trace_id") not in tree_ids:
        problems.append(
            f"{hw}: headline trace_id has no matching trace_tree row"
        )


# Private-key refusal: committed captures may carry certs for provenance,
# but key MATERIAL in the repo is a credential leak no matter how "test"
# it looks. artifacts/tls/ is the designated LOCAL scratch
# (serve/auth.py ensure_test_certs writes there; .gitignore'd) — keys are
# tolerated there and NOWHERE else. Suffix-targeted so the sweep stays
# cheap on large checkouts.
_KEY_SUFFIXES = (".pem", ".key", ".crt", ".cer")
_KEY_MARKER = "PRIVATE KEY"
_KEY_SCRATCH_DIRS = (os.path.join("artifacts", "tls"),)


def check_no_private_keys(repo_root: str, problems: list) -> None:
    for dirpath, dirnames, filenames in os.walk(repo_root):
        rel_dir = os.path.relpath(dirpath, repo_root)

        def _keep(d: str) -> bool:
            if d.startswith(".") or d == "__pycache__":
                return False
            rel = os.path.normpath(os.path.join(rel_dir, d))
            return rel not in _KEY_SCRATCH_DIRS

        dirnames[:] = [d for d in dirnames if _keep(d)]
        for name in filenames:
            if not name.lower().endswith(_KEY_SUFFIXES):
                continue
            path = os.path.join(dirpath, name)
            try:
                with open(path, errors="replace") as f:
                    head = f.read(1 << 16)
            except OSError:
                continue
            if _KEY_MARKER in head:
                problems.append(
                    f"{os.path.relpath(path, repo_root)}: contains "
                    f"{_KEY_MARKER!r} material — private keys must never "
                    "be committed (generate test certs into artifacts/tls/"
                    ", which is gitignored)"
                )


# Numeric keys every train_supervised headline row must carry — the
# crash-resume contract of train/resilience.py:supervise + `train
# --supervise`. kill/resume/rollback counts plus the bit_exact boolean are
# the point of a resilience capture: a headline without them measured
# nothing the training tier promises.
RESILIENCE_HEADLINE_KEYS = ("kills", "resumes", "rollbacks", "final_episode")


def check_resilience_jsonl(path: str, problems: list) -> None:
    """RESILIENCE_*.jsonl: metric rows + the supervised-run contract."""
    where = os.path.relpath(path)
    check_metric_jsonl(path, problems)
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return  # already reported by check_metric_jsonl
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue  # already reported
        if not isinstance(row, dict):
            continue
        metric = row.get("metric")
        if metric == "train_supervised":
            for key in RESILIENCE_HEADLINE_KEYS:
                v = row.get(key)
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    problems.append(
                        f"{where}:{i + 1}: train_supervised headline "
                        f"missing numeric {key!r}"
                    )
            if not isinstance(row.get("bit_exact"), bool):
                problems.append(
                    f"{where}:{i + 1}: train_supervised headline missing "
                    "boolean 'bit_exact' (committed captures must run "
                    "--verify-uninterrupted)"
                )
        elif metric == "train_rollback_total":
            if not isinstance(row.get("converged"), bool):
                problems.append(
                    f"{where}:{i + 1}: train_rollback_total row missing "
                    "boolean 'converged'"
                )


# Promotion captures (`promote --inject`, serve/promotion.py): every
# promotion_case row is a gate/canary decision and must carry the safety
# contract — the gate verdict string, the canary stage list, availability
# in [0, 1] and the rolled_back/promoted booleans. A case row without
# them proved nothing about deployment safety.
def check_promotion_jsonl(path: str, problems: list) -> None:
    """PROMOTION_*.jsonl: metric rows + the promotion-case contract."""
    where = os.path.relpath(path)
    check_metric_jsonl(path, problems)
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return  # already reported by check_metric_jsonl
    saw_case = False
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue  # already reported
        if not isinstance(row, dict) or row.get("metric") != "promotion_case":
            continue
        saw_case = True
        if not isinstance(row.get("gate_verdict"), str):
            problems.append(
                f"{where}:{i + 1}: promotion_case missing string "
                "'gate_verdict'"
            )
        if not isinstance(row.get("canary_stages"), list):
            problems.append(
                f"{where}:{i + 1}: promotion_case missing list "
                "'canary_stages'"
            )
        availability = row.get("availability")
        if not isinstance(availability, (int, float)) or isinstance(
            availability, bool
        ):
            problems.append(
                f"{where}:{i + 1}: promotion_case missing numeric "
                "'availability'"
            )
        elif not 0.0 <= availability <= 1.0:
            problems.append(
                f"{where}:{i + 1}: availability {availability} outside "
                "[0, 1]"
            )
        for key in ("rolled_back", "promoted"):
            if not isinstance(row.get(key), bool):
                problems.append(
                    f"{where}:{i + 1}: promotion_case missing boolean "
                    f"{key!r}"
                )
    if not saw_case:
        problems.append(f"{where}: no promotion_case row")


def check_autopilot_jsonl(path: str, problems: list) -> None:
    """AUTOPILOT_*.jsonl: metric rows + the unattended-cycle contract —
    numeric cycles/promotions/blocked/rollbacks, availability in [0, 1],
    boolean all_safe on the ``autopilot_bench`` headline (which must be
    the LAST row), plus per-cycle ``autopilot_cycle`` rows."""
    where = os.path.relpath(path)
    check_metric_jsonl(path, problems)
    try:
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError:
        return  # already reported by check_metric_jsonl
    rows = []
    for i, line in enumerate(lines):
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue  # already reported
        if isinstance(row, dict):
            rows.append((i + 1, row))
    cycles = [r for _, r in rows if r.get("metric") == "autopilot_cycle"]
    if not cycles:
        problems.append(f"{where}: no autopilot_cycle row")
    headlines = [
        (n, r) for n, r in rows if r.get("metric") == "autopilot_bench"
    ]
    if not headlines:
        problems.append(f"{where}: no autopilot_bench headline row")
        return
    n, head = headlines[-1]
    if rows and rows[-1][1] is not head:
        problems.append(
            f"{where}: autopilot_bench headline must be the last row"
        )
    for key in ("cycles", "promotions", "blocked", "rollbacks",
                "bad_promotions"):
        v = head.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            problems.append(
                f"{where}:{n}: autopilot_bench missing numeric {key!r}"
            )
    availability = head.get("availability")
    if not isinstance(availability, (int, float)) or isinstance(
        availability, bool
    ):
        problems.append(
            f"{where}:{n}: autopilot_bench missing numeric 'availability'"
        )
    elif not 0.0 <= availability <= 1.0:
        problems.append(
            f"{where}:{n}: availability {availability} outside [0, 1]"
        )
    if not isinstance(head.get("all_safe"), bool):
        problems.append(
            f"{where}:{n}: autopilot_bench missing boolean 'all_safe'"
        )
    for i, row in enumerate(cycles):
        for key in ("cycle",):
            if not isinstance(row.get(key), (int, float)):
                problems.append(
                    f"{where}: autopilot_cycle row {i} missing numeric "
                    f"{key!r}"
                )
        for key in ("promoted", "blocked_at_gate", "rolled_back",
                    "outcome_ok"):
            if not isinstance(row.get(key), bool):
                problems.append(
                    f"{where}: autopilot_cycle row {i} missing boolean "
                    f"{key!r}"
                )


_JOURNAL_PHASES = (
    "idle", "exporting", "retraining", "gating", "canarying",
    "promoted", "aborted",
)


def check_cycle_journal(path: str, problems: list) -> None:
    """Validate one autopilot cycle journal (serve/autopilot.py): kind +
    format_version, a digest that VERIFIES over the canonical state
    payload, a known phase, and the safety counters. The digest check is
    the whole point — a committed journal that does not verify is
    exactly the torn write the atomic-rename contract exists to
    prevent."""
    import hashlib

    where = os.path.relpath(path)
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError) as err:
        problems.append(f"{where}: unreadable journal ({err})")
        return
    if record.get("kind") != "autopilot_journal":
        problems.append(f"{where}: kind != 'autopilot_journal'")
        return
    if not isinstance(record.get("format_version"), int):
        problems.append(f"{where}: missing integer format_version")
    state = record.get("state")
    if not isinstance(state, dict):
        problems.append(f"{where}: missing state object")
        return
    payload = json.dumps(state, sort_keys=True, separators=(",", ":"))
    want = f"sha256:{hashlib.sha256(payload.encode()).hexdigest()}"
    if record.get("digest") != want:
        problems.append(f"{where}: journal digest does not verify")
    if state.get("phase") not in _JOURNAL_PHASES:
        problems.append(f"{where}: unknown phase {state.get('phase')!r}")
    for key in ("cycle", "promotions", "blocked", "rollbacks",
                "bad_promotions"):
        if not isinstance(state.get(key), (int, float)) or isinstance(
            state.get(key), bool
        ):
            problems.append(f"{where}: state missing numeric {key!r}")
    if not isinstance(state.get("lineage"), list):
        problems.append(f"{where}: state missing list 'lineage'")


# Checkpoint integrity manifests (train/checkpoint.py save layout):
# models_<impl>/<setting>/ep_<episode>/p2p_manifest.json.
CHECKPOINT_MANIFEST_GLOBS = (
    os.path.join("models*", "models_*", "*", "ep_*", "p2p_manifest.json"),
    os.path.join("models_*", "*", "ep_*", "p2p_manifest.json"),
    os.path.join("artifacts", "models_*", "*", "ep_*", "p2p_manifest.json"),
)


def check_checkpoint_manifest(path: str, problems: list) -> None:
    """Validate one checkpoint step's p2p_manifest.json (the atomic-save
    integrity record of train/checkpoint.py). Structure only — the content
    digest itself is verified by the restore path, which can parse the
    Orbax payload; this stdlib checker enforces the manifest contract."""
    where = os.path.relpath(path)
    try:
        with open(path) as f:
            m = json.load(f)
    except (OSError, json.JSONDecodeError) as err:
        problems.append(f"{where}: unreadable ({err})")
        return
    if not isinstance(m, dict):
        problems.append(f"{where}: not an object")
        return
    if m.get("kind") != "checkpoint_manifest":
        problems.append(
            f"{where}: kind is {m.get('kind')!r}, expected "
            "'checkpoint_manifest'"
        )
    for key, typ in (("format_version", int), ("episode", int)):
        if not isinstance(m.get(key), typ) or isinstance(m.get(key), bool):
            problems.append(f"{where}: missing integer {key!r}")
    digest = m.get("digest")
    if not (isinstance(digest, str) and digest.startswith("sha256:")):
        problems.append(f"{where}: 'digest' is not a sha256:<hex> string")
    tree = m.get("tree")
    if not isinstance(tree, dict) or not tree:
        problems.append(f"{where}: 'tree' missing or empty")
    else:
        for leaf, spec in tree.items():
            if (
                not isinstance(spec, dict)
                or not isinstance(spec.get("shape"), list)
                or not isinstance(spec.get("dtype"), str)
            ):
                problems.append(
                    f"{where}: tree leaf {leaf!r} missing shape/dtype"
                )
                break
    keys = m.get("payload_keys")
    if not isinstance(keys, list) or "pol_state" not in keys:
        problems.append(
            f"{where}: payload_keys missing or lacks 'pol_state'"
        )
    # The step directory must hold more than the manifest (a manifest next
    # to zero payload files is a stripped/partial step).
    step_dir = os.path.dirname(path)
    payload_entries = [
        e for e in os.listdir(step_dir) if e != os.path.basename(path)
    ]
    if not payload_entries:
        problems.append(f"{where}: step directory has no payload files")


def check_gateway_stats(path: str, problems: list) -> None:
    """GATEWAY_STATS_*.json: one /stats snapshot (serve/gateway.py)."""
    where = os.path.relpath(path)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as err:
        problems.append(f"{where}: unreadable ({err})")
        return
    if not isinstance(doc, dict):
        problems.append(f"{where}: top level is not an object")
        return
    if doc.get("kind") != "gateway_stats":
        problems.append(
            f"{where}: kind is {doc.get('kind')!r}, expected 'gateway_stats'"
        )
    for key in ("created", "default"):
        if not isinstance(doc.get(key), str):
            problems.append(f"{where}: missing string {key!r}")
    for key in ("gateway", "admission", "bundles"):
        if not isinstance(doc.get(key), dict):
            problems.append(f"{where}: missing object {key!r}")
    bundles = doc.get("bundles")
    if isinstance(bundles, dict):
        if not bundles:
            problems.append(f"{where}: 'bundles' is empty")
        for h, b in bundles.items():
            if not isinstance(b, dict):
                problems.append(f"{where}: bundle {h!r} is not an object")
                continue
            for key in ("requests", "batches", "queue_depth"):
                if not isinstance(b.get(key), (int, float)) or isinstance(
                    b.get(key), bool
                ):
                    problems.append(
                        f"{where}: bundle {h!r} missing numeric {key!r}"
                    )
        default = doc.get("default")
        if isinstance(default, str) and default not in bundles:
            problems.append(
                f"{where}: default {default!r} not among bundles "
                f"{sorted(bundles)}"
            )
    if isinstance(doc.get("admission"), dict) and not isinstance(
        doc["admission"].get("shed_total"), (int, float)
    ):
        problems.append(f"{where}: admission missing numeric 'shed_total'")


BUNDLE_IMPLEMENTATIONS = ("tabular", "dqn", "ddpg")
BUNDLE_MANIFEST_KEYS = {
    "format_version": int,
    "implementation": str,
    "created": str,
    "n_agents": int,
    "dtype": str,
    "params_file": str,
    "obs_spec": dict,
    "action_spec": dict,
    "model": dict,
}


def check_bundle_dir(bundle_dir: str, problems: list) -> None:
    """Validate one policy-bundle directory (serve/export.py layout)."""
    where = os.path.relpath(bundle_dir)
    mpath = os.path.join(bundle_dir, "manifest.json")
    if not os.path.exists(mpath):
        problems.append(f"{where}: missing manifest.json")
        return
    try:
        with open(mpath) as f:
            m = json.load(f)
    except (OSError, json.JSONDecodeError) as err:
        problems.append(f"{where}/manifest.json: unreadable ({err})")
        return
    if not isinstance(m, dict):
        problems.append(f"{where}/manifest.json: not an object")
        return
    if m.get("kind") != "policy_bundle":
        problems.append(
            f"{where}/manifest.json: kind is {m.get('kind')!r}, "
            "expected 'policy_bundle'"
        )
    for key, typ in BUNDLE_MANIFEST_KEYS.items():
        if key not in m:
            problems.append(f"{where}/manifest.json: missing key {key!r}")
        elif not isinstance(m[key], typ) or isinstance(m[key], bool):
            problems.append(
                f"{where}/manifest.json: key {key!r} has type "
                f"{type(m[key]).__name__}"
            )
    if m.get("implementation") not in BUNDLE_IMPLEMENTATIONS:
        problems.append(
            f"{where}/manifest.json: unknown implementation "
            f"{m.get('implementation')!r}"
        )
    if isinstance(m.get("obs_spec"), dict) and m["obs_spec"].get("dim") != 4:
        problems.append(
            f"{where}/manifest.json: obs_spec.dim is "
            f"{m['obs_spec'].get('dim')!r}, expected 4"
        )
    pfile = m.get("params_file")
    if isinstance(pfile, str) and not os.path.exists(
        os.path.join(bundle_dir, pfile)
    ):
        problems.append(f"{where}: params_file {pfile!r} does not exist")
    if isinstance(m.get("dtype"), str) and m["dtype"] not in QUANT_DTYPES:
        problems.append(
            f"{where}/manifest.json: dtype {m['dtype']!r} not in "
            f"{QUANT_DTYPES}"
        )
    if m.get("dtype") == "int8":
        # The quantization contract (serve/export.py): per-leaf scales and
        # the measured error bound must be recorded — an int8 bundle
        # without them cannot be dequantized or gate-checked.
        quant = m.get("quant")
        if not isinstance(quant, dict):
            problems.append(
                f"{where}/manifest.json: int8 bundle missing 'quant' object"
            )
        else:
            scales = quant.get("scales")
            if not isinstance(scales, dict) or not scales:
                problems.append(
                    f"{where}/manifest.json: int8 quant.scales missing/empty"
                )
            elif not all(
                isinstance(s, (int, float)) and not isinstance(s, bool)
                and s > 0
                for s in scales.values()
            ):
                problems.append(
                    f"{where}/manifest.json: int8 quant.scales must be "
                    "positive numbers"
                )
            eb = quant.get("error_bound")
            if not isinstance(eb, dict) or "kind" not in eb:
                problems.append(
                    f"{where}/manifest.json: int8 quant.error_bound "
                    "missing (the recorded contract measurement)"
                )
            elif eb.get("kind") == "continuous_ulp" and not isinstance(
                eb.get("max_ulp"), (int, float)
            ):
                problems.append(
                    f"{where}/manifest.json: continuous int8 error_bound "
                    "missing numeric max_ulp"
                )
            elif eb.get("kind") == "discrete_argmax" and eb.get(
                "bit_exact_argmax"
            ) is not True:
                problems.append(
                    f"{where}/manifest.json: discrete int8 bundle must "
                    "certify bit_exact_argmax=true"
                )


def check_run_dir(run_dir: str, problems: list) -> None:
    where = os.path.relpath(run_dir)
    mpath = os.path.join(run_dir, "manifest.json")
    if not os.path.exists(mpath):
        problems.append(f"{where}: missing manifest.json")
    else:
        try:
            with open(mpath) as f:
                m = json.load(f)
            if not isinstance(m, dict):
                problems.append(f"{where}/manifest.json: not an object")
            else:
                for key in ("run_id", "created"):
                    if key not in m:
                        problems.append(f"{where}/manifest.json: missing {key!r}")
        except (OSError, json.JSONDecodeError) as err:
            problems.append(f"{where}/manifest.json: unreadable ({err})")
    jpath = os.path.join(run_dir, "metrics.jsonl")
    if os.path.exists(jpath):
        with open(jpath) as f:
            for i, line in enumerate(f):
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    problems.append(
                        f"{where}/metrics.jsonl:{i + 1}: not valid JSON"
                    )
                    continue
                if not isinstance(rec, dict):
                    problems.append(
                        f"{where}/metrics.jsonl:{i + 1}: not an object"
                    )
                    continue
                if not isinstance(rec.get("ts"), (int, float)):
                    problems.append(
                        f"{where}/metrics.jsonl:{i + 1}: missing numeric 'ts'"
                    )
                if not isinstance(rec.get("kind"), str):
                    problems.append(
                        f"{where}/metrics.jsonl:{i + 1}: missing string 'kind'"
                    )
    spath = os.path.join(run_dir, "summary.json")
    if os.path.exists(spath):
        try:
            with open(spath) as f:
                s = json.load(f)
            for key in ("counters", "gauges", "histograms", "spans"):
                if not isinstance(s.get(key), dict):
                    problems.append(
                        f"{where}/summary.json: {key!r} missing or not an object"
                    )
        except (OSError, json.JSONDecodeError) as err:
            problems.append(f"{where}/summary.json: unreadable ({err})")
    tpath = os.path.join(run_dir, "trace.json")
    if os.path.exists(tpath):
        try:
            with open(tpath) as f:
                t = json.load(f)
            if not isinstance(t, dict) or not isinstance(
                t.get("traceEvents"), list
            ):
                problems.append(
                    f"{where}/trace.json: not a Chrome trace object "
                    "(traceEvents list)"
                )
        except (OSError, json.JSONDecodeError) as err:
            problems.append(f"{where}/trace.json: unreadable ({err})")


# Keep in sync with p2pmicrogrid_tpu/data/results.py:TELEMETRY_SCHEMA_VERSION
# (hardcoded so this tool stays stdlib-only and runs without the package).
# v1 = warehouse tables; v2 added export_leases (the export/retention
# handshake); v3 added trace_spans (distributed-trace trees). An older DB
# is still valid — it migrates in place on its next write
# (data/results.ensure_telemetry_schema) — so all three verify.
ACCEPTED_TELEMETRY_SCHEMA_VERSIONS = (1, 2, 3)

_TELEMETRY_TABLES = ("telemetry_runs", "telemetry_points", "telemetry_spans")

# Where results DBs live (shared by check_all and main's summary count).
RESULTS_DB_GLOBS = (
    "*.db", "*.sqlite",
    os.path.join("artifacts", "*.db"), os.path.join("artifacts", "*.sqlite"),
)


def check_results_db(path: str, problems: list) -> None:
    """Validate one results DB's telemetry warehouse tables."""
    import sqlite3

    where = os.path.relpath(path)
    try:
        con = sqlite3.connect(f"file:{path}?mode=ro", uri=True)
    except sqlite3.Error as err:
        problems.append(f"{where}: unreadable ({err})")
        return
    try:
        try:
            tables = {
                row[0]
                for row in con.execute(
                    "SELECT name FROM sqlite_master WHERE type='table'"
                )
            }
        except sqlite3.DatabaseError as err:
            problems.append(f"{where}: not a SQLite database ({err})")
            return
        present = [t for t in _TELEMETRY_TABLES if t in tables]
        if not present:
            return  # pre-warehouse DB: nothing to validate
        missing = [t for t in _TELEMETRY_TABLES if t not in tables]
        if missing:
            problems.append(
                f"{where}: telemetry tables incomplete — has "
                f"{present}, missing {missing}"
            )
            return
        (version,) = con.execute("PRAGMA user_version").fetchone()
        if version not in ACCEPTED_TELEMETRY_SCHEMA_VERSIONS:
            problems.append(
                f"{where}: telemetry schema version {version}, expected "
                f"one of {ACCEPTED_TELEMETRY_SCHEMA_VERSIONS}"
            )
        for table in ("telemetry_points", "telemetry_spans"):
            (orphans,) = con.execute(
                f"SELECT COUNT(*) FROM {table} t WHERE NOT EXISTS "
                "(SELECT 1 FROM telemetry_runs r WHERE r.run_id = t.run_id)"
            ).fetchone()
            if orphans:
                problems.append(
                    f"{where}: {orphans} {table} row(s) reference no "
                    "telemetry_runs row (orphaned run_id)"
                )
        if "eval_runs" in tables:
            (null_hash,) = con.execute(
                "SELECT COUNT(*) FROM eval_runs WHERE config_hash IS NULL"
            ).fetchone()
            if null_hash:
                problems.append(
                    f"{where}: {null_hash} eval_runs row(s) carry no "
                    "config_hash (unjoinable)"
                )
    finally:
        con.close()


def check_all(repo_root: str, strict_tail: bool = False) -> list:
    """All problems found under ``repo_root`` (empty list = clean)."""
    problems: list = []
    for path in sorted(glob.glob(os.path.join(repo_root, "BENCH_*.json"))):
        check_bench_capture(path, problems, strict_tail=strict_tail)
    gateway_jsonl = set(
        glob.glob(os.path.join(repo_root, "artifacts", "SERVE_GATEWAY_*.jsonl"))
    )
    serve_cb_jsonl = set(
        glob.glob(os.path.join(repo_root, "artifacts", "SERVE_CB_*.jsonl"))
    )
    for pattern in ("BENCH_*.jsonl", "SERVE_*.jsonl"):
        for path in sorted(
            glob.glob(os.path.join(repo_root, "artifacts", pattern))
        ):
            if path in gateway_jsonl or path in serve_cb_jsonl:
                # SERVE_GATEWAY_* / SERVE_CB_* match SERVE_* too; their
                # dedicated checks below include the metric-row validation.
                continue
            check_metric_jsonl(path, problems)
    for path in sorted(gateway_jsonl):
        check_gateway_jsonl(path, problems)
    for path in sorted(serve_cb_jsonl):
        check_serve_cb_jsonl(path, problems)
    fleet_proc_jsonl = set(
        glob.glob(os.path.join(repo_root, "artifacts", "FLEET_PROC_*.jsonl"))
    )
    for path in sorted(
        glob.glob(os.path.join(repo_root, "artifacts", "FLEET_*.jsonl"))
    ):
        if path in fleet_proc_jsonl:
            # FLEET_PROC_* matches FLEET_* too; the process check below
            # includes the fleet validation plus the wire/trust keys.
            continue
        check_fleet_jsonl(path, problems)
    for path in sorted(fleet_proc_jsonl):
        check_fleet_proc_jsonl(path, problems)
    for path in sorted(
        glob.glob(os.path.join(repo_root, "artifacts", "SCALE_*.jsonl"))
    ):
        check_scale_jsonl(path, problems)
    for path in sorted(
        glob.glob(os.path.join(repo_root, "artifacts", "TRACE_*.jsonl"))
    ):
        check_trace_jsonl(path, problems)
    check_no_private_keys(repo_root, problems)
    for path in sorted(
        glob.glob(os.path.join(repo_root, "artifacts", "RESILIENCE_*.jsonl"))
    ):
        check_resilience_jsonl(path, problems)
    for path in sorted(
        glob.glob(os.path.join(repo_root, "artifacts", "PROMOTION_*.jsonl"))
    ):
        check_promotion_jsonl(path, problems)
    for path in sorted(
        glob.glob(os.path.join(repo_root, "artifacts", "AUTOPILOT_*.jsonl"))
    ):
        check_autopilot_jsonl(path, problems)
    for path in sorted(
        glob.glob(os.path.join(repo_root, "artifacts", "REGIME_*.jsonl"))
    ):
        check_regime_jsonl(path, problems)
    for pattern in (
        os.path.join("artifacts", "AUTOPILOT_JOURNAL_*.json"),
        os.path.join("artifacts", "autopilot*", "cycle_journal.json"),
    ):
        for path in sorted(glob.glob(os.path.join(repo_root, pattern))):
            check_cycle_journal(path, problems)
    for pattern in CHECKPOINT_MANIFEST_GLOBS:
        for path in sorted(glob.glob(os.path.join(repo_root, pattern))):
            check_checkpoint_manifest(path, problems)
    for path in sorted(
        glob.glob(os.path.join(repo_root, "artifacts", "GATEWAY_STATS_*.json"))
    ):
        check_gateway_stats(path, problems)
    for run_dir in sorted(
        glob.glob(os.path.join(repo_root, "artifacts", "runs", "*"))
    ):
        if os.path.isdir(run_dir):
            check_run_dir(run_dir, problems)
    for root in ("bundles", os.path.join("artifacts", "bundles")):
        for bundle_dir in sorted(glob.glob(os.path.join(repo_root, root, "*"))):
            if os.path.isdir(bundle_dir):
                check_bundle_dir(bundle_dir, problems)
    for pattern in RESULTS_DB_GLOBS:
        for path in sorted(glob.glob(os.path.join(repo_root, pattern))):
            check_results_db(path, problems)
    # Host-sync hygiene rides the same sweep (tools/check_host_sync.py):
    # hot-path modules may not grow un-annotated blocking readbacks. The
    # checker skips roots without the package files, so artifact-only scan
    # roots (tests' tmp dirs) are unaffected.
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "check_host_sync",
            os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "check_host_sync.py",
            ),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        problems.extend(mod.check_host_sync(repo_root))
    except Exception as err:  # noqa: BLE001 — artifact checks still count
        problems.append(f"check_host_sync unavailable: {err}")
    return problems


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--root",
        default=os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."),
        help="repo root to scan (default: the checkout containing this script)",
    )
    parser.add_argument(
        "--strict-tail", action="store_true",
        help="also flag non-JSON noise interleaved into BENCH capture tails "
             "(new captures through the telemetry stdout sink must be clean; "
             "pre-telemetry captures are historical and fail this)",
    )
    args = parser.parse_args(argv)
    root = os.path.abspath(args.root)
    problems = check_all(root, strict_tail=args.strict_tail)
    for p in problems:
        print(p, file=sys.stderr)
    n_bench = len(glob.glob(os.path.join(root, "BENCH_*.json")))
    n_runs = len(glob.glob(os.path.join(root, "artifacts", "runs", "*")))
    n_bundles = len(
        glob.glob(os.path.join(root, "bundles", "*"))
    ) + len(glob.glob(os.path.join(root, "artifacts", "bundles", "*")))
    n_dbs = sum(
        len(glob.glob(os.path.join(root, pat))) for pat in RESULTS_DB_GLOBS
    )
    n_ckpts = sum(
        len(glob.glob(os.path.join(root, pat)))
        for pat in CHECKPOINT_MANIFEST_GLOBS
    )
    print(
        f"checked {n_bench} bench captures, {n_runs} telemetry runs, "
        f"{n_bundles} policy bundles, {n_dbs} results DBs, "
        f"{n_ckpts} checkpoint manifests: {len(problems)} problem(s)"
    )
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
