"""At-scale DQN learning evidence (round-5 VERDICT #5).

Tabular has the 50x256 monotone curve (round 2), DDPG has the full
north-star curves — this closes the set: community-shared DQN trained
through the CHUNKED path (which exercises the per-chunk record-only replay
warmup, the reference's init_buffers at community.py:125-147) at 50 agents
x 2 chunks x 64 = 128 aggregate scenarios, with the greedy held-out
community cost tracked every 10 episodes. Claim: greedy held-out cost
falls below the episode-0 (untrained) cost and stays there.

Usage: ``PYTHONPATH=/root/repo:$PYTHONPATH python tools/learning_dqn.py
[EPISODES] [OUT] [SEED]``
"""

from __future__ import annotations

import json
import sys
import time

import jax
import numpy as np

from p2pmicrogrid_tpu.config import (
    DQNConfig,
    SimConfig,
    TrainConfig,
    default_config,
)
from p2pmicrogrid_tpu.envs import make_ratings
from p2pmicrogrid_tpu.parallel import init_shared_pol_state
from p2pmicrogrid_tpu.parallel.device_gen import device_episode_arrays
from p2pmicrogrid_tpu.parallel.scenarios import (
    make_chunked_episode_runner,
    make_shared_episode_fn,
    train_scenarios_chunked,
)
from p2pmicrogrid_tpu.train import make_policy
from p2pmicrogrid_tpu.train.health import HealthMonitor, make_greedy_eval

A, S_CHUNK, K = 50, 64, 2
EPISODES, EVAL_EVERY, S_EVAL = 200, 10, 8
OUT = "artifacts/LEARNING_dqn_r05.json"
SEED = 0


def summarize(curve) -> dict:
    """Cost AND reward endpoints + basin-transit bookkeeping — cost alone
    would call a don't-heat basin point (cost < 0, reward ~-1400) the best
    of the run; the health surface exists to prevent exactly that read."""
    costs = [p["greedy_cost_eur"] for p in curve]
    rewards = [p["greedy_reward"] for p in curve]
    statuses = [p["status"] for p in curve]
    return {
        "initial_cost": costs[0],
        "final_cost": costs[-1],
        "initial_reward": rewards[0],
        "final_reward": rewards[-1],
        "improved_cost": costs[-1] < costs[0],
        "improved_reward": rewards[-1] > rewards[0],
        "stable_tail": all(c < costs[0] for c in costs[-5:]),
        "basin_evals": statuses.count("basin"),
        "final_status": statuses[-1],
        "note": (
            "min(cost) is NOT the best point when its status is basin — "
            "judge by (cost, reward) jointly"
        ),
    }


def main() -> None:
    global EPISODES, OUT, SEED
    args = sys.argv[1:]
    if len(args) >= 1:
        EPISODES = int(args[0])
    if len(args) >= 2:
        OUT = args[1]
    if len(args) >= 3:
        SEED = int(args[2])
    cfg = default_config(
        sim=SimConfig(n_agents=A, n_scenarios=S_CHUNK),
        train=TrainConfig(implementation="dqn"),
        dqn=DQNConfig(),
    )
    doc = {
        "round": 5,
        "what": (
            f"Greedy held-out community cost while training community-shared "
            f"DQN through the chunked path ({A} agents, {K} chunks x "
            f"{S_CHUNK} = {K * S_CHUNK} scenarios/episode) incl. the "
            "per-chunk record-only replay warmup."
        ),
        "config": {
            "n_agents": A, "chunk_scenarios": S_CHUNK, "chunks": K,
            "episodes": EPISODES, "eval_scenarios": S_EVAL, "seed": SEED,
            "warmup_passes": cfg.dqn.warmup_passes,
            "device": jax.devices()[0].device_kind,
        },
        "curve": [],
    }
    ratings = make_ratings(cfg, np.random.default_rng(42))
    policy = make_policy(cfg)
    params = init_shared_pol_state(cfg, jax.random.PRNGKey(SEED))
    greedy_eval = make_greedy_eval(cfg, policy, ratings, s_eval=S_EVAL)
    monitor = HealthMonitor(cfg.sim.slots_per_day)
    t0 = time.time()

    def record(ep, extra=None):
        c, r = greedy_eval(params, jax.random.PRNGKey(1))
        status = monitor.update(ep, c, r)
        row = {"episode": ep, "greedy_cost_eur": round(float(c), 2),
               "greedy_reward": round(float(r), 1), "status": status,
               "wall_s": round(time.time() - t0, 1)}
        row.update(extra or {})
        doc["curve"].append(row)
        print(row, file=sys.stderr, flush=True)
        with open(OUT, "w") as f:
            json.dump(doc, f, indent=2)

    # Prebuilt programs (one compile, reused across eval blocks), incl. the
    # record-only warmup program the default path would build per call.
    arrays_fn = lambda k: device_episode_arrays(cfg, k, ratings, S_CHUNK)
    episode_fn = make_shared_episode_fn(
        cfg, policy, None, ratings, arrays_fn=arrays_fn, n_scenarios=S_CHUNK
    )
    warmup_fn = make_shared_episode_fn(
        cfg, policy, None, ratings, arrays_fn=arrays_fn,
        n_scenarios=S_CHUNK, record_only=True,
    )
    runner = make_chunked_episode_runner(
        cfg, episode_fn, K, warmup_fn=warmup_fn
    )

    record(0)
    key = (
        jax.random.PRNGKey(7)
        if SEED == 0
        else jax.random.fold_in(jax.random.PRNGKey(7), SEED)
    )
    for start in range(0, EPISODES, EVAL_EVERY):
        params, rewards, _, secs = train_scenarios_chunked(
            cfg, policy, params, ratings, key,
            n_episodes=EVAL_EVERY, n_chunks=K, episode0=start,
            episode_fn=episode_fn, runner=runner,
        )
        record(start + EVAL_EVERY, {
            "train_reward_mean": round(float(np.mean(rewards[-2:])), 1),
            "train_secs": round(secs, 1),
        })
    doc["summary"] = summarize(doc["curve"])
    with open(OUT, "w") as f:
        json.dump(doc, f, indent=2)
    print(f"wrote {OUT}: {doc['summary']}")


if __name__ == "__main__":
    main()
