"""Measured per-kernel decomposition backing the config-4 roofline claim.

Round-2 VERDICT: the "~40% of HBM roofline" statement rested on an analytic
traffic model only. This script MEASURES, on the real chip at config-4 scale,
the per-slot cost of each fused phase of the scenario-batched slot program —
the negotiation matrix kernels (ops/pallas_market.py), the pooled DDPG learn
pass, and the full slot — plus each phase's HBM traffic model, and emits one
JSON document for ``artifacts/``.

Timing protocol (from .claude/skills/verify/SKILL.md): the tunneled TPU has
~85-260 ms of blocked-round-trip overhead and ``block_until_ready`` may
return early, so each phase chains N dependent calls, forces sync with a
scalar pull, divides by N, and takes best-of-3.

Usage: ``PYTHONPATH=/root/repo python tools/roofline.py [S] [A]``
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

HBM_PEAK_GB_S = 820.0  # TPU v5e spec sheet


def _timeit(fn, *args, n: int = 20, repeats: int = 3) -> float:
    """Best-of-``repeats`` seconds per call of jitted ``fn`` chained n deep."""
    out = fn(*args)
    jax.block_until_ready(out)

    def chain():
        res = args
        t0 = time.time()
        for _ in range(n):
            res = fn(*res) if isinstance(res, tuple) else fn(res)
        leaves = jax.tree_util.tree_leaves(res)
        float(leaves[0].sum())  # force a real sync through the tunnel
        return (time.time() - t0) / n

    return min(chain() for _ in range(repeats))


def main(S: int = 64, A: int = 1000) -> dict:
    from p2pmicrogrid_tpu.config import (
        BatteryConfig,
        DDPGConfig,
        SimConfig,
        TrainConfig,
        default_config,
    )
    from p2pmicrogrid_tpu.envs import make_ratings
    from p2pmicrogrid_tpu.models.ddpg import ddpg_learn_batch, ddpg_params_init
    from p2pmicrogrid_tpu.ops.pallas_market import (
        clear_market_fused,
        divide_power_fused_with_mean,
        divide_rank1_fused,
    )
    from p2pmicrogrid_tpu.parallel import (
        init_shared_state,
        make_scenario_traces,
        stack_scenario_arrays,
    )
    from p2pmicrogrid_tpu.parallel.scenarios import make_shared_episode_fn
    from p2pmicrogrid_tpu.train import make_policy

    # market_impl pinned to "matrix": the matrix-phase rows and ablations
    # below decompose the MATRIX slot program; the shipped TPU default since
    # round 4 is the matrix-free factored clearing, measured as its own
    # full-slot rows at the end.
    cfg = default_config(
        sim=SimConfig(n_agents=A, n_scenarios=S, market_impl="matrix"),
        battery=BatteryConfig(enabled=True),
        train=TrainConfig(implementation="ddpg"),
        ddpg=DDPGConfig(buffer_size=256, batch_size=4, share_across_agents=True),
    )
    d = cfg.ddpg
    key = jax.random.PRNGKey(0)
    rows = []

    def add(name, secs, traffic_bytes, note):
        rows.append(
            {
                "phase": name,
                "ms": round(secs * 1e3, 3),
                "hbm_gb_modeled": round(traffic_bytes / 1e9, 3),
                "achieved_gb_per_s": round(traffic_bytes / secs / 1e9, 1),
                "hbm_peak_fraction_v5e": round(
                    traffic_bytes / secs / 1e9 / HBM_PEAK_GB_S, 3
                ),
                "note": note,
            }
        )

    # --- negotiation matrix kernels (per invocation = one round of one slot)
    mat_bytes = S * A * A * 4  # one f32 [S, A, A] matrix in HBM
    vec = jax.random.normal(key, (S, A))
    p2p = jax.random.normal(key, (S, A, A))

    f_rank1 = jax.jit(lambda v: divide_rank1_fused(v, v)[0][:, 0, :])
    secs = _timeit(f_rank1, vec)
    add("divide_rank1_fused", secs, mat_bytes,
        "round-1 proposal split: writes [S,A,A], reads only [S,A] vectors")

    f_div = jax.jit(lambda m: divide_power_fused_with_mean(m, m[:, :, 0])[0])
    secs = _timeit(f_div, p2p)
    add("divide_power_fused_with_mean", secs, 2 * mat_bytes,
        "later rounds: read + write [S,A,A] in one pass (round 2+ only)")

    # Chainable: fold the [S, A] clear result back into an [S, A, A] carry.
    f_clear = jax.jit(lambda m: m + clear_market_fused(m)[0][:, None, :])
    secs = _timeit(f_clear, p2p)
    add("clear_market_fused (+chain add)", secs, 3 * mat_bytes,
        "market clearing reads [S,A,A] in VMEM; the chaining add costs an "
        "extra matrix read+write, included in the traffic model")

    # --- pooled shared-critic learn pass (per slot update)
    pool = d.batch_size * S * A  # pooled rows in the replay slab sample
    # The capped update (DDPGConfig.learn_batch_cap) consumes a contiguous
    # block of `cap` rows of the flattened slab — net passes scale with the
    # EFFECTIVE batch, plus the slab gather + wraparound pad it slices from
    # (10 floats per pooled row, read + write).
    B = pool if d.learn_batch_cap is None else min(pool, d.learn_batch_cap)
    params = ddpg_params_init(d, A, key)
    s_b = jax.random.normal(key, (B, 4))
    a_b = jax.random.normal(key, (B, 1))
    r_b = jax.random.normal(key, (B,))

    @jax.jit
    def learn(s_in):
        out = ddpg_learn_batch(
            d, params.actor, params.critic, params.actor_target,
            params.critic_target, params.actor_opt, params.critic_opt,
            s_in, a_b, r_b, s_in,
        )
        # Chainable: mean residual folded into the carried input.
        return s_in + jnp.mean(out[-1])

    h = max(d.actor_hidden, d.critic_hidden)
    # ~10 activation passes (actor/critic fwd+bwd+target) of [B, h] f32,
    # plus (when capped) the slab gather read + pad write of the pool.
    learn_bytes = 10 * B * h * 4 + (3 * 10 * pool * 4 if B < pool else 0)
    secs = _timeit(learn, s_b)
    add("ddpg_learn_batch (effective batch)", secs, 10 * B * h * 4,
        f"one shared actor-critic update on the [{B}, obs] update batch "
        f"(pool {pool}, cap {d.learn_batch_cap})")

    # --- full compiled episodes: the authoritative rows -----------------
    # Standalone kernel rows above are dispatch-bound UPPER bounds (each
    # isolated dispatch through the tunneled runtime costs ~5 ms); only
    # whole compiled programs measure true device cost. The ablation rows
    # below re-measure the full slot with one phase removed AT COMPILE TIME
    # — the difference attributes the slot's time without any standalone-
    # dispatch distortion (round-4 method; the chain-add in the standalone
    # clear row is a measurement artifact, not real slot traffic).
    import dataclasses

    from p2pmicrogrid_tpu.envs import init_physical
    from p2pmicrogrid_tpu.envs.community import (
        AgentRatings,
        resolve_market_dtype,
        slot_dynamics_batched,
    )

    ratings = make_ratings(cfg, np.random.default_rng(42))
    traces = make_scenario_traces(cfg)
    policy = make_policy(cfg)

    def episode_secs(cfg_v, learn: bool = True) -> float:
        """Best-of-3 seconds per compiled episode of the given config
        variant; ``learn=False`` runs act+market+physics only (the
        environment half of the slot, no parameter update, no replay)."""
        arrays_v = stack_scenario_arrays(cfg_v, traces, ratings)
        if learn:
            ep = make_shared_episode_fn(cfg_v, policy, arrays_v, ratings)
            carry = init_shared_state(cfg_v, key)
        else:
            ratings_j = AgentRatings(*(jnp.asarray(a) for a in ratings))
            xs0 = jax.tree_util.tree_map(
                lambda x: jnp.swapaxes(x, 0, 1), arrays_v
            )
            xs0 = (xs0.time, xs0.t_out, xs0.load_w, xs0.pv_w,
                   xs0.next_time, xs0.next_load_w, xs0.next_pv_w)

            from p2pmicrogrid_tpu.models.ddpg import ddpg_shared_act

            params_eval = ddpg_params_init(d, A, key)

            def act_fn(p, obs_s, prev, round_key, ex):
                frac, q, _ = ddpg_shared_act(
                    d, p, obs_s, jnp.zeros(obs_s.shape[:2]),
                    round_key, explore=False,
                )
                return frac, frac, q, ex

            @jax.jit
            def ep(phys, k):
                def slot(carry, xs_t):
                    phys_s, kk = carry
                    kk, k_act = jax.random.split(kk)
                    phys_s, _, out, _, _ = slot_dynamics_batched(
                        cfg_v, policy, params_eval, phys_s, xs_t, k_act,
                        ratings_j, explore=False, act_fn=act_fn,
                    )
                    return (phys_s, kk), jnp.mean(out.reward, axis=-1)

                (phys, _), r = jax.lax.scan(slot, (phys, k), xs0)
                return phys, r
            carry = jax.vmap(lambda k: init_physical(cfg_v, k))(
                jax.random.split(key, S)
            )
        best = np.inf
        cur = carry
        for i in range(4):  # first iteration = compile warmup
            t0 = time.time()
            if learn:
                cur, _ = ep(cur, key)
                jax.block_until_ready(cur[0])
            else:
                cur, _ = ep(cur, key)
                jax.block_until_ready(cur)
            if i:
                best = min(best, time.time() - t0)
        return best

    slots = cfg.sim.slots_per_day
    mdt = resolve_market_dtype(cfg)
    mat_stored = S * A * A * (2 if mdt == "bfloat16" else 4)
    slot_bytes = 2 * mat_stored + learn_bytes

    full = episode_secs(cfg) / slots
    add(f"full slot ({mdt} market, auto)", full, slot_bytes,
        "whole compiled slot: negotiate + clear + settle + learn + step")

    cfg_f32 = dataclasses.replace(
        cfg, sim=dataclasses.replace(cfg.sim, market_dtype="float32")
    )
    full_f32 = episode_secs(cfg_f32) / slots
    add("full slot (float32 market)", full_f32,
        2 * S * A * A * 4 + learn_bytes,
        "same slot with f32-carried matrices — isolates the bf16 saving")

    env_only = episode_secs(cfg, learn=False) / slots
    add(f"env-only slot ({mdt})", env_only, 2 * mat_stored,
        "act + negotiate + clear + settle + physics, NO learn/replay — "
        "market traffic only")

    cfg_nt = dataclasses.replace(
        cfg, sim=dataclasses.replace(cfg.sim, trading=False)
    )
    no_trade = episode_secs(cfg_nt) / slots
    add("no-trading slot", no_trade, learn_bytes,
        "act + physics + learn, no negotiation matrices at all — "
        "learn-side traffic only")

    cfg_u4 = dataclasses.replace(
        cfg, sim=dataclasses.replace(cfg.sim, slot_unroll=4)
    )
    unroll4 = episode_secs(cfg_u4) / slots
    add(f"full slot (unroll=4, {mdt})", unroll4, slot_bytes,
        "slot scan unrolled x4 — measures scan-iteration overhead headroom")

    # --- the shipped TPU default: matrix-free factored clearing ---------
    cfg_fac = dataclasses.replace(
        cfg, sim=dataclasses.replace(cfg.sim, market_impl="factored")
    )
    fac = episode_secs(cfg_fac) / slots
    add("full slot (factored market, DEFAULT on TPU)", fac, learn_bytes,
        "ops/factored_market.py: no [S, A, A] streams at all — clearing is "
        "O(A^2) fused VPU compute over [S, A] vectors; remaining modeled "
        "HBM is the learn side only")
    fac_env = episode_secs(cfg_fac, learn=False) / slots
    add("env-only slot (factored)", fac_env, 0,
        "act + factored negotiate/clear/settle + physics, no learn/replay "
        "— near-zero modeled HBM")

    market_ms = full - no_trade
    learn_ms = full - env_only
    fixed_ms = env_only + no_trade - full
    hbm_ms = market_ms + learn_ms
    breakdown = {
        "factored_full_ms": round(fac * 1e3, 3),
        "factored_market_side_ms": round((fac - no_trade) * 1e3, 3),
        "factored_vs_matrix_slot_speedup": round(full / fac, 3),
        "market_side_ms": round(market_ms * 1e3, 3),
        "market_side_gb_per_s": round(2 * mat_stored / market_ms / 1e9, 1),
        "learn_side_ms": round(learn_ms * 1e3, 3),
        "learn_side_gb_per_s": round(learn_bytes / learn_ms / 1e9, 1),
        "overlap_or_fixed_ms": round(fixed_ms * 1e3, 3),
        "bf16_saving_ms": round((full_f32 - full) * 1e3, 3),
        "hbm_phases_peak_fraction": round(
            slot_bytes / hbm_ms / 1e9 / HBM_PEAK_GB_S, 3
        ),
        "note": (
            "full = env_only + no_trade - overlap (the two ablations share "
            "act+physics); a positive overlap_or_fixed term is the shared "
            "act/physics/scan cost — tiny [S*A, 4] act matmuls, [S, A] "
            "physics vector ops and scan iteration, which move almost no "
            "HBM. hbm_phases_peak_fraction is the slot's HBM-moving time "
            "(market + learn) against the traffic model: what binds the "
            "full-slot fraction below it is the fixed phase, not the "
            "memory streams"
        ),
    }

    doc = {
        "round": 4,
        "what": (
            "Measured decomposition of the config-4 slot program. "
            "Authoritative rows are the full-compiled-episode ones; the "
            "standalone kernel rows are dispatch-bound upper bounds "
            "(~5 ms tunneled dispatch each). The in_program_breakdown "
            "attributes the slot via compile-time ablations: market side = "
            "full - no_trading, learn side = full - env_only, and the "
            "overlap term is the shared act/physics/scan cost."
        ),
        "config": {
            "n_agents": A, "n_scenarios": S, "implementation": "ddpg",
            "share_across_agents": True, "batch_size": d.batch_size,
            "market_dtype_resolved": mdt,
            "device": jax.devices()[0].device_kind,
            "hbm_peak_gb_s_assumed": HBM_PEAK_GB_S,
        },
        "phases": rows,
        "in_program_breakdown": breakdown,
        "protocol": (
            "standalone rows: chained x20 dependent calls, scalar-sync, "
            "best of 3 (dispatch-bound upper bounds); full-slot rows: whole "
            "compiled episodes, best of 3"
        ),
    }
    print(json.dumps(doc, indent=2))
    return doc


if __name__ == "__main__":
    S = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    A = int(sys.argv[2]) if len(sys.argv) > 2 else 1000
    main(S, A)
