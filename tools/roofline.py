"""Measured per-kernel decomposition backing the config-4 roofline claim.

Round-2 VERDICT: the "~40% of HBM roofline" statement rested on an analytic
traffic model only. This script MEASURES, on the real chip at config-4 scale,
the per-slot cost of each fused phase of the scenario-batched slot program —
the negotiation matrix kernels (ops/pallas_market.py), the pooled DDPG learn
pass, and the full slot — plus each phase's HBM traffic model, and emits one
JSON document for ``artifacts/``.

Timing protocol (from .claude/skills/verify/SKILL.md): the tunneled TPU has
~85-260 ms of blocked-round-trip overhead and ``block_until_ready`` may
return early, so each phase chains N dependent calls, forces sync with a
scalar pull, divides by N, and takes best-of-3.

Usage: ``PYTHONPATH=/root/repo python tools/roofline.py [S] [A]``
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

HBM_PEAK_GB_S = 820.0  # TPU v5e spec sheet


def _timeit(fn, *args, n: int = 20, repeats: int = 3) -> float:
    """Best-of-``repeats`` seconds per call of jitted ``fn`` chained n deep."""
    out = fn(*args)
    jax.block_until_ready(out)

    def chain():
        res = args
        t0 = time.time()
        for _ in range(n):
            res = fn(*res) if isinstance(res, tuple) else fn(res)
        leaves = jax.tree_util.tree_leaves(res)
        float(leaves[0].sum())  # force a real sync through the tunnel
        return (time.time() - t0) / n

    return min(chain() for _ in range(repeats))


def main(S: int = 64, A: int = 1000) -> dict:
    from p2pmicrogrid_tpu.config import (
        BatteryConfig,
        DDPGConfig,
        SimConfig,
        TrainConfig,
        default_config,
    )
    from p2pmicrogrid_tpu.envs import make_ratings
    from p2pmicrogrid_tpu.models.ddpg import ddpg_learn_batch, ddpg_params_init
    from p2pmicrogrid_tpu.ops.pallas_market import (
        clear_market_fused,
        divide_power_fused_with_mean,
        divide_rank1_fused,
    )
    from p2pmicrogrid_tpu.parallel import (
        init_shared_state,
        make_scenario_traces,
        stack_scenario_arrays,
    )
    from p2pmicrogrid_tpu.parallel.scenarios import make_shared_episode_fn
    from p2pmicrogrid_tpu.train import make_policy

    cfg = default_config(
        sim=SimConfig(n_agents=A, n_scenarios=S),
        battery=BatteryConfig(enabled=True),
        train=TrainConfig(implementation="ddpg"),
        ddpg=DDPGConfig(buffer_size=256, batch_size=4, share_across_agents=True),
    )
    d = cfg.ddpg
    key = jax.random.PRNGKey(0)
    rows = []

    def add(name, secs, traffic_bytes, note):
        rows.append(
            {
                "phase": name,
                "ms": round(secs * 1e3, 3),
                "hbm_gb_modeled": round(traffic_bytes / 1e9, 3),
                "achieved_gb_per_s": round(traffic_bytes / secs / 1e9, 1),
                "hbm_peak_fraction_v5e": round(
                    traffic_bytes / secs / 1e9 / HBM_PEAK_GB_S, 3
                ),
                "note": note,
            }
        )

    # --- negotiation matrix kernels (per invocation = one round of one slot)
    mat_bytes = S * A * A * 4  # one f32 [S, A, A] matrix in HBM
    vec = jax.random.normal(key, (S, A))
    p2p = jax.random.normal(key, (S, A, A))

    f_rank1 = jax.jit(lambda v: divide_rank1_fused(v, v)[0][:, 0, :])
    secs = _timeit(f_rank1, vec)
    add("divide_rank1_fused", secs, mat_bytes,
        "round-1 proposal split: writes [S,A,A], reads only [S,A] vectors")

    f_div = jax.jit(lambda m: divide_power_fused_with_mean(m, m[:, :, 0])[0])
    secs = _timeit(f_div, p2p)
    add("divide_power_fused_with_mean", secs, 2 * mat_bytes,
        "later rounds: read + write [S,A,A] in one pass (round 2+ only)")

    # Chainable: fold the [S, A] clear result back into an [S, A, A] carry.
    f_clear = jax.jit(lambda m: m + clear_market_fused(m)[0][:, None, :])
    secs = _timeit(f_clear, p2p)
    add("clear_market_fused (+chain add)", secs, 3 * mat_bytes,
        "market clearing reads [S,A,A] in VMEM; the chaining add costs an "
        "extra matrix read+write, included in the traffic model")

    # --- pooled shared-critic learn pass (per slot update)
    B = d.batch_size * S * A  # pooled batch rows
    params = ddpg_params_init(d, A, key)
    s_b = jax.random.normal(key, (B, 4))
    a_b = jax.random.normal(key, (B, 1))
    r_b = jax.random.normal(key, (B,))

    @jax.jit
    def learn(s_in):
        out = ddpg_learn_batch(
            d, params.actor, params.critic, params.actor_target,
            params.critic_target, params.actor_opt, params.critic_opt,
            s_in, a_b, r_b, s_in,
        )
        # Chainable: mean residual folded into the carried input.
        return s_in + jnp.mean(out[-1])

    h = max(d.actor_hidden, d.critic_hidden)
    # ~10 activation passes (actor/critic fwd+bwd+target) of [B, h] f32.
    learn_bytes = 10 * B * h * 4
    secs = _timeit(learn, s_b)
    add("ddpg_learn_batch (pooled)", secs, learn_bytes,
        f"one shared actor-critic update on the pooled [{B}, obs] batch")

    # --- the full slot, from the real compiled episode program
    ratings = make_ratings(cfg, np.random.default_rng(42))
    traces = make_scenario_traces(cfg)
    arrays = stack_scenario_arrays(cfg, traces, ratings)
    policy = make_policy(cfg)
    ps, scen = init_shared_state(cfg, key)
    episode_fn = make_shared_episode_fn(cfg, policy, arrays, ratings)
    carry = (ps, scen)
    out = episode_fn(carry, key)
    jax.block_until_ready(out[0][0])
    best = np.inf
    for _ in range(3):
        t0 = time.time()
        carry, _ = episode_fn(carry, key)
        jax.block_until_ready(carry[0])
        best = min(best, time.time() - t0)
    slots = int(arrays.time.shape[1])
    slot_secs = best / slots
    # Per-slot traffic: rank-1 write + clear read (round 0-1 path) + learn.
    slot_bytes = 2 * mat_bytes + learn_bytes
    add("full slot (episode/96)", slot_secs, slot_bytes,
        "whole compiled slot: negotiate + clear + settle + learn + step")

    doc = {
        "config": {
            "n_agents": A, "n_scenarios": S, "implementation": "ddpg",
            "share_across_agents": True, "batch_size": d.batch_size,
            "device": jax.devices()[0].device_kind,
            "hbm_peak_gb_s_assumed": HBM_PEAK_GB_S,
        },
        "phases": rows,
        "protocol": "chained x20 dependent calls, scalar-sync, best of 3",
    }
    print(json.dumps(doc, indent=2))
    return doc


if __name__ == "__main__":
    S = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    A = int(sys.argv[2]) if len(sys.argv) > 2 else 1000
    main(S, A)
