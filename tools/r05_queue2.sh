#!/bin/bash
# Round-5 queue 2: waits for queue 1, then mitigated basin arm + cfg5 roofline.
cd /root/repo
export PYTHONPATH=/root/repo:$PYTHONPATH
while ! grep -q "ALL DONE" artifacts/r05_queue.log 2>/dev/null; do sleep 30; done
echo "[queue2] lrboost arm start $(date)" >> artifacts/r05_queue.log
BS_VARIANTS=capped_lrboost python tools/basin_stats.py 240 artifacts/BASIN_STATS_lrboost_r05.json >> artifacts/r05_queue.log 2>&1
echo "[queue2] lrboost arm rc=$? $(date)" >> artifacts/r05_queue.log
echo "[queue2] roofline_cfg5 start $(date)" >> artifacts/r05_queue.log
python tools/roofline_cfg5.py >> artifacts/r05_queue.log 2>&1
echo "[queue2] roofline_cfg5 rc=$? $(date)" >> artifacts/r05_queue.log
echo "[queue2] ALL DONE $(date)" >> artifacts/r05_queue.log
