"""Measure and PIN the sequential-NumPy baseline rates (round-3 VERDICT #3).

``vs_baseline`` ratios were re-derived each bench session by re-timing the
NumPy reference loop on a shared host — the same cfg3 measurement reported
713x in one capture and 1,341x in another, and the 1000-agent rate was
extrapolated from 2 cold slots. This tool measures every community size the
benchmark suite compares against over FULL days (96 slots — even at 1000
agents a full day is ~15 s), takes the best of ``--repeats`` runs (the
baseline is a rate: contention can only slow it, so max is the honest
choice), and writes ``artifacts/BASELINES_PINNED.json`` with provenance.
``benchmarks._baseline_info`` reads the committed table by default;
``P2P_REMEASURE_BASELINES=1`` bypasses it.

Usage: ``PYTHONPATH=/root/repo:$PYTHONPATH python tools/pin_baselines.py``
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import platform
import time

from p2pmicrogrid_tpu.benchmarks import numpy_reference_steps_per_sec

SIZES = (2, 10, 50, 128, 1000)
OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "artifacts",
    "BASELINES_PINNED.json",
)


def cpu_model() -> str:
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("model name"):
                    return line.split(":", 1)[1].strip()
    except OSError:
        pass
    return platform.processor() or "unknown"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--slots", type=int, default=96)
    ap.add_argument("--sizes", default=",".join(map(str, SIZES)))
    args = ap.parse_args()

    rates = {}
    for a in (int(s) for s in args.sizes.split(",")):
        runs = []
        for _ in range(args.repeats):
            t0 = time.time()
            runs.append(numpy_reference_steps_per_sec(a, args.slots))
            print(
                f"A={a}: {runs[-1]:.2f} slots/s ({time.time() - t0:.1f}s)",
                flush=True,
            )
        rates[str(a)] = {
            "steps_per_sec": round(max(runs), 3),
            "all_runs": [round(r, 3) for r in runs],
            "slots_measured": args.slots,
        }

    doc = {
        "what": (
            "Sequential per-agent NumPy reference loop rates "
            "(benchmarks.numpy_reference_steps_per_sec — the reference's "
            "execution model, community.py:67-93, minus TF overhead), "
            "measured over full days, best of repeats. The committed "
            "denominator for every vs_baseline ratio."
        ),
        "provenance": {
            "date": datetime.date.today().isoformat(),
            "host": platform.node(),
            "cpu": cpu_model(),
            "python": platform.python_version(),
            "repeats": args.repeats,
            "selection": "max over repeats (contention only slows a rate)",
        },
        "rates": rates,
    }
    with open(OUT, "w") as f:
        json.dump(doc, f, indent=2)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
