"""Basin entry/dwell statistics via the validated K=4 chunk proxy
(round-5 VERDICT #4).

Round 4 established the don't-heat basin narrative on n=4 seeds at the full
K=80 north star — too few to estimate entry probability. This sweep runs
>=10 seeds x {capped default, uncapped, half-lr} through the K=4 proxy
(4 chunks x 128 = 512 aggregate scenarios/episode), which round 4 validated
to <=0.1% against full K=80 runs (the chunk-delta mean is converged in K;
README round-4 notes), and classifies every 10th episode's greedy held-out
eval with the shipped detector (train/health.py). Output: per-run curves +
entry probability and dwell-time distributions per variant.

Usage: ``PYTHONPATH=/root/repo:$PYTHONPATH python tools/basin_stats.py
[EPISODES] [OUT]`` — env: BS_SEEDS (comma list, default 0-9).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

import jax
import numpy as np

from p2pmicrogrid_tpu.config import (
    BatteryConfig,
    DDPGConfig,
    SimConfig,
    TrainConfig,
    default_config,
)
from p2pmicrogrid_tpu.envs import make_ratings
from p2pmicrogrid_tpu.parallel import init_shared_pol_state
from p2pmicrogrid_tpu.parallel.device_gen import device_episode_arrays
from p2pmicrogrid_tpu.parallel.scenarios import (
    auto_scale_ddpg_lrs,
    make_chunked_episode_runner,
    make_shared_episode_fn,
    train_scenarios_chunked,
)
from p2pmicrogrid_tpu.train import make_policy
from p2pmicrogrid_tpu.train.health import HealthMonitor, make_greedy_eval

A, S_CHUNK, K = 1000, 128, 4          # the validated K=4 proxy
EPISODES, EVAL_EVERY, S_EVAL = 240, 10, 8
OUT = "artifacts/BASIN_STATS_r05.json"


def variant_cfg(name: str):
    base = dict(
        sim=SimConfig(n_agents=A, n_scenarios=S_CHUNK, market_dtype="bfloat16"),
        battery=BatteryConfig(enabled=True),
        train=TrainConfig(implementation="ddpg"),
    )
    if name == "capped_default":
        return default_config(
            ddpg=DDPGConfig(buffer_size=96, batch_size=4,
                            share_across_agents=True),
            **base,
        )
    if name == "uncapped":
        return default_config(
            ddpg=DDPGConfig(buffer_size=96, batch_size=4,
                            share_across_agents=True, learn_batch_cap=None),
            **base,
        )
    if name == "half_lr":
        cfg = default_config(
            ddpg=DDPGConfig(buffer_size=96, batch_size=4,
                            share_across_agents=True),
            **base,
        )
        scaled = auto_scale_ddpg_lrs(cfg)
        return dataclasses.replace(
            cfg,
            ddpg=dataclasses.replace(
                cfg.ddpg,
                actor_lr=scaled.ddpg.actor_lr * 0.5,
                critic_lr=scaled.ddpg.critic_lr * 0.5,
                lr_auto_scale=False,
            ),
        )
    if name == "capped_lrboost":
        # Same training as capped_default; the MITIGATED arm's boost
        # program is built separately in main().
        return variant_cfg("capped_default")
    raise ValueError(name)


def run_one(cfg, policy, ratings, episode_fn, runner, greedy_eval, seed,
            boosted=None):
    """One seeded proxy run. ``boosted`` = (runner, episode_fn) built from
    the lr-boosted config: while the monitor reports basin, training goes
    through it (the shipped --basin-mitigate lr-boost behavior)."""
    params = init_shared_pol_state(cfg, jax.random.PRNGKey(seed))
    mon = HealthMonitor(cfg.sim.slots_per_day,
                        warn_stream=open(os.devnull, "w"))
    curve = []

    def ev(ep):
        c, r = greedy_eval(params, jax.random.PRNGKey(1))
        status = mon.update(ep, c, r)
        curve.append({"episode": ep, "greedy_cost_eur": round(float(c), 2),
                      "greedy_reward": round(float(r), 1), "status": status})

    ev(0)
    key = (
        jax.random.PRNGKey(7)
        if seed == 0
        else jax.random.fold_in(jax.random.PRNGKey(7), seed)
    )
    for start in range(0, EPISODES, EVAL_EVERY):
        use_runner, use_fn = runner, episode_fn
        if boosted is not None and mon.in_basin:
            use_runner, use_fn = boosted
        params, _, _, _ = train_scenarios_chunked(
            cfg, policy, params, ratings, key,
            n_episodes=EVAL_EVERY, n_chunks=K, episode0=start,
            episode_fn=use_fn, runner=use_runner,
        )
        ev(start + EVAL_EVERY)
    dwell = None
    if mon.basin_entries:
        exit_ep = mon.basin_exits[0] if mon.basin_exits else EPISODES
        dwell = exit_ep - mon.basin_entries[0]
    return {
        "seed": seed,
        "entries": mon.basin_entries,
        "exits": mon.basin_exits,
        "entered": bool(mon.basin_entries),
        "dwell_episodes": dwell,
        "slides": sum(1 for p in curve if p["status"] == "slide"),
        "final": curve[-1],
        "curve": curve,
    }


def main() -> None:
    global EPISODES, OUT
    args = sys.argv[1:]
    if len(args) >= 1:
        EPISODES = int(args[0])
    if len(args) >= 2:
        OUT = args[1]
    seeds = [int(s) for s in
             os.environ.get("BS_SEEDS", ",".join(map(str, range(10)))).split(",")]
    doc = {
        "round": 5,
        "what": (
            f"Basin statistics on the K={K} chunk proxy (validated <=0.1% "
            f"vs K=80, round 4): {len(seeds)} seeds x 3 lr/cap variants, "
            f"{EPISODES} episodes each, greedy held-out eval every "
            f"{EVAL_EVERY} episodes classified by train/health.py. Note: "
            "round-5 slot rewrite changes f32 summation order vs the "
            "round-4 curves; trajectories are statistically comparable, "
            "not bit-identical."
        ),
        "config": {"n_agents": A, "chunk_scenarios": S_CHUNK, "chunks": K,
                   "episodes": EPISODES, "eval_scenarios": S_EVAL,
                   "seeds": seeds,
                   "device": jax.devices()[0].device_kind},
        "variants": {},
    }
    ratings = make_ratings(cfg_ref := variant_cfg("capped_default"),
                           np.random.default_rng(42))
    policy = make_policy(cfg_ref)

    variants = os.environ.get(
        "BS_VARIANTS", "capped_default,uncapped,half_lr"
    ).split(",")
    for name in variants:
        cfg = variant_cfg(name)
        eff = auto_scale_ddpg_lrs(cfg)

        def build(c):
            fn = make_shared_episode_fn(
                c, policy, None, ratings,
                arrays_fn=lambda k, cc=c: device_episode_arrays(
                    cc, k, ratings, S_CHUNK
                ),
                n_scenarios=S_CHUNK,
            )
            return make_chunked_episode_runner(c, fn, K), fn

        runner, episode_fn = build(cfg)
        boosted = None
        if name == "capped_lrboost":
            from p2pmicrogrid_tpu.train.health import _lr_boosted_cfg

            boosted = build(_lr_boosted_cfg(cfg, 3.0))
        greedy_eval = make_greedy_eval(cfg, policy, ratings, s_eval=S_EVAL)
        runs = []
        for seed in seeds:
            t0 = time.time()
            r = run_one(cfg, policy, ratings, episode_fn, runner,
                        greedy_eval, seed, boosted=boosted)
            r["wall_s"] = round(time.time() - t0, 1)
            runs.append(r)
            print(f"{name} seed {seed}: entered={r['entered']} "
                  f"dwell={r['dwell_episodes']} final={r['final']['status']} "
                  f"({r['wall_s']}s)", file=sys.stderr, flush=True)
            dwells = [x["dwell_episodes"] for x in runs if x["entered"]]
            doc["variants"][name] = {
                "effective_actor_lr": eff.ddpg.actor_lr,
                "effective_critic_lr": eff.ddpg.critic_lr,
                "learn_batch_cap": cfg.ddpg.learn_batch_cap,
                "n_runs": len(runs),
                "n_entered": sum(x["entered"] for x in runs),
                "entry_probability": round(
                    sum(x["entered"] for x in runs) / len(runs), 3
                ),
                "dwell_episodes": dwells,
                "n_ended_unhealthy": sum(
                    x["final"]["status"] != "healthy" for x in runs
                ),
                "runs": runs,
            }
            with open(OUT, "w") as f:
                json.dump(doc, f, indent=2)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
