"""Learning evidence at the full north-star scale (round-3 VERDICT item 1).

Trains BASELINE.md's flagship configuration — 1000 agents, 80 chunks x 128 =
10,240 Monte-Carlo scenarios per episode, community-shared actor-critic DDPG,
bfloat16 market matrices — with the DEFAULT pooled-batch lr rule
(parallel/scenarios.py:auto_scale_ddpg_lrs; nothing hand-tuned) and tracks
the GREEDY policy's community cost on a fixed held-out scenario set. The
claim under test: at 200x the scale of the reference's learning-curve
evidence (data_analysis.py:697-772), held-out cost falls and STAYS low —
replacing round 3's 100-agent-only evidence whose default lrs diverged.

Writes ``artifacts/LEARNING_northstar_r04.json`` incrementally (the run is
hours long; a partial curve survives interruption).

Usage: ``PYTHONPATH=/root/repo:$PYTHONPATH python tools/learning_northstar.py``
"""

from __future__ import annotations

import json
import sys

import jax
import numpy as np

from p2pmicrogrid_tpu.config import (
    BatteryConfig,
    DDPGConfig,
    SimConfig,
    TrainConfig,
    default_config,
)
from p2pmicrogrid_tpu.envs import make_ratings
from p2pmicrogrid_tpu.parallel import init_shared_pol_state
from p2pmicrogrid_tpu.parallel.device_gen import device_episode_arrays
from p2pmicrogrid_tpu.parallel.scenarios import (
    auto_scale_ddpg_lrs,
    ddpg_pooled_batch,
    make_chunked_episode_runner,
    make_shared_episode_fn,
    train_scenarios_chunked,
)
from p2pmicrogrid_tpu.train import make_policy
from p2pmicrogrid_tpu.train.health import make_greedy_eval

A, S_CHUNK, K = 1000, 128, 80        # 10,240 aggregate scenarios per episode
EPISODES, EVAL_EVERY = 240, 10
S_EVAL = 8
OUT = "artifacts/LEARNING_northstar_r04.json"
SEED = 0


def _resolved_market_impl(cfg) -> str:
    from p2pmicrogrid_tpu.envs.community import resolve_market_impl

    return resolve_market_impl(cfg)


def main() -> None:
    import os
    import sys as _sys

    global EPISODES, OUT, SEED
    args = _sys.argv[1:]
    # Optional: EPISODES OUT SEED (the seed-robustness rerun uses them).
    if len(args) >= 1:
        EPISODES = int(args[0])
    if len(args) >= 2:
        OUT = args[1]
    if len(args) >= 3:
        SEED = int(args[2])
    # NS_LEARN_CAP overrides DDPGConfig.learn_batch_cap for A/B runs
    # against the shipped capped default ("0" = uncapped, matching the CLI's
    # --learn-batch-cap 0 convention).
    cap_env = os.environ.get("NS_LEARN_CAP")
    ddpg_kw = {}
    if cap_env is not None:
        ddpg_kw["learn_batch_cap"] = int(cap_env) or None
    # NS_LR_MULT post-multiplies the auto-rule's effective lrs (basin
    # operating-point probes): the scaled lrs are pinned explicitly and the
    # auto rule is turned off so the episode builder doesn't rescale.
    lr_mult = float(os.environ.get("NS_LR_MULT", "1"))
    cfg = default_config(
        sim=SimConfig(
            n_agents=A, n_scenarios=S_CHUNK, market_dtype="bfloat16"
        ),
        battery=BatteryConfig(enabled=True),
        train=TrainConfig(implementation="ddpg"),
        # bench_northstar's exact learner config; lrs come from the default
        # auto rule, not from hand tuning.
        ddpg=DDPGConfig(buffer_size=96, batch_size=4, share_across_agents=True,
                        **ddpg_kw),
    )
    if lr_mult != 1.0:
        import dataclasses

        scaled = auto_scale_ddpg_lrs(cfg)
        cfg = dataclasses.replace(
            cfg,
            ddpg=dataclasses.replace(
                cfg.ddpg,
                actor_lr=scaled.ddpg.actor_lr * lr_mult,
                critic_lr=scaled.ddpg.critic_lr * lr_mult,
                lr_auto_scale=False,
            ),
        )
    eff = auto_scale_ddpg_lrs(cfg)
    doc = {
        "round": 4,
        "what": (
            "Greedy held-out community cost while training the FULL north "
            f"star ({A} agents, {K} chunks x {S_CHUNK} = {K * S_CHUNK} "
            "scenarios/episode, shared-critic DDPG, bf16 market) at the "
            "DEFAULT pooled-batch lr rule — no hand-tuned lrs."
        ),
        "config": {
            "n_agents": A, "chunk_scenarios": S_CHUNK, "chunks": K,
            "aggregate_scenarios": K * S_CHUNK, "episodes": EPISODES,
            "eval_scenarios": S_EVAL, "market_dtype": "bfloat16",
            "pooled_batch": ddpg_pooled_batch(cfg),
            "learn_batch_cap": cfg.ddpg.learn_batch_cap,
            "market_impl": _resolved_market_impl(cfg),
            "lr_rule": (
                "auto (sqrt(400/effective pooled), scenarios.py)"
                if lr_mult == 1.0
                else f"auto x {lr_mult} (NS_LR_MULT, pinned)"
            ),
            "effective_actor_lr": eff.ddpg.actor_lr,
            "effective_critic_lr": eff.ddpg.critic_lr,
            "seed": SEED,  # init/training randomness; community + eval fixed
            "device": jax.devices()[0].device_kind,
        },
        "curve": [],
    }

    ratings = make_ratings(cfg, np.random.default_rng(42))
    policy = make_policy(cfg)
    params = init_shared_pol_state(cfg, jax.random.PRNGKey(SEED))

    # The first-class health evaluator (train/health.py) — same fixed
    # held-out draw (eval seed 10_000) and aggregation as the original
    # round-4 closure, so curves remain comparable across rounds.
    greedy_cost = make_greedy_eval(cfg, policy, ratings, s_eval=S_EVAL)

    episode_fn = make_shared_episode_fn(
        cfg, policy, None, ratings,
        arrays_fn=lambda k: device_episode_arrays(cfg, k, ratings, S_CHUNK),
        n_scenarios=S_CHUNK,
    )
    # NS_CHUNK_PARALLEL widens the runner (bench_northstar ships C=2); the
    # per-chunk trajectories and K-delta mean are identical either way, so
    # curves at different widths must agree up to float summation order.
    C = int(os.environ.get("NS_CHUNK_PARALLEL", "1"))
    doc["config"]["chunk_parallel"] = C
    runner = make_chunked_episode_runner(cfg, episode_fn, K, chunk_parallel=C)

    def record(ep, extra=None):
        c, r = greedy_cost(params, jax.random.PRNGKey(1))
        row = {"episode": ep, "greedy_cost_eur": round(float(c), 2),
               "greedy_reward": round(float(r), 1)}
        row.update(extra or {})
        doc["curve"].append(row)
        print(row, file=sys.stderr, flush=True)
        with open(OUT, "w") as f:
            json.dump(doc, f, indent=2)

    record(0)
    # SEED 0 reproduces the original committed run's exact key chain.
    key = (
        jax.random.PRNGKey(7)
        if SEED == 0
        else jax.random.fold_in(jax.random.PRNGKey(7), SEED)
    )
    for start in range(0, EPISODES, EVAL_EVERY):
        params, rewards, _, secs = train_scenarios_chunked(
            cfg, policy, params, ratings, key,
            n_episodes=EVAL_EVERY, n_chunks=K, episode0=start,
            episode_fn=episode_fn, runner=runner,
        )
        record(start + EVAL_EVERY, {
            "train_reward_mean": round(float(np.mean(rewards[-2:])), 1),
            "train_secs": round(secs, 1),
        })
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
