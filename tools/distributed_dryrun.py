"""Two-process ``jax.distributed`` dryrun of the multi-host mesh path.

Round-3 VERDICT weak #6: ``make_hybrid_mesh``'s ``jax.process_count()``
branch (parallel/mesh.py:54) and the hybrid DCN x ICI grid were only ever
exercised inside one process on a virtual mesh. This tool launches TWO real
OS processes, each with 4 virtual CPU devices, wires them together with
``jax.distributed.initialize`` (the multi-controller runtime a TPU pod
uses), builds the (2 hosts x 4 chips) hybrid mesh via the process_count()
branch in each, and runs ONE shared-tabular training episode with the
scenario axis sharded over the full host x chip grid — the scenario-mean
parameter update lowers to a hierarchical all-reduce crossing the "dcn"
axis. A third, single-process run on 8 virtual devices with the same seeds
is the equivalence reference: identical results prove sharding-over-
processes changes placement, not math.

Usage::

    PYTHONPATH=/root/repo:$PYTHONPATH python tools/distributed_dryrun.py
        [--out artifacts/DISTRIBUTED_r04.json]

Exit 0 and ``"ok": true`` in the JSON document on success. Worker mode
(internal): ``--worker PID --nproc N --port P`` / ``--single``.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys

S, A = 8, 3  # scenarios (sharded over all 8 devices) x agents


def run_step(mesh) -> dict:
    """One shared-tabular episode on ``mesh`` with on-device scenario
    synthesis, scenario axis sharded over every mesh axis. Returns
    replicated scalar summaries (addressable on every process)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2pmicrogrid_tpu.config import SimConfig, TrainConfig, default_config
    from p2pmicrogrid_tpu.envs import make_ratings
    from p2pmicrogrid_tpu.parallel.device_gen import device_episode_arrays
    from p2pmicrogrid_tpu.parallel.mesh import (
        hybrid_scenario_sharding,
        replicate,
    )
    from p2pmicrogrid_tpu.parallel.scenarios import make_shared_episode_fn
    from p2pmicrogrid_tpu.train import init_policy_state, make_policy

    cfg = default_config(
        sim=SimConfig(n_agents=A, n_scenarios=S),
        train=TrainConfig(implementation="tabular"),
    )
    ratings = make_ratings(cfg, np.random.default_rng(0))
    policy = make_policy(cfg)
    sh = hybrid_scenario_sharding(mesh)
    episode_fn = make_shared_episode_fn(
        cfg, policy, None, ratings,
        arrays_fn=lambda k: device_episode_arrays(
            cfg, k, ratings, S, scenario_sharding=sh
        ),
        n_scenarios=S,
    )
    # Identical on every process; explicit replication makes the inputs
    # global arrays the multi-controller runtime accepts.
    pol_state = replicate(init_policy_state(cfg, jax.random.PRNGKey(0)), mesh)

    @jax.jit
    def step(carry, key):
        (pol, _), (r, _) = episode_fn(carry, key)
        return jnp.sum(jnp.abs(pol.q_table)), jnp.sum(r)

    qsum, rsum = step((pol_state, None), jax.random.PRNGKey(1))
    return {"qsum": float(qsum), "rsum": float(rsum)}


def worker(pid: int, nproc: int, port: int) -> None:
    import jax

    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    assert jax.process_count() == nproc, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    from p2pmicrogrid_tpu.parallel.mesh import make_hybrid_mesh

    # No dcn_size: THE process_count() branch under test.
    mesh = make_hybrid_mesh()
    assert mesh.devices.shape == (nproc, 8 // nproc), mesh.devices.shape
    out = run_step(mesh)
    out.update(
        {
            "process": pid,
            "process_count": jax.process_count(),
            "local_devices": len(jax.local_devices()),
            "mesh_shape": list(mesh.devices.shape),
            "mesh_axes": list(mesh.axis_names),
        }
    )
    print(json.dumps(out), flush=True)


def single() -> None:
    """Single-process equivalence reference: same mesh geometry (2 x 4) on
    8 virtual devices in one process, same seeds."""
    from p2pmicrogrid_tpu.parallel.mesh import make_hybrid_mesh

    mesh = make_hybrid_mesh(dcn_size=2)
    out = run_step(mesh)
    out["mesh_shape"] = list(mesh.devices.shape)
    print(json.dumps(out), flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", type=int, default=None)
    ap.add_argument("--nproc", type=int, default=2)
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--single", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    if args.worker is not None:
        worker(args.worker, args.nproc, args.port)
        return 0
    if args.single:
        single()
        return 0

    # Coordinator: pick a free port, launch 2 workers (4 virtual CPU devices
    # each) + the single-process reference (8 devices).
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    # The TPU-plugin site hook (a path entry like ~/.axon_site) pins the
    # platform via jax.config at interpreter startup, SHADOWING the
    # JAX_PLATFORMS env var — strip it so the workers really run the CPU
    # backend with virtual devices.
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        [repo]
        + [
            p
            for p in env.get("PYTHONPATH", "").split(os.pathsep)
            # Only the plugin hook dirs (hidden "*_site" entries) are
            # stripped; ordinary user paths pass through untouched.
            if p
            and not (
                os.path.basename(p).startswith(".")
                and os.path.basename(p).endswith("_site")
            )
        ]
    )
    base = [sys.executable, os.path.abspath(__file__)]

    def spawn(extra, n_local):
        e = dict(env)
        e["JAX_PLATFORMS"] = "cpu"
        e["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={n_local}"
        )
        return subprocess.Popen(
            base + extra, env=e, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )

    nproc = 2
    procs = [
        spawn(["--worker", str(i), "--nproc", str(nproc), "--port", str(port)], 4)
        for i in range(nproc)
    ]
    ref = spawn(["--single"], 8)

    rows, errs = [], []
    children = procs + [ref]
    for p in children:
        try:
            out, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            # One hung child (e.g. a lost coordinator port) must not orphan
            # the rest or leave --out unwritten: kill everything, record it.
            for q in children:
                if q.poll() is None:
                    q.kill()
            out, err = p.communicate()
            errs.append(f"timeout after 600s; partial stderr: {err[-1500:]}")
            continue
        if p.returncode != 0:
            errs.append(err[-2000:])
        for line in out.splitlines():
            if line.startswith("{"):
                rows.append(json.loads(line))

    workers = [r for r in rows if "process" in r]
    singles = [r for r in rows if "process" not in r]
    ok = (
        not errs
        and len(workers) == nproc
        and len(singles) == 1
        and all(r["process_count"] == nproc for r in workers)
        and all(r["mesh_shape"] == [2, 4] for r in workers)
        # Both processes computed the SAME replicated result...
        and abs(workers[0]["qsum"] - workers[1]["qsum"]) < 1e-6
        # ...equal to the single-process 8-device run (placement, not math).
        and abs(workers[0]["qsum"] - singles[0]["qsum"]) < 1e-4
        and abs(workers[0]["rsum"] - singles[0]["rsum"]) < 1e-2
    )
    doc = {
        "ok": ok,
        "what": (
            "2-process jax.distributed dryrun: hybrid (2 hosts x 4 devices) "
            "mesh via the process_count() branch, one shared-tabular episode "
            "with the scenario axis sharded over the host grid, checked "
            "equal across processes AND against a single-process 8-device "
            "run of the same seeds."
        ),
        "workers": workers,
        "single_reference": singles,
        "errors": errs,
    }
    text = json.dumps(doc, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out} ok={ok}")
    else:
        print(text)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
