#!/bin/bash
# Round-5 serialized TPU measurement queue (one chip — jobs must not overlap).
cd /root/repo
export PYTHONPATH=/root/repo:$PYTHONPATH
echo "[queue] basin_mitigation start $(date)" >> artifacts/r05_queue.log
python tools/basin_mitigation.py 200 artifacts/BASIN_MITIGATION_r05.json 2 >> artifacts/r05_queue.log 2>&1
echo "[queue] basin_mitigation rc=$? $(date)" >> artifacts/r05_queue.log
echo "[queue] basin_stats start $(date)" >> artifacts/r05_queue.log
python tools/basin_stats.py 240 artifacts/BASIN_STATS_r05.json >> artifacts/r05_queue.log 2>&1
echo "[queue] basin_stats rc=$? $(date)" >> artifacts/r05_queue.log
echo "[queue] learning_dqn start $(date)" >> artifacts/r05_queue.log
python tools/learning_dqn.py 200 artifacts/LEARNING_dqn_r05.json 0 >> artifacts/r05_queue.log 2>&1
echo "[queue] learning_dqn rc=$? $(date)" >> artifacts/r05_queue.log
echo "[queue] ALL DONE $(date)" >> artifacts/r05_queue.log
