#!/usr/bin/env python
"""Static check: hot-path modules must not grow un-annotated host syncs.

The async episode pipeline (PR 4) exists because blocking readbacks crept
into every training driver one ``np.asarray(...)`` at a time — each one
looked harmless, and together they serialized dispatch against the full
host round trip per episode (~0.1 s over the tunneled runtime). This
checker makes that regression class executable: the hot-path modules below
may only contain blocking-readback constructs on lines that carry an
explicit ``# host-sync: <why>`` annotation (same line, or in the comment
block immediately above). New un-annotated sites fail tier-1
(tests/test_pipeline.py) and ``check_artifacts_schema.py --root``'s
``check_all`` sweep.

Flagged constructs (conservative, string-level — the point is to force a
human to write down WHY a sync is on the hot path, not to prove one
exists):

* ``np.asarray(`` on a possibly-device value (``jnp.asarray`` — a
  host->device transfer, not a readback — is NOT flagged),
* ``jax.device_get(``,
* ``block_until_ready(``,
* ``.item()``.

Whitelisted sites in-tree today: the pipeline's own drain resolve
(telemetry/async_drain.py — copies were started asynchronously at dispatch
time), end-of-loop timing barriers, the serve engine's intentional
per-batch latency boundary, and host-side numpy array construction that
never touches a device value.

Exit status: 0 when clean, 1 with one problem per line on stderr.
Stdlib-only — runs with the accelerator stack down.
"""

from __future__ import annotations

import argparse
import io
import os
import re
import sys
import tokenize

# The modules on the dispatch hot path: training drivers, the episode env,
# the serving engine, and the async drain itself.
HOT_PATH_FILES = (
    os.path.join("p2pmicrogrid_tpu", "parallel", "scenarios.py"),
    os.path.join("p2pmicrogrid_tpu", "train", "loop.py"),
    os.path.join("p2pmicrogrid_tpu", "envs", "community.py"),
    # The fused slot megakernel (ISSUE 12): its wrapper runs inside every
    # fused episode's scan — a blocking readback there would serialize the
    # whole training dispatch per slot.
    os.path.join("p2pmicrogrid_tpu", "ops", "pallas_slot.py"),
    os.path.join("p2pmicrogrid_tpu", "serve", "engine.py"),
    # The continuous batcher's step loop (ISSUE 14) IS the serving hot
    # path: every request of every session rides one worker's engine
    # steps, and a stray readback there serializes the whole slot ring.
    os.path.join("p2pmicrogrid_tpu", "serve", "continuous.py"),
    # The gateway's async handlers serve every connected household from one
    # event loop — a single un-annotated blocking readback stalls ALL of
    # them, not one request (the worst place in the repo for this class).
    os.path.join("p2pmicrogrid_tpu", "serve", "gateway.py"),
    os.path.join("p2pmicrogrid_tpu", "serve", "registry.py"),
    # The fleet tier sits in front of EVERY replica's event loop: a
    # blocking readback in the router's act path or the fault injector
    # stalls the whole fleet's traffic, not one process.
    os.path.join("p2pmicrogrid_tpu", "serve", "router.py"),
    os.path.join("p2pmicrogrid_tpu", "serve", "faults.py"),
    # The wire/trust tier (PR 9): the mux framing and token checks run
    # per request on the gateway/proxy event loops, and the proxy fans
    # every household through one process — the same worst-case blast
    # radius as the gateway.
    os.path.join("p2pmicrogrid_tpu", "serve", "wire.py"),
    os.path.join("p2pmicrogrid_tpu", "serve", "auth.py"),
    os.path.join("p2pmicrogrid_tpu", "serve", "proxy.py"),
    os.path.join("p2pmicrogrid_tpu", "serve", "procfleet.py"),
    # The resilience layer wraps every training dispatch (guard observation
    # per block, checkpoint callbacks on the save cadence): a blocking
    # readback here would serialize the whole async pipeline it guards.
    os.path.join("p2pmicrogrid_tpu", "train", "resilience.py"),
    # The continual loop (PR 10): the trace-pretrain scan and the chunked
    # fine-tune it enters share the training dispatch path, and the
    # promotion gate/canary run next to live serving — stray readbacks in
    # either stall training or the canary's stage cadence.
    os.path.join("p2pmicrogrid_tpu", "train", "continual.py"),
    os.path.join("p2pmicrogrid_tpu", "serve", "promotion.py"),
    # The autopilot (ISSUE 11) drives the whole continual cycle next to
    # live fleet traffic: a stray blocking readback in its cycle loop
    # stalls the canary cadence and the recovery path alike.
    os.path.join("p2pmicrogrid_tpu", "serve", "autopilot.py"),
    # The population sampler (ISSUE 17) generates the per-request arrival
    # stream for million-household benches: a device readback per draw
    # would turn the O(log N) vectorized sampler into the bench's own
    # bottleneck and poison every scale capture's open-loop schedule.
    os.path.join("p2pmicrogrid_tpu", "scale", "population.py"),
    # The regime engine (ISSUE 13) wraps every regime episode's slot scan
    # and the per-regime eval/training drivers — a blocking readback in
    # the slot wrapper or the episode closures would serialize every
    # mixed-regime training dispatch per slot.
    os.path.join("p2pmicrogrid_tpu", "regimes", "engine.py"),
    os.path.join("p2pmicrogrid_tpu", "regimes", "train.py"),
    os.path.join("p2pmicrogrid_tpu", "regimes", "evaluate.py"),
    os.path.join("p2pmicrogrid_tpu", "telemetry", "async_drain.py"),
    # Trace-context propagation (ISSUE 16) runs per request on every
    # serving hot path above — the module must stay stdlib-only and
    # readback-free, or tracing taxes the very latencies it attributes.
    os.path.join("p2pmicrogrid_tpu", "telemetry", "tracing.py"),
)

ANNOTATION = "host-sync:"

PATTERNS = (
    # np.asarray on device values blocks; jnp.asarray is host->device.
    (re.compile(r"(?<!j)np\.asarray\("), "np.asarray("),
    (re.compile(r"jax\.device_get\("), "jax.device_get("),
    (re.compile(r"block_until_ready\("), "block_until_ready("),
    (re.compile(r"\.item\(\)"), ".item()"),
)


def _annotated(lines: list, i: int) -> bool:
    """True when line ``i`` carries the annotation inline or in the
    contiguous comment block immediately above it."""
    if ANNOTATION in lines[i]:
        return True
    j = i - 1
    while j >= 0 and lines[j].lstrip().startswith("#"):
        if ANNOTATION in lines[j]:
            return True
        j -= 1
    return False


def _code_only(source: str) -> list:
    """The source's lines with every string literal and comment blanked —
    docstrings DISCUSSING ``np.asarray`` must not trip the check, and the
    annotation lookup runs on the original lines separately."""
    lines = [list(l) for l in source.splitlines()]
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type not in (tokenize.STRING, tokenize.COMMENT):
                continue
            (sr, sc), (er, ec) = tok.start, tok.end
            for r in range(sr - 1, er):
                if r >= len(lines):
                    break
                c0 = sc if r == sr - 1 else 0
                c1 = ec if r == er - 1 else len(lines[r])
                for c in range(c0, min(c1, len(lines[r]))):
                    lines[r][c] = " "
    except (tokenize.TokenError, IndentationError):
        pass  # best-effort: unparseable files fall back to raw lines
    return ["".join(l) for l in lines]


def check_file(path: str, rel: str, problems: list) -> None:
    try:
        with open(path) as f:
            source = f.read()
    except OSError as err:
        problems.append(f"{rel}: unreadable ({err})")
        return
    lines = source.splitlines()
    for i, line in enumerate(_code_only(source)):
        for pattern, label in PATTERNS:
            if pattern.search(line) and not _annotated(lines, i):
                problems.append(
                    f"{rel}:{i + 1}: un-annotated blocking readback "
                    f"({label!r}) on a hot-path module — route it through "
                    "the async drain (telemetry/async_drain.py) or annotate "
                    "the line with '# host-sync: <why this must block>'"
                )
                break


def check_host_sync(repo_root: str) -> list:
    """All problems found in the hot-path modules under ``repo_root``
    (empty list = clean). Files absent under ``repo_root`` are skipped, so
    the check composes with artifact-only scan roots."""
    problems: list = []
    for rel in HOT_PATH_FILES:
        path = os.path.join(repo_root, rel)
        if os.path.exists(path):
            check_file(path, rel, problems)
    return problems


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--root",
        default=os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."),
        help="repo root to scan (default: the checkout containing this script)",
    )
    args = parser.parse_args(argv)
    root = os.path.abspath(args.root)
    problems = check_host_sync(root)
    for p in problems:
        print(p, file=sys.stderr)
    n_files = sum(
        os.path.exists(os.path.join(root, rel)) for rel in HOT_PATH_FILES
    )
    print(
        f"checked {n_files} hot-path module(s): {len(problems)} "
        "un-annotated host sync(s)"
    )
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
