"""Op-level attribution of the north-star slot's fixed phase (VERDICT r4 #2).

The round-4 width sweep quantified ~0.6 ms/slot of width-independent fixed
cost (artifacts/WIDTH_SWEEP_r04.json) — ~44% of the shipped cfg4 slot — but
no profile showed WHICH ops compose it. This tool captures a jax.profiler
device trace of the exact north-star chunk episode program (A=1000, S=128,
factored market, capped pooled DDPG, bf16) and emits the per-slot op table:
every XLA op's device-time share, bucketed by source phase via the HLO
metadata the trace carries (op_name annotations from jax name scopes).

Usage: ``PYTHONPATH=/root/repo:$PYTHONPATH python tools/slot_profile.py
[S] [EPISODES]`` — writes artifacts/SLOT_PROFILE_r05.json.
"""

from __future__ import annotations

import glob
import gzip
import json
import sys
from collections import defaultdict

import jax
import numpy as np

OUT = "artifacts/SLOT_PROFILE_r05.json"
TRACE_DIR = "/tmp/slot_profile_trace"


def build_episode(S: int):
    from p2pmicrogrid_tpu.config import (
        BatteryConfig,
        DDPGConfig,
        SimConfig,
        TrainConfig,
        default_config,
    )
    from p2pmicrogrid_tpu.envs import make_ratings
    from p2pmicrogrid_tpu.parallel import init_shared_pol_state
    from p2pmicrogrid_tpu.parallel.device_gen import device_episode_arrays
    from p2pmicrogrid_tpu.parallel.scenarios import (
        init_scen_state_only,
        make_shared_episode_fn,
    )
    from p2pmicrogrid_tpu.train import make_policy

    A = 1000
    cfg = default_config(
        sim=SimConfig(n_agents=A, n_scenarios=S, market_dtype="bfloat16"),
        battery=BatteryConfig(enabled=True),
        train=TrainConfig(implementation="ddpg"),
        ddpg=DDPGConfig(buffer_size=96, batch_size=4, share_across_agents=True),
    )
    ratings = make_ratings(cfg, np.random.default_rng(42))
    policy = make_policy(cfg)
    ps = init_shared_pol_state(cfg, jax.random.PRNGKey(0))
    scen = init_scen_state_only(cfg, jax.random.PRNGKey(1))
    episode_fn = make_shared_episode_fn(
        cfg, policy, None, ratings,
        arrays_fn=lambda k: device_episode_arrays(cfg, k, ratings, S),
        n_scenarios=S,
    )
    return cfg, episode_fn, (ps, scen)


def collect_device_ops(trace_dir: str) -> dict:
    """Per-op EXCLUSIVE (self) device durations from the newest trace.

    The device's "XLA Ops" track nests container rows (the slot `while`
    spans every op it contains, vmapped bodies add further levels), so
    summing raw durations double-counts. Events are replayed through an
    interval stack per track and each op is credited only with time not
    covered by its children."""
    files = sorted(glob.glob(f"{trace_dir}/plugins/profile/*/*.trace.json.gz"))
    if not files:
        raise RuntimeError(f"no trace written under {trace_dir}")
    d = json.load(gzip.open(files[-1]))
    ev = d.get("traceEvents", [])
    pid_names, tid_names = {}, {}
    for e in ev:
        if e.get("ph") == "M":
            if e.get("name") == "process_name":
                pid_names[e["pid"]] = e["args"]["name"]
            elif e.get("name") == "thread_name":
                tid_names[(e["pid"], e["tid"])] = e["args"]["name"]
    op_events = [
        e for e in ev
        if e.get("ph") == "X"
        and "TPU" in pid_names.get(e.get("pid"), "")
        and tid_names.get((e["pid"], e["tid"])) == "XLA Ops"
    ]
    op_events.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
    ops = defaultdict(float)
    metas = {}
    stack = []  # (end_ts, name, child_time_accum_index)
    child_time = []
    for e in op_events:
        ts, dur, name = e["ts"], e.get("dur", 0.0), e["name"]
        while stack and ts >= stack[-1][0] - 1e-9:
            _, p_name, idx = stack.pop()
            ops[p_name] += child_time[idx][0] - child_time[idx][1]
            if stack:
                child_time[stack[-1][2]][1] += child_time[idx][0]
        child_time.append([dur, 0.0])
        stack.append((ts + dur, name, len(child_time) - 1))
        if e.get("args") and name not in metas:
            metas[name] = e["args"]
    while stack:
        _, p_name, idx = stack.pop()
        ops[p_name] += child_time[idx][0] - child_time[idx][1]
        if stack:
            child_time[stack[-1][2]][1] += child_time[idx][0]
    return {"durations_us": dict(ops), "meta_sample": metas}


def main() -> None:
    S = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    episodes = int(sys.argv[2]) if len(sys.argv) > 2 else 2
    cfg, episode_fn, carry = build_episode(S)
    slots = cfg.sim.slots_per_day

    # Warm/compile outside the trace.
    carry, _ = episode_fn(carry, jax.random.PRNGKey(100))
    jax.block_until_ready(carry)

    with jax.profiler.trace(TRACE_DIR):
        for i in range(episodes):
            carry, _ = episode_fn(carry, jax.random.PRNGKey(200 + i))
        jax.block_until_ready(carry)

    raw = collect_device_ops(TRACE_DIR)
    n_slots = episodes * slots
    rows = []
    total_us = 0.0
    for name, us in raw["durations_us"].items():
        if name.startswith("jit_"):  # enclosing XLA-program row, not an op
            continue
        total_us += us
        rows.append({
            "op": name,
            "total_us": round(us, 1),
            "us_per_slot": round(us / n_slots, 3),
            "args": raw["meta_sample"].get(name, {}),
        })
    rows.sort(key=lambda r: -r["total_us"])
    doc = {
        "round": 5,
        "what": (
            f"Device-op profile of the factored north-star chunk episode "
            f"(A=1000, S={S}, {episodes} episodes x {slots} slots). "
            "us_per_slot sums to the slot's device-op time; the gap to the "
            "measured wall slot time is scan/runtime dispatch."
        ),
        "device": jax.devices()[0].device_kind,
        "episodes": episodes,
        "slots_per_episode": slots,
        "total_device_us_per_slot": round(total_us / n_slots, 2),
        "ops": rows[:60],
        "tail_op_count": max(0, len(rows) - 60),
        "tail_us_per_slot": round(
            sum(r["us_per_slot"] for r in rows[60:]), 2
        ),
    }
    with open(OUT, "w") as f:
        json.dump(doc, f, indent=2)
    print(json.dumps({k: doc[k] for k in
                      ("total_device_us_per_slot", "tail_op_count",
                       "tail_us_per_slot")}, indent=1))
    for r in rows[:25]:
        print(f"{r['us_per_slot']:>9.2f} us/slot  {r['op'][:70]}")


if __name__ == "__main__":
    main()
