"""Measure chunk-parallel width C for the chunked north-star runner.

Same chunk size/update semantics as bench_northstar (A=1000, S_chunk=128,
capped pooled DDPG, factored market); K is kept small so compile+run stays
probe-sized — per-scenario-step throughput is width-dependent, not
K-dependent (the runner is one scan over K/C groups either way).

Usage: PYTHONPATH=/root/repo:$PYTHONPATH python tools/chunk_parallel_probe.py [C ...]
"""

from __future__ import annotations

import json
import sys
import time

import jax
import numpy as np


def main(widths) -> list:
    from p2pmicrogrid_tpu.config import (
        BatteryConfig,
        DDPGConfig,
        SimConfig,
        TrainConfig,
        default_config,
    )
    from p2pmicrogrid_tpu.envs import make_ratings
    from p2pmicrogrid_tpu.parallel import init_shared_pol_state
    from p2pmicrogrid_tpu.parallel.device_gen import device_episode_arrays
    from p2pmicrogrid_tpu.parallel.scenarios import (
        make_chunked_episode_runner,
        make_shared_episode_fn,
    )
    from p2pmicrogrid_tpu.train import make_policy

    A, S_chunk, K = 1000, 128, 8
    cfg = default_config(
        sim=SimConfig(n_agents=A, n_scenarios=S_chunk),
        battery=BatteryConfig(enabled=True),
        train=TrainConfig(implementation="ddpg"),
        ddpg=DDPGConfig(buffer_size=96, batch_size=4, share_across_agents=True),
    )
    ratings = make_ratings(cfg, np.random.default_rng(42))
    policy = make_policy(cfg)
    key = jax.random.PRNGKey(0)
    ps = init_shared_pol_state(cfg, key)
    episode_fn = make_shared_episode_fn(
        cfg, policy, None, ratings,
        arrays_fn=lambda k: device_episode_arrays(cfg, k, ratings, S_chunk),
        n_scenarios=S_chunk,
    )
    slots = cfg.sim.slots_per_day
    rows = []
    for C in widths:
        runner = make_chunked_episode_runner(
            cfg, episode_fn, K, chunk_parallel=C
        )
        chunk_keys = jax.random.split(jax.random.PRNGKey(1), K)
        out = runner(ps, chunk_keys)  # compile
        jax.block_until_ready(jax.tree_util.tree_leaves(out)[0])

        best = float("inf")
        for _ in range(3):
            p = ps
            t0 = time.time()
            for i in range(3):  # chained dependent episode calls
                p, r, _ = runner(p, jax.random.split(jax.random.PRNGKey(i), K))
            float(jax.tree_util.tree_leaves(p)[0].sum())
            best = min(best, (time.time() - t0) / 3)

        steps_s = slots * S_chunk * K / best
        row = {
            "chunk_parallel": C,
            "episode_ms": round(best * 1e3, 1),
            "scenario_env_steps_per_sec": round(steps_s),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
    return rows


if __name__ == "__main__":
    widths = [int(a) for a in sys.argv[1:]] or [1, 2, 4]
    main(widths)
