"""Learning-dynamics evidence for chunked aggregate-scenario training.

Chunk-averaged parameter deltas (local-SGD with Adam inner updates,
scenarios.py:train_scenarios_chunked) are an approximation of the
synchronized scenario-averaged update, so the claim "the north-star mode
actually learns" needs measurement, not argument. This script trains a
shared-critic DDPG community in chunked mode and tracks the GREEDY policy's
community cost on a fixed held-out scenario set at checkpoints; a
monotonic-ish cost decrease is the evidence. Emits one JSON document for
``artifacts/``.

Usage: ``PYTHONPATH=/root/repo python tools/learning_chunked.py``
"""

from __future__ import annotations

import json
import sys

import jax
import jax.numpy as jnp
import numpy as np

from p2pmicrogrid_tpu.config import (
    BatteryConfig,
    DDPGConfig,
    SimConfig,
    TrainConfig,
    default_config,
)
from p2pmicrogrid_tpu.envs import init_physical, make_ratings
from p2pmicrogrid_tpu.envs.community import AgentRatings, slot_dynamics_batched
from p2pmicrogrid_tpu.models.ddpg import ddpg_shared_act
from p2pmicrogrid_tpu.parallel import init_shared_pol_state
from p2pmicrogrid_tpu.parallel.device_gen import device_episode_arrays
from p2pmicrogrid_tpu.parallel.scenarios import train_scenarios_chunked
from p2pmicrogrid_tpu.train import make_policy

A, S_CHUNK, K = 100, 64, 4          # 256 aggregate scenarios per episode
EPISODES, EVAL_EVERY = 120, 20
S_EVAL = 8                           # fixed held-out draws

# Measured round 3: at the DDPG default lrs (1e-4/2e-4) the chunked pooled
# update converges by episode 20 then DIVERGES after ~60 (the pooled batch
# is K*S*A*B = 102k transitions — the default step size over-drives the
# critic); at lr/4 the same run is stable through 120 episodes. The tool
# runs both so the artifact shows the failure mode and the fix.
LR_VARIANTS = (
    ("default_lr", 1e-4, 2e-4),
    ("quarter_lr", 2.5e-5, 5e-5),
)


def main() -> dict:
    return {
        "round": 3,
        "what": (
            "Greedy held-out community cost while training in CHUNKED "
            f"aggregate-scenario mode ({A} agents, {K} chunks x {S_CHUNK} "
            f"= {K * S_CHUNK} scenarios/episode, shared-critic DDPG): "
            "evidence that chunk-averaged parameter deltas learn, and where "
            "the step size must adapt to the pooled batch."
        ),
        "config": {
            "n_agents": A, "chunk_scenarios": S_CHUNK, "chunks": K,
            "episodes": EPISODES, "eval_scenarios": S_EVAL,
            "device": jax.devices()[0].device_kind,
        },
        "variants": {
            name: run_variant(alr, clr) for name, alr, clr in LR_VARIANTS
        },
    }


def run_variant(actor_lr: float, critic_lr: float) -> list:
    cfg = default_config(
        sim=SimConfig(n_agents=A, n_scenarios=S_CHUNK),
        battery=BatteryConfig(enabled=True),
        train=TrainConfig(implementation="ddpg"),
        ddpg=DDPGConfig(
            buffer_size=96, batch_size=4, share_across_agents=True,
            actor_lr=actor_lr, critic_lr=critic_lr,
            # This tool A/B-compares PINNED lrs; the pooled-batch auto rule
            # (scenarios.py:auto_scale_ddpg_lrs) must not rescale them.
            lr_auto_scale=False,
        ),
    )
    ratings = make_ratings(cfg, np.random.default_rng(42))
    ratings_j = AgentRatings(*(jnp.asarray(a) for a in ratings))
    policy = make_policy(cfg)
    params = init_shared_pol_state(cfg, jax.random.PRNGKey(0))

    # Fixed held-out evaluation scenarios (a key the training never uses).
    eval_arrays = device_episode_arrays(
        cfg, jax.random.PRNGKey(10_000), ratings, S_EVAL
    )

    @jax.jit
    def greedy_cost(params, key):
        def act_fn(p, obs_s, prev, round_key, ex):
            frac, q, _ = ddpg_shared_act(
                cfg.ddpg, p, obs_s, jnp.zeros(obs_s.shape[:2]),
                round_key, explore=False,
            )
            return frac, frac, q, ex

        k_phys, k_scan = jax.random.split(key)
        phys = jax.vmap(lambda k: init_physical(cfg, k))(
            jax.random.split(k_phys, S_EVAL)
        )
        xs = jax.tree_util.tree_map(lambda x: jnp.swapaxes(x, 0, 1), eval_arrays)
        xs = (xs.time, xs.t_out, xs.load_w, xs.pv_w,
              xs.next_time, xs.next_load_w, xs.next_pv_w)

        def slot(carry, xs_t):
            phys_s, kk = carry
            kk, k_act = jax.random.split(kk)
            phys_s, _, out, _, _ = slot_dynamics_batched(
                cfg, policy, params, phys_s, xs_t, k_act, ratings_j,
                explore=False, act_fn=act_fn,
            )
            return (phys_s, kk), (out.cost, out.reward)

        (_, _), (cost, reward) = jax.lax.scan(slot, (phys, k_scan), xs)
        # Mean per-scenario community day cost [€] and mean episode reward.
        return jnp.sum(cost, axis=(0, 2)).mean(), jnp.sum(
            jnp.mean(reward, axis=-1), axis=0
        ).mean()

    # One episode_fn + runner per variant: a fresh jit wrapper per
    # train_scenarios_chunked call would recompile the chunk program every
    # 20 episodes and fold compile time into the recorded train_secs.
    from p2pmicrogrid_tpu.parallel.scenarios import (
        make_chunked_episode_runner,
        make_shared_episode_fn,
    )

    episode_fn = make_shared_episode_fn(
        cfg, policy, None, ratings,
        arrays_fn=lambda k: device_episode_arrays(cfg, k, ratings, S_CHUNK),
        n_scenarios=S_CHUNK,
    )
    runner = make_chunked_episode_runner(cfg, episode_fn, K)

    curve = []
    c0, r0 = greedy_cost(params, jax.random.PRNGKey(1))
    curve.append({"episode": 0, "greedy_cost_eur": round(float(c0), 2),
                  "greedy_reward": round(float(r0), 1)})
    print(curve[-1], file=sys.stderr, flush=True)

    key = jax.random.PRNGKey(7)
    for start in range(0, EPISODES, EVAL_EVERY):
        params, rewards, _, secs = train_scenarios_chunked(
            cfg, policy, params, ratings, key,
            n_episodes=EVAL_EVERY, n_chunks=K, episode0=start,
            episode_fn=episode_fn, runner=runner,
        )
        c, r = greedy_cost(params, jax.random.PRNGKey(1))
        curve.append(
            {
                "episode": start + EVAL_EVERY,
                "greedy_cost_eur": round(float(c), 2),
                "greedy_reward": round(float(r), 1),
                "train_reward_mean": round(float(np.mean(rewards[-5:])), 1),
                "train_secs": round(secs, 1),
            }
        )
        print(curve[-1], file=sys.stderr, flush=True)
    return curve


if __name__ == "__main__":
    print(json.dumps(main(), indent=2))
