#!/bin/sh
# Reference-experiment outcome replication (artifacts/OUTCOMES_r03.json):
# train the reference's 2-agent tabular community (com + no-com variants,
# 1000 episodes), evaluate greedily on the test days, run both baselines,
# then the statistics battery — all through the public CLI.
#
# Usage: PYTHONPATH=/root/repo sh tools/outcomes.sh /tmp/outcomes
set -e
DIR="${1:-/tmp/outcomes}"
mkdir -p "$DIR" && cd "$DIR"
P="python -m p2pmicrogrid_tpu"
COMMON="--agents 2 --results-db r.db --model-dir m --timing-json t.json"

$P train $COMMON --episodes 1000 --jit-block 50
$P train $COMMON --episodes 1000 --jit-block 50 --no-trading
$P eval $COMMON --test
$P eval $COMMON --test --no-trading
$P baseline $COMMON --test
$P baseline $COMMON --test --kind semi-intelligent

# Scale and negotiation-round variants: population for the community-scale
# and nr-rounds Levene/ANOVA analyses (reference data_analysis.py:1378-1437).
SCALE="--agents 5 --results-db r.db --model-dir m --timing-json t.json"
$P train $SCALE --episodes 1000 --jit-block 50
$P eval $SCALE --test
ROUNDS="--agents 2 --rounds 3 --results-db r.db --model-dir m --timing-json t.json"
$P train $ROUNDS --episodes 1000 --jit-block 50
$P eval $ROUNDS --test

$P analyse --results-db r.db --figures-dir figs --timing-json t.json --model-dir m
