"""Scenario-chunk-size scaling probe for the config-4 / north-star slot.

The round-4 roofline (artifacts/ROOFLINE_r04.json) shows the factored slot
is no longer memory-bound: ~1.4 ms of the S=64 slot is a per-slot fixed
phase (tiny act matmuls, [S, A] physics vector ops, scan iteration) that
amortizes over the scenario axis. This probe measures the full shared
episode (act + factored market + physics + capped pooled learn + replay)
at A=1000 across chunk sizes S and prints scenario-env-steps/s for each —
the direct evidence for choosing the north-star chunk shape (K x S with
K*S = 10,240 fixed).

Usage: PYTHONPATH=/root/repo python tools/s_scaling_probe.py [S ...]
"""

from __future__ import annotations

import json
import sys
import time

import jax
import numpy as np


def main(sizes) -> list:
    from p2pmicrogrid_tpu.config import (
        BatteryConfig,
        DDPGConfig,
        SimConfig,
        TrainConfig,
        default_config,
    )
    from p2pmicrogrid_tpu.envs import make_ratings
    from p2pmicrogrid_tpu.parallel import init_shared_state
    from p2pmicrogrid_tpu.parallel.device_gen import device_episode_arrays
    from p2pmicrogrid_tpu.parallel.scenarios import make_shared_episode_fn
    from p2pmicrogrid_tpu.train import make_policy

    import os

    A = 1000
    buf = int(os.environ.get("PROBE_BUFFER", "96"))  # bench_northstar's ring
    key = jax.random.PRNGKey(0)
    rows = []
    for S in sizes:
        cfg = default_config(
            sim=SimConfig(n_agents=A, n_scenarios=S),
            battery=BatteryConfig(enabled=True),
            train=TrainConfig(implementation="ddpg"),
            ddpg=DDPGConfig(buffer_size=buf, batch_size=4,
                            share_across_agents=True),
        )
        ratings = make_ratings(cfg, np.random.default_rng(42))
        policy = make_policy(cfg)
        # On-device trace synthesis (the north-star transport): host-built
        # arrays at S>=256 are baked into the HLO as constants and blow the
        # remote compile service's request-size limit (HTTP 413).
        ep = make_shared_episode_fn(
            cfg, policy, None, ratings,
            arrays_fn=lambda k: device_episode_arrays(cfg, k, ratings, S),
            n_scenarios=S,
        )
        carry = init_shared_state(cfg, key)
        k = jax.random.PRNGKey(1)
        carry2, _ = ep(carry, k)  # compile
        jax.block_until_ready(jax.tree_util.tree_leaves(carry2)[0])

        best = float("inf")
        for _ in range(3):
            c = carry
            t0 = time.time()
            for i in range(4):  # chained dependent episodes, scalar sync
                c, _ = ep(c, jax.random.fold_in(k, i))
            float(jax.tree_util.tree_leaves(c)[0].sum())
            best = min(best, (time.time() - t0) / 4)

        slots = cfg.sim.slots_per_day
        steps_s = slots * S / best
        row = {
            "S": S,
            "episode_ms": round(best * 1e3, 1),
            "slot_ms": round(best * 1e3 / slots, 3),
            "scenario_env_steps_per_sec": round(steps_s),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
    return rows


if __name__ == "__main__":
    sizes = [int(a) for a in sys.argv[1:]] or [64, 128, 256, 512]
    main(sizes)
