"""Assemble the outcome-replication artifact from an outcomes.sh results DB.

``tools/outcomes.sh`` trains/evaluates the reference's experiment ladder
through the public CLI into a results DB; this script derives the committed
artifact document (mean daily community cost per setting, per-day costs,
and the statistics battery — the reference thesis's headline comparisons,
data_analysis.py:327-394,1378-1437) from that DB. Round 3 assembled the
document by hand; this makes it reproducible:

    JAX_PLATFORMS=cpu PYTHONPATH=/root/repo sh tools/outcomes.sh /tmp/outcomes
    PYTHONPATH=/root/repo python tools/outcomes_report.py /tmp/outcomes/r.db \
        --round 4 --out artifacts/OUTCOMES_r04.json
"""

from __future__ import annotations

import argparse
import json

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("db")
    ap.add_argument("--round", type=int, default=4)
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--device-note",
        default="host XLA-CPU (outcome quality is device-independent; "
        "crossover-placed per artifacts/CROSSOVER_r03.json)",
    )
    args = ap.parse_args()

    from p2pmicrogrid_tpu.analysis.stats import (
        daily_cost_table,
        statistical_tests,
    )
    from p2pmicrogrid_tpu.data import ResultsStore

    store = ResultsStore(args.db)
    table = daily_cost_table(store.get_test_results())  # [day x run-label]

    doc = {
        "round": args.round,
        "what": (
            "Reference-experiment outcome replication end-to-end through "
            "the public CLI (tools/outcomes.sh; statistics derived by "
            "tools/outcomes_report.py): the reference thesis's headline "
            "result — the RL community's daily electricity cost beats the "
            "rule-based thermostat and the price-aware semi-intelligent "
            "baselines on the held-out test days — plus the community-scale "
            "analysis (matched com-rounds-1 family) and the negotiation-"
            "rounds analysis (within the 2-agent size), at the reference's "
            "own 1000-episode budget and schedule."
        ),
        "device": args.device_note,
        "mean_daily_cost_eur_per_community": {
            s: round(float(np.mean(table[s].dropna())), 3)
            for s in table.columns
        },
        "per_day_cost_eur": {
            s: [round(float(v), 3) for v in table[s].dropna().tolist()]
            for s in table.columns
        },
        "test_days": [int(d) for d in table.index.tolist()],
        "statistics": statistical_tests(store),
    }
    text = json.dumps(doc, indent=2, default=float)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}")
    else:
        print(text)


if __name__ == "__main__":
    main()
