"""Config-5 (multi-community inter-trading) roofline + op attribution
(round-5 VERDICT #3).

Round 4 shipped one README sentence for cfg5's 366x ratio ("per-op-overhead
bound") with no committed artifact. This tool gives the 8x128 inter-trading
program the same rigor config 4 got in rounds 4-5:

1. device-op profile of the full episode program (top ops, us/slot) via the
   shared trace parser (tools/slot_profile.py);
2. in-program compile-time ablations: full vs no-inter-trading (plain
   shared episode over the community axis) vs env-only (act + physics +
   market + inter-settlement, no learning);
3. slot-unroll and episode-block sweeps on the full program.

Writes artifacts/ROOFLINE_cfg5_r05.json.

Usage: ``PYTHONPATH=/root/repo:$PYTHONPATH python tools/roofline_cfg5.py``
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo/tools")
from slot_profile import collect_device_ops  # noqa: E402

OUT = "artifacts/ROOFLINE_cfg5_r05.json"
TRACE_DIR = "/tmp/cfg5_trace"
C, A = 8, 128


def build(unroll: int = 8):
    from p2pmicrogrid_tpu.config import SimConfig, TrainConfig, default_config
    from p2pmicrogrid_tpu.envs import make_ratings
    from p2pmicrogrid_tpu.envs.multi_community import (
        make_multi_community_episode_fn,
    )
    from p2pmicrogrid_tpu.parallel import (
        init_shared_state,
        make_scenario_traces,
        stack_scenario_arrays,
    )
    from p2pmicrogrid_tpu.parallel.scenarios import make_shared_episode_fn
    from p2pmicrogrid_tpu.train import make_policy

    cfg = default_config(
        sim=SimConfig(n_agents=A, n_scenarios=C, slot_unroll=unroll),
        train=TrainConfig(implementation="tabular"),
    )
    ratings = make_ratings(cfg, np.random.default_rng(42))
    traces = make_scenario_traces(cfg)
    arrays = stack_scenario_arrays(cfg, traces, ratings)
    policy = make_policy(cfg)
    ps, scen = init_shared_state(cfg, jax.random.PRNGKey(0))
    full = make_multi_community_episode_fn(cfg, policy, arrays, ratings)
    no_inter = make_shared_episode_fn(cfg, policy, arrays, ratings)
    return cfg, policy, arrays, ratings, (ps, scen), full, no_inter


def env_only_fn(cfg, policy, arrays, ratings):
    """Act + negotiate + market + inter-community settlement + physics,
    NO learning — the ablation isolating the learn side."""
    from p2pmicrogrid_tpu.envs import init_physical
    from p2pmicrogrid_tpu.envs.community import (
        AgentRatings,
        slot_dynamics_batched,
    )
    from p2pmicrogrid_tpu.envs.multi_community import (
        make_inter_community_settlement,
    )

    ratings_j = AgentRatings(*(jnp.asarray(a) for a in ratings))
    hook = make_inter_community_settlement(cfg)

    @jax.jit
    def episode(carry, key):
        ps, scen = carry
        k_phys, k_scan = jax.random.split(key)
        phys = jax.vmap(lambda k: init_physical(cfg, k))(
            jax.random.split(k_phys, C)
        )
        xs = jax.tree_util.tree_map(lambda x: jnp.swapaxes(x, 0, 1), arrays)
        xs = (xs.time, xs.t_out, xs.load_w, xs.pv_w,
              xs.next_time, xs.next_load_w, xs.next_pv_w)

        def slot(inner, xs_t):
            phys_s, kk = inner
            kk, k_act = jax.random.split(kk)
            phys_s, _, out, _, _ = slot_dynamics_batched(
                cfg, policy, ps, phys_s, xs_t, k_act, ratings_j,
                explore=True, settlement_hook=hook,
            )
            return (phys_s, kk), jnp.mean(out.reward, axis=-1)

        (_, _), r = jax.lax.scan(
            slot, (phys, k_scan), xs, unroll=cfg.sim.slot_unroll
        )
        return carry, (jnp.sum(r, axis=0), jnp.zeros(C))

    return episode


def timed_block(episode_fn, carry, block: int = 10, repeats: int = 3):
    blocked = jax.jit(
        lambda c, k: jax.lax.scan(episode_fn, c, jax.random.split(k, block))
    )
    c, _ = blocked(carry, jax.random.PRNGKey(0))
    jax.block_until_ready(jax.tree_util.tree_leaves(c)[0])
    best = np.inf
    for i in range(repeats):
        t0 = time.time()
        c2, _ = blocked(c, jax.random.PRNGKey(1 + i))
        float(jax.tree_util.tree_leaves(c2)[0].sum())
        best = min(best, time.time() - t0)
    return best, blocked, c


def main() -> None:
    cfg, policy, arrays, ratings, carry, full, no_inter = build(unroll=8)
    slots = int(arrays.time.shape[1])
    doc = {
        "round": 5,
        "what": (
            f"Config-5 rigor: device-op profile + ablations + sweeps for "
            f"the {C}x{A} multi-community inter-trading episode program."
        ),
        "device": jax.devices()[0].device_kind,
        "config": {"communities": C, "agents": A, "slots": slots,
                   "implementation": "tabular", "slot_unroll": 8},
    }

    # --- ablations at block 10 (the bench's own measurement shape) -------
    rows = {}
    for name, fn in [
        ("full", full),
        ("no_inter_trading", no_inter),
        ("env_only", env_only_fn(cfg, policy, arrays, ratings)),
    ]:
        secs, blocked, warm = timed_block(fn, carry, block=10)
        rate = 10 * slots * C * A / secs
        rows[name] = {
            "block10_secs": round(secs, 4),
            "env_steps_per_sec": round(rate, 1),
            "slot_ms": round(1e3 * secs / (10 * slots), 4),
        }
        print(name, rows[name], flush=True)
        if name == "full":
            with jax.profiler.trace(TRACE_DIR):
                c2, _ = blocked(warm, jax.random.PRNGKey(99))
                jax.block_until_ready(jax.tree_util.tree_leaves(c2)[0])
            raw = collect_device_ops(TRACE_DIR)
            n_slots = 10 * slots
            ops = []
            for op, us in raw["durations_us"].items():
                if op.startswith("jit_"):
                    continue
                meta = raw["meta_sample"].get(op, {})
                src = meta.get("source", "")
                ops.append({
                    "op": op,
                    "us_per_slot": round(us / n_slots, 3),
                    "source": src,
                    "category": meta.get("hlo_category", ""),
                })
            ops.sort(key=lambda r: -r["us_per_slot"])
            doc["device_op_profile_top"] = ops[:30]
            doc["device_total_us_per_slot"] = round(
                sum(r["us_per_slot"] for r in ops), 2
            )
    doc["ablations_block10"] = rows
    f = rows["full"]["slot_ms"]
    doc["attribution_ms_per_slot"] = {
        "inter_trading_side": round(
            f - rows["no_inter_trading"]["slot_ms"], 4
        ),
        "learn_side": round(f - rows["env_only"]["slot_ms"], 4),
        "env_only": rows["env_only"]["slot_ms"],
    }

    # --- unroll sweep on the full program --------------------------------
    sweep = []
    for unroll in (1, 4, 8, 16):
        cfg_u, policy_u, arrays_u, ratings_u, carry_u, full_u, _ = build(unroll)
        secs, _, _ = timed_block(full_u, carry_u, block=10)
        sweep.append({
            "slot_unroll": unroll,
            "env_steps_per_sec": round(10 * slots * C * A / secs, 1),
        })
        print(sweep[-1], flush=True)
    doc["unroll_sweep_block10"] = sweep

    # --- episode-block sweep at unroll 8 ---------------------------------
    bsweep = []
    for block in (1, 10, 40):
        secs, _, _ = timed_block(full, carry, block=block)
        bsweep.append({
            "episode_block": block,
            "env_steps_per_sec": round(block * slots * C * A / secs, 1),
        })
        print(bsweep[-1], flush=True)
    doc["episode_block_sweep"] = bsweep

    with open(OUT, "w") as fjson:
        json.dump(doc, fjson, indent=2)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
