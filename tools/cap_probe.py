"""Learning-quality probe for the capped shared-DDPG update
(DDPGConfig.learn_batch_cap, parallel/scenarios.py:_ddpg_update_shared).

Round-4 throughput work capped the agent-shared pooled update — the 512k-row
pooled batch at the north star becomes a contiguous random block of `cap`
rows, and the pooled-batch lr rule keys on the EFFECTIVE (capped) batch, so
capping also raises the auto-scaled lrs (sqrt(400/cap) vs sqrt(400/512k)).
That changes the training dynamics, so the throughput win (cfg4 measured
28.2k -> 39.9k env-steps/s at cap 32768, 54.8k at 8192) must be paired with
learning evidence. This probe re-runs the K=4-chunk north-star proxy of
artifacts/LEARNING_northstar_seeds_r04.json (1000 agents, 4 x 128 scenarios
per episode — the same per-update dynamics as the K=80 flagship at 1/20 the
cost) across the same 3 seeds at candidate caps, tracking greedy held-out
community cost.

Comparison anchors (uncapped, from LEARNING_northstar_seeds_r04.json):
seed 0 falls 3058->1464, seed 2 falls 3159->836, seed 1 peaks ~6.1k at
episode 60 then recovers to ~3.0k by episode 120.

Writes artifacts/LEARNING_cap_probe_r04.json incrementally.

Usage: PYTHONPATH=/root/repo python tools/cap_probe.py [cap ...]
       (default caps: 32768 8192)
"""

from __future__ import annotations

import json
import sys

import jax
import jax.numpy as jnp
import numpy as np

from p2pmicrogrid_tpu.config import (
    BatteryConfig,
    DDPGConfig,
    SimConfig,
    TrainConfig,
    default_config,
)
from p2pmicrogrid_tpu.envs import init_physical, make_ratings
from p2pmicrogrid_tpu.envs.community import AgentRatings, slot_dynamics_batched
from p2pmicrogrid_tpu.models.ddpg import ddpg_shared_act
from p2pmicrogrid_tpu.parallel import init_shared_pol_state
from p2pmicrogrid_tpu.parallel.device_gen import device_episode_arrays
from p2pmicrogrid_tpu.parallel.scenarios import (
    auto_scale_ddpg_lrs,
    ddpg_pooled_batch,
    make_chunked_episode_runner,
    make_shared_episode_fn,
    train_scenarios_chunked,
)
from p2pmicrogrid_tpu.train import make_policy

A, S_CHUNK, K = 1000, 128, 4
EPISODES, EVAL_EVERY = 120, 20
S_EVAL = 8
SEEDS = (0, 1, 2)
OUT = "artifacts/LEARNING_cap_probe_r04.json"


def make_cfg(cap):
    return default_config(
        sim=SimConfig(n_agents=A, n_scenarios=S_CHUNK, market_dtype="bfloat16"),
        battery=BatteryConfig(enabled=True),
        train=TrainConfig(implementation="ddpg"),
        ddpg=DDPGConfig(
            buffer_size=96, batch_size=4, share_across_agents=True,
            learn_batch_cap=cap,
        ),
    )


def main() -> None:
    caps = [int(x) for x in sys.argv[1:]] or [32768, 8192]
    doc = {
        "round": 4,
        "what": (
            f"Greedy held-out community cost, K={K}-chunk north-star proxy "
            f"({A} agents, {K}x{S_CHUNK} scenarios/episode, shared-critic "
            "DDPG, bf16 market, default lr rule) with the CAPPED pooled "
            "update at each candidate learn_batch_cap, across the 3 seeds "
            "of LEARNING_northstar_seeds_r04.json. Uncapped anchors: seed0 "
            "3058->1464, seed2 3159->836, seed1 excursion to ~6.1k@ep60 "
            "recovering to ~3.0k@ep120."
        ),
        "config": {
            "n_agents": A, "chunk_scenarios": S_CHUNK, "chunks": K,
            "episodes": EPISODES, "eval_scenarios": S_EVAL,
            "uncapped_pool": 4 * S_CHUNK * A,
        },
        "by_cap": {},
    }

    ratings = make_ratings(make_cfg(None), np.random.default_rng(42))
    ratings_j = AgentRatings(*(jnp.asarray(a) for a in ratings))
    policy = make_policy(make_cfg(None))

    for cap in caps:
        cfg = make_cfg(cap)
        eff = auto_scale_ddpg_lrs(cfg, S_CHUNK)
        entry = {
            "effective_batch": ddpg_pooled_batch(cfg, S_CHUNK),
            "effective_actor_lr": eff.ddpg.actor_lr,
            "effective_critic_lr": eff.ddpg.critic_lr,
            "by_seed": {},
        }
        doc["by_cap"][str(cap)] = entry

        eval_arrays = device_episode_arrays(
            cfg, jax.random.PRNGKey(10_000), ratings, S_EVAL
        )

        @jax.jit
        def greedy_cost(params, key):
            def act_fn(p, obs_s, prev, round_key, ex):
                frac, q, _ = ddpg_shared_act(
                    cfg.ddpg, p, obs_s, jnp.zeros(obs_s.shape[:2]),
                    round_key, explore=False,
                )
                return frac, frac, q, ex

            k_phys, k_scan = jax.random.split(key)
            phys = jax.vmap(lambda k: init_physical(cfg, k))(
                jax.random.split(k_phys, S_EVAL)
            )
            xs = jax.tree_util.tree_map(
                lambda x: jnp.swapaxes(x, 0, 1), eval_arrays
            )
            xs = (xs.time, xs.t_out, xs.load_w, xs.pv_w,
                  xs.next_time, xs.next_load_w, xs.next_pv_w)

            def slot(carry, xs_t):
                phys_s, kk = carry
                kk, k_act = jax.random.split(kk)
                phys_s, _, out, _, _ = slot_dynamics_batched(
                    cfg, policy, params, phys_s, xs_t, k_act, ratings_j,
                    explore=False, act_fn=act_fn,
                )
                return (phys_s, kk), out.cost

            (_, _), cost = jax.lax.scan(slot, (phys, k_scan), xs)
            return jnp.sum(cost, axis=(0, 2)).mean()

        episode_fn = make_shared_episode_fn(
            cfg, policy, None, ratings,
            arrays_fn=lambda k: device_episode_arrays(cfg, k, ratings, S_CHUNK),
            n_scenarios=S_CHUNK,
        )
        runner = make_chunked_episode_runner(cfg, episode_fn, K)

        for seed in SEEDS:
            params = init_shared_pol_state(cfg, jax.random.PRNGKey(seed))
            curve = []
            entry["by_seed"][str(seed)] = curve

            def record(ep):
                c = float(greedy_cost(params, jax.random.PRNGKey(1)))
                curve.append({"episode": ep, "greedy_cost_eur": round(c)})
                print(f"cap={cap} seed={seed} ep={ep}: {c:.0f}",
                      file=sys.stderr, flush=True)
                with open(OUT, "w") as f:
                    json.dump(doc, f, indent=2)

            record(0)
            # Same key chain as the seeds artifact's probes.
            key = (
                jax.random.PRNGKey(7)
                if seed == 0
                else jax.random.fold_in(jax.random.PRNGKey(7), seed)
            )
            for start in range(0, EPISODES, EVAL_EVERY):
                params, _, _, _ = train_scenarios_chunked(
                    cfg, policy, params, ratings, key,
                    n_episodes=EVAL_EVERY, n_chunks=K, episode0=start,
                    episode_fn=episode_fn, runner=runner,
                )
                record(start + EVAL_EVERY)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
