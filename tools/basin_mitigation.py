"""Measured basin mitigation at the north-star scale (round-5 VERDICT #1).

Reruns the committed seed-2 basin run (artifacts/
LEARNING_northstar_r04b_seed2_full.json: capture by the don't-heat basin
from ~episode 40, escape only at ~episode 200-220) through the SHIPPED
health surface (train/health.py:train_chunked_with_health) with
``mitigate="lr-boost"``: identical config and key chain (the block-wise
trainer folds absolute episode indices; note the round-5 slot rewrite
changes f32 summation order, so trajectories match the committed run
statistically rather than bit-for-bit), and once the monitor flags the
basin the episode program with lrs x BOOST trains until the greedy policy
recovers.

Claim under test: detection fires within one 10-episode eval period of
entry (~episode 30-40), and the boosted program escapes the basin
measurably sooner than the unmitigated ~170-episode dwell.

Usage: ``PYTHONPATH=/root/repo:$PYTHONPATH python tools/basin_mitigation.py
[EPISODES] [OUT] [SEED]`` — env knobs: ``NS_LR_BOOST`` (default 3.0),
``NS_MITIGATE`` (default lr-boost).
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import numpy as np

from p2pmicrogrid_tpu.config import (
    BatteryConfig,
    DDPGConfig,
    SimConfig,
    TrainConfig,
    default_config,
)
from p2pmicrogrid_tpu.envs import make_ratings
from p2pmicrogrid_tpu.parallel import init_shared_pol_state
from p2pmicrogrid_tpu.parallel.scenarios import auto_scale_ddpg_lrs
from p2pmicrogrid_tpu.train import make_policy
from p2pmicrogrid_tpu.train.health import (
    HealthMonitor,
    train_chunked_with_health,
)

A, S_CHUNK, K = 1000, 128, 80
EPISODES, EVAL_EVERY, S_EVAL = 200, 10, 8
OUT = "artifacts/BASIN_MITIGATION_r05.json"
SEED = 2


def main() -> None:
    global EPISODES, OUT, SEED
    args = sys.argv[1:]
    if len(args) >= 1:
        EPISODES = int(args[0])
    if len(args) >= 2:
        OUT = args[1]
    if len(args) >= 3:
        SEED = int(args[2])
    boost = float(os.environ.get("NS_LR_BOOST", "3.0"))
    mitigate = os.environ.get("NS_MITIGATE", "lr-boost")

    cfg = default_config(
        sim=SimConfig(n_agents=A, n_scenarios=S_CHUNK, market_dtype="bfloat16"),
        battery=BatteryConfig(enabled=True),
        train=TrainConfig(implementation="ddpg"),
        ddpg=DDPGConfig(buffer_size=96, batch_size=4, share_across_agents=True),
    )
    eff = auto_scale_ddpg_lrs(cfg)
    doc = {
        "round": 5,
        "what": (
            f"Seed-{SEED} north-star rerun through the shipped health "
            f"surface with mitigate={mitigate!r} (lr x {boost} while in "
            "basin). Reference dwell without mitigation: "
            "artifacts/LEARNING_northstar_r04b_seed2_full.json (flagged "
            "~ep 30-40, escape ~ep 200-220)."
        ),
        "config": {
            "n_agents": A, "chunk_scenarios": S_CHUNK, "chunks": K,
            "episodes": EPISODES, "eval_every": EVAL_EVERY,
            "eval_scenarios": S_EVAL, "seed": SEED,
            "mitigate": mitigate, "lr_boost": boost,
            "effective_actor_lr": eff.ddpg.actor_lr,
            "effective_critic_lr": eff.ddpg.critic_lr,
            "learn_batch_cap": cfg.ddpg.learn_batch_cap,
            "device": jax.devices()[0].device_kind,
        },
        "curve": [],
    }

    ratings = make_ratings(cfg, np.random.default_rng(42))
    policy = make_policy(cfg)
    params = init_shared_pol_state(cfg, jax.random.PRNGKey(SEED))
    monitor = HealthMonitor(cfg.sim.slots_per_day)

    t0 = time.time()

    def health_cb(point):
        row = point._asdict()
        row["wall_s"] = round(time.time() - t0, 1)
        doc["curve"].append(row)
        doc["basin_entries"] = monitor.basin_entries
        doc["basin_exits"] = monitor.basin_exits
        print(row, file=sys.stderr, flush=True)
        with open(OUT, "w") as f:
            json.dump(doc, f, indent=2)

    # Same key chain as tools/learning_northstar.py for this seed.
    key = (
        jax.random.PRNGKey(7)
        if SEED == 0
        else jax.random.fold_in(jax.random.PRNGKey(7), SEED)
    )
    params, rewards, _, secs, monitor = train_chunked_with_health(
        cfg, policy, params, ratings, key,
        n_episodes=EPISODES, n_chunks=K, eval_every=EVAL_EVERY,
        mitigate=mitigate, lr_boost=boost, monitor=monitor,
        health_cb=health_cb, s_eval=S_EVAL,
    )
    doc["train_secs"] = round(secs, 1)
    dwell = None
    if monitor.basin_entries:
        exit_ep = (
            monitor.basin_exits[0]
            if monitor.basin_exits
            else EPISODES
        )
        dwell = exit_ep - monitor.basin_entries[0]
    doc["dwell_episodes"] = dwell
    doc["reference_dwell_episodes"] = 170
    with open(OUT, "w") as f:
        json.dump(doc, f, indent=2)
    print(f"wrote {OUT}; dwell={dwell}")


if __name__ == "__main__":
    main()
