"""Measured CPU/TPU crossover for small sequential communities.

Round-2 VERDICT: benchmark configs 1-2 (2-agent tabular, 10-agent
actor-critic) report host-CPU numbers because toy sequential programs cannot
fill the chip — but no measured crossover backed that placement. This script
runs the SAME jitted single-scenario training program
(benchmarks.single_community_steps_per_sec) on both backends across community
sizes and emits the crossover table for ``artifacts/``.

``--serve`` measures the SERVING crossover instead: the padded-bucket
``PolicyEngine.act`` program over (n_agents, max_batch) on both backends.
The training table is a B=1 sequential measurement and says nothing about
whether a 64-wide padded serve bucket fills the chip; the committed
``artifacts/CROSSOVER_SERVE_r0X.json`` capture is what
``train.placement.pick_serve_device`` consults for batch-width-aware
auto-placement.

Usage: ``PYTHONPATH=/root/repo python tools/crossover.py [--serve]``
"""

from __future__ import annotations

import argparse
import json
import tempfile
import time

import jax

from p2pmicrogrid_tpu.benchmarks import single_community_steps_per_sec

SIZES_TABULAR = (2, 10, 50, 100, 250)
SIZES_DDPG = (10, 50, 100)

# Serve sweep: community sizes x coalescing caps (powers of two — the
# engine's bucket grid). max_batch IS the widest padded bucket the engine
# compiles, so measuring the full bucket measures the worst-case program.
SERVE_SIZES = (2, 10, 100)
SERVE_BATCHES = (1, 8, 64)
SERVE_REPEATS = 30


def _serve_engine(implementation: str, n_agents: int, max_batch: int, device):
    """A fresh-init engine for the sweep, pinned to ``device``."""
    from p2pmicrogrid_tpu.config import SimConfig, TrainConfig, default_config
    from p2pmicrogrid_tpu.serve import PolicyEngine, export_policy_bundle
    from p2pmicrogrid_tpu.train import init_policy_state

    cfg = default_config(
        sim=SimConfig(n_agents=n_agents),
        train=TrainConfig(implementation=implementation),
    )
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    bundle = export_policy_bundle(cfg, ps, tempfile.mkdtemp(prefix="xover-"))
    engine = PolicyEngine(
        bundle_dir=bundle, max_batch=max_batch,
        device="cpu" if device.platform == "cpu" else "default",
    )
    engine.warmup([max_batch], include_step=False)
    return engine


def _serve_batches_per_sec(engine, max_batch: int) -> float:
    import numpy as np

    obs = np.zeros((max_batch, engine.n_agents, 4), dtype=np.float32)
    engine.act(obs)  # one extra warm call outside the timed window
    t0 = time.perf_counter()
    for _ in range(SERVE_REPEATS):
        engine.act(obs)
    return SERVE_REPEATS / (time.perf_counter() - t0)


def serve_main() -> dict:
    """The (n_agents, max_batch) padded-batch serve crossover sweep.

    On a host WITHOUT an accelerator the sweep still runs — both placements
    resolve to host XLA-CPU, the honest ratio is ~1.0, and the capture is
    marked ``accelerator: false`` so ``train/placement.py`` only trusts it
    when the serving process itself runs on the CPU backend (a host-only
    capture says nothing about where a TPU host should place a bucket; the
    TPU capture stays ROADMAP measurement debt). Committing it exercises
    the crossover-table loader end to end, which had been live with nothing
    to read since the gateway round.
    """
    accel = jax.devices()[0]
    cpu = jax.devices("cpu")[0]
    has_accel = accel.platform != "cpu"
    if not has_accel:
        accel = cpu
        print(
            "crossover --serve: no accelerator backend; measuring the "
            "host-only sweep (both placements = XLA-CPU, accelerator: "
            "false in the capture)",
            flush=True,
        )

    rows = []
    for impl in ("tabular", "ddpg"):
        for a in SERVE_SIZES:
            for b in SERVE_BATCHES:
                r_cpu = _serve_batches_per_sec(
                    _serve_engine(impl, a, b, cpu), b
                )
                r_tpu = _serve_batches_per_sec(
                    _serve_engine(impl, a, b, accel), b
                )
                rows.append(
                    {
                        "implementation": impl,
                        "n_agents": a,
                        "max_batch": b,
                        "cpu_batches_per_sec": round(r_cpu, 1),
                        "tpu_batches_per_sec": round(r_tpu, 1),
                        "tpu_over_cpu": round(r_tpu / r_cpu, 3),
                        "winner": "tpu" if r_tpu > r_cpu else "cpu",
                    }
                )
                print(
                    f"{impl} A={a} B={b}: cpu {r_cpu:.0f} vs "
                    f"{accel.platform} {r_tpu:.0f} batches/s "
                    f"({r_tpu / r_cpu:.2f}x)",
                    flush=True,
                )

    doc = {
        "what": (
            "padded-bucket PolicyEngine.act placed on each backend; one "
            "full max_batch bucket per call, fresh-init bundles, "
            f"{SERVE_REPEATS} timed calls after warmup"
            + (
                "" if has_accel else
                " — HOST-ONLY capture: no accelerator was present, both "
                "placements ran on XLA-CPU (placement ignores this table "
                "on accelerator hosts)"
            )
        ),
        "kind": "serve_crossover",
        "accelerator": has_accel,
        "device": jax.devices()[0].device_kind,
        "rows": rows,
    }
    print(json.dumps(doc, indent=2))
    return doc


def main() -> dict:
    tpu = jax.devices()[0]
    cpu = jax.devices("cpu")[0]
    assert tpu.platform != "cpu", "run this on a TPU host"

    rows = []
    for impl, sizes in (("tabular", SIZES_TABULAR), ("ddpg", SIZES_DDPG)):
        for a in sizes:
            r_cpu = single_community_steps_per_sec(a, impl, device=cpu)
            r_tpu = single_community_steps_per_sec(a, impl, device=tpu)
            rows.append(
                {
                    "implementation": impl,
                    "n_agents": a,
                    "cpu_steps_per_sec": round(r_cpu, 1),
                    "tpu_steps_per_sec": round(r_tpu, 1),
                    "tpu_over_cpu": round(r_tpu / r_cpu, 2),
                    "winner": "tpu" if r_tpu > r_cpu else "cpu",
                }
            )
            print(
                f"{impl} A={a}: cpu {r_cpu:.0f} vs tpu {r_tpu:.0f} "
                f"({r_tpu / r_cpu:.2f}x)",
                flush=True,
            )

    crossover = {}
    for impl in ("tabular", "ddpg"):
        sizes = [r["n_agents"] for r in rows if r["implementation"] == impl]
        winners = [r["winner"] for r in rows if r["implementation"] == impl]
        above = [a for a, w in zip(sizes, winners) if w == "tpu"]
        crossover[impl] = min(above) if above else f"> {max(sizes)}"

    doc = {
        "what": (
            "same jitted single-scenario training program placed on each "
            "backend; one sequential community, 96-slot day, "
            "20-episode fused blocks"
        ),
        "device": jax.devices()[0].device_kind,
        "rows": rows,
        "tpu_wins_from_n_agents": crossover,
    }
    print(json.dumps(doc, indent=2))
    return doc


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--serve", action="store_true",
        help="measure the padded-batch SERVE crossover over "
             "(n_agents, max_batch) instead of the training crossover "
             "(emit as artifacts/CROSSOVER_SERVE_r0X.json)",
    )
    if parser.parse_args().serve:
        serve_main()
    else:
        main()
