"""Measured CPU/TPU crossover for small sequential communities.

Round-2 VERDICT: benchmark configs 1-2 (2-agent tabular, 10-agent
actor-critic) report host-CPU numbers because toy sequential programs cannot
fill the chip — but no measured crossover backed that placement. This script
runs the SAME jitted single-scenario training program
(benchmarks.single_community_steps_per_sec) on both backends across community
sizes and emits the crossover table for ``artifacts/``.

Usage: ``PYTHONPATH=/root/repo python tools/crossover.py``
"""

from __future__ import annotations

import json

import jax

from p2pmicrogrid_tpu.benchmarks import single_community_steps_per_sec

SIZES_TABULAR = (2, 10, 50, 100, 250)
SIZES_DDPG = (10, 50, 100)


def main() -> dict:
    tpu = jax.devices()[0]
    cpu = jax.devices("cpu")[0]
    assert tpu.platform != "cpu", "run this on a TPU host"

    rows = []
    for impl, sizes in (("tabular", SIZES_TABULAR), ("ddpg", SIZES_DDPG)):
        for a in sizes:
            r_cpu = single_community_steps_per_sec(a, impl, device=cpu)
            r_tpu = single_community_steps_per_sec(a, impl, device=tpu)
            rows.append(
                {
                    "implementation": impl,
                    "n_agents": a,
                    "cpu_steps_per_sec": round(r_cpu, 1),
                    "tpu_steps_per_sec": round(r_tpu, 1),
                    "tpu_over_cpu": round(r_tpu / r_cpu, 2),
                    "winner": "tpu" if r_tpu > r_cpu else "cpu",
                }
            )
            print(
                f"{impl} A={a}: cpu {r_cpu:.0f} vs tpu {r_tpu:.0f} "
                f"({r_tpu / r_cpu:.2f}x)",
                flush=True,
            )

    crossover = {}
    for impl in ("tabular", "ddpg"):
        sizes = [r["n_agents"] for r in rows if r["implementation"] == impl]
        winners = [r["winner"] for r in rows if r["implementation"] == impl]
        above = [a for a, w in zip(sizes, winners) if w == "tpu"]
        crossover[impl] = min(above) if above else f"> {max(sizes)}"

    doc = {
        "what": (
            "same jitted single-scenario training program placed on each "
            "backend; one sequential community, 96-slot day, "
            "20-episode fused blocks"
        ),
        "device": jax.devices()[0].device_kind,
        "rows": rows,
        "tpu_wins_from_n_agents": crossover,
    }
    print(json.dumps(doc, indent=2))
    return doc


if __name__ == "__main__":
    main()
