"""``python -m p2pmicrogrid_tpu`` — the CLI entry point (the reference's
``microgrid/__main__.py`` is an empty file; SURVEY.md section 1)."""

import sys

from p2pmicrogrid_tpu.cli import main

sys.exit(main())
