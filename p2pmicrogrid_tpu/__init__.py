"""p2pmicrogrid_tpu — a TPU-native P2P electricity-trading community framework.

A ground-up JAX/XLA re-design of the capabilities of Simencassiman/P2PMicrogrid
(reference mounted at /root/reference): prosumer agents (household load + PV +
battery + 2R2C heat-pump thermal model) learn — tabular Q, DQN, or DDPG-style
actor-critic — to schedule heat-pump power and trade energy at negotiated P2P
prices against a sinusoidal time-of-use grid tariff.

Architectural stance (vs. the reference's eager, object-per-agent TensorFlow):

* All simulation state is one explicit PyTree (struct-of-arrays); agents are a
  batch axis, Monte-Carlo scenarios a second batch axis.
* The whole community step — multi-round price negotiation, pairwise market
  clearing, asset dynamics, rewards, and per-slot learning — is a single pure
  function; an episode is ``jax.lax.scan`` over time slots; everything compiles
  into one XLA program.
* Scenarios shard over a ``jax.sharding.Mesh`` (ICI all-reduce for shared
  parameters), scaling to 1000-agent x 10k-scenario training.

Layer map (mirrors SURVEY.md section 1 of the parent repo):

* ``config``    — typed experiment configuration (reference: microgrid/setup.py)
* ``data``      — trace ingestion/synthesis + results store (dataset.py, database.py)
* ``ops``       — pure physics/market math (heating.py, storage.py, community.py)
* ``models``    — policies as pure functions over batched params (rl.py, ml.py)
* ``envs``      — the community simulator (community.py, environment.py)
* ``train``     — training loops and replay (rl.py Trainer, community.main)
* ``parallel``  — mesh/sharding utilities (no reference analogue; TPU-native)
* ``analysis``  — post-run reporting (data_analysis.py)
"""

__version__ = "0.3.0"
