"""Thesis-figure plotting utilities.

Reference: data_analysis.py's figure factory — learning curves (:697-772),
cost comparisons across settings (:324-417), per-day state/decision traces
(:420-694), round-by-round decision comparison (:997-1096), and Q-table
visualization (:1214-1297). All functions return matplotlib Figures and never
call ``plt.show()``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def _plt():
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    return plt


def plot_learning_curves(progress_df, settings: Optional[Sequence[str]] = None):
    """Reward / TD-error training curves (data_analysis.py:697-772).

    ``progress_df``: the ``training_progress`` table (ResultsStore).
    """
    plt = _plt()
    df = progress_df
    if settings is not None:
        df = df[df["setting"].isin(list(settings))]
    fig, axes = plt.subplots(1, 2, figsize=(12, 4))
    for (setting, impl), g in df.groupby(["setting", "implementation"]):
        g = g.sort_values("episode")
        axes[0].plot(g["episode"], g["reward"], label=f"{setting} ({impl})")
        axes[1].plot(g["episode"], g["error"], label=f"{setting} ({impl})")
    axes[0].set_xlabel("Episode")
    axes[0].set_ylabel("Average reward")
    axes[0].set_title("Training reward")
    axes[1].set_xlabel("Episode")
    axes[1].set_ylabel("Average error")
    axes[1].set_title("Training error")
    axes[0].legend(fontsize=7)
    fig.tight_layout()
    return fig


def plot_cost_comparison(test_df, settings: Optional[Sequence[str]] = None):
    """Average daily cost per setting, with per-day spread
    (data_analysis.py:324-417)."""
    from p2pmicrogrid_tpu.analysis.stats import daily_cost_table

    plt = _plt()
    df = test_df
    if settings is not None:
        df = df[df["setting"].isin(list(settings))]
    daily = daily_cost_table(df).reset_index().melt(
        id_vars="day", var_name="setting", value_name="cost"
    )
    order = sorted(daily["setting"].unique())
    means = [daily.loc[daily["setting"] == s, "cost"].mean() for s in order]
    stds = [daily.loc[daily["setting"] == s, "cost"].std() for s in order]
    fig, ax = plt.subplots(figsize=(max(6, len(order) * 1.2), 4))
    ax.bar(range(len(order)), means, 0.6, yerr=stds, capsize=4)
    ax.set_xticks(range(len(order)))
    ax.set_xticklabels(order, rotation=20, ha="right", fontsize=8)
    ax.set_ylabel("Avg daily cost per agent [€]")
    ax.set_title("Cost comparison")
    fig.tight_layout()
    return fig


def plot_day_traces(test_df, setting: str, day: int, comfort_bounds=(20.0, 22.0)):
    """Per-slot load/pv/temperature/heat-pump/cost traces for one day
    (data_analysis.py:420-694)."""
    plt = _plt()
    df = test_df[(test_df["setting"] == setting) & (test_df["day"] == day)]
    fig, axes = plt.subplots(4, 1, figsize=(9, 11), sharex=True)
    for agent, g in df.groupby("agent"):
        g = g.sort_values("time")
        t = g["time"] * 24
        axes[0].plot(t, g["load"] * 1e-3, label=f"agent {agent}")
        axes[0].plot(t, g["pv"] * 1e-3, "--", alpha=0.6)
        axes[1].plot(t, g["temperature"])
        axes[2].plot(t, g["heatpump"] * 1e-3)
        axes[3].plot(t, g["cost"].cumsum())
    axes[0].set_ylabel("Load / PV [kW]")
    axes[0].legend(fontsize=7)
    axes[1].set_ylabel("T indoor [°C]")
    axes[1].axhspan(*comfort_bounds, alpha=0.15, color="green")
    axes[2].set_ylabel("Heat pump [kW]")
    axes[3].set_ylabel("Cumulative cost [€]")
    axes[3].set_xlabel("Time [h]")
    fig.suptitle(f"{setting} — day {day}")
    fig.tight_layout()
    return fig


def plot_rounds_decisions(rounds_df, setting: str, day: int):
    """Round-by-round heat-pump decisions (data_analysis.py:997-1096)."""
    plt = _plt()
    df = rounds_df[(rounds_df["setting"] == setting) & (rounds_df["day"] == day)]
    agents = sorted(df["agent"].unique())
    fig, axes = plt.subplots(len(agents), 1, figsize=(9, 2.5 * len(agents)), sharex=True, squeeze=False)
    for ax, agent in zip(axes[:, 0], agents):
        g = df[df["agent"] == agent]
        for rnd, gg in g.groupby("round"):
            gg = gg.sort_values("time")
            ax.step(gg["time"] * 24, gg["decision"] * 1e-3, where="post", label=f"round {rnd}")
        ax.set_ylabel(f"agent {agent} [kW]")
        ax.legend(fontsize=7)
    axes[-1, 0].set_xlabel("Time [h]")
    fig.suptitle(f"Per-round decisions — {setting}, day {day}")
    fig.tight_layout()
    return fig


def plot_sweep_curves(sweep_df, metric: str = "training"):
    """Hyperparameter-sweep curves from the ``hyperparameters_single_day``
    table (the reference's DDPG sweep figures, data_analysis.py:1460-1629):
    one line per (settings, trial), episode on x."""
    plt = _plt()
    fig, ax = plt.subplots(figsize=(9, 4.5))
    for (settings, trial), g in sweep_df.groupby(["settings", "trial"]):
        g = g.sort_values("episode")
        ax.plot(g["episode"], g[metric], label=f"{settings} #{trial}", alpha=0.8)
    ax.set_xlabel("Episode")
    ax.set_ylabel(metric)
    ax.set_title(f"Hyperparameter sweep — {metric}")
    ax.legend(fontsize=6)
    fig.tight_layout()
    return fig


def plot_qtable_heatmap(q_table: np.ndarray):
    """Greedy-policy heatmap over (time, temperature), marginalizing the
    balance/p2p state dims (data_analysis.py:1214-1297).

    q_table: one agent's table [nt, ntemp, nb, np2p, n_actions].
    """
    plt = _plt()
    q = np.asarray(q_table)
    # Marginalize balance/p2p by averaging Q before the argmax.
    q2 = q.mean(axis=(2, 3))  # [nt, ntemp, n_actions]
    greedy = q2.argmax(axis=-1)
    fig, axes = plt.subplots(1, 2, figsize=(11, 4))
    im0 = axes[0].pcolormesh(greedy.T, cmap="viridis")
    axes[0].set_title("Greedy action (0=off, 2=full)")
    axes[0].set_xlabel("Time bin")
    axes[0].set_ylabel("Temperature bin")
    fig.colorbar(im0, ax=axes[0])
    im1 = axes[1].pcolormesh(q2.max(axis=-1).T, cmap="magma")
    axes[1].set_title("Max Q-value")
    axes[1].set_xlabel("Time bin")
    fig.colorbar(im1, ax=axes[1])
    fig.tight_layout()
    return fig
