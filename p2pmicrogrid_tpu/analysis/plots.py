"""Thesis-figure plotting utilities.

Reference: data_analysis.py's figure factory — learning curves (:697-772),
cost comparisons across settings (:324-417), per-day state/decision traces
(:420-694), round-by-round decision comparison (:997-1096), and Q-table
visualization (:1214-1297). All functions return matplotlib Figures and never
call ``plt.show()``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def _plt():
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    return plt


def _parse_setting(setting: str):
    """(n_agents, rounds) from a community setting string
    ('{n}-multi-agent-com-rounds-{r}-...', community.py:423); rounds is None
    for no-com / unparsable settings."""
    import re

    m = re.match(r"^(\d+)-multi-agent-com-rounds-(\d+)", setting)
    if m:
        return int(m.group(1)), int(m.group(2))
    m = re.match(r"^(\d+)-multi-agent-no-com", setting)
    if m:
        return int(m.group(1)), None
    return None, None


def plot_scaling(timing: dict, phase: str = "train"):
    """Computation-time scaling figures (data_analysis.py:775-845): wall-clock
    vs community size (one line per negotiation-round count) and vs rounds
    (one line per community size), from the per-setting timing JSON the CLI
    writes (--timing-json; the reference's save_times, community.py:324-338).
    """
    plt = _plt()
    points = []  # (n, rounds, seconds)
    for setting, phases in timing.items():
        if phase not in phases:
            continue
        n, r = _parse_setting(setting)
        if n is None or r is None:
            continue
        points.append((n, r, float(phases[phase])))
    fig, axes = plt.subplots(1, 2, figsize=(12, 4))
    by_rounds = {}
    by_n = {}
    for n, r, s in sorted(points):
        by_rounds.setdefault(r, []).append((n, s))
        by_n.setdefault(n, []).append((r, s))
    for r, xs in sorted(by_rounds.items()):
        axes[0].plot(*zip(*sorted(xs)), marker="o", label=f"{r} round(s)")
    for n, xs in sorted(by_n.items()):
        axes[1].plot(*zip(*sorted(xs)), marker="o", label=f"{n} agents")
    axes[0].set_xlabel("Community size [agents]")
    axes[1].set_xlabel("Negotiation rounds")
    for ax in axes:
        ax.set_ylabel(f"{phase} wall-clock [s]")
        if ax.lines:
            ax.legend()
    fig.tight_layout()
    return fig


def plot_cost_vs_community_size(results_df):
    """Average daily cost per agent vs community size
    (data_analysis.py:775-806's cost-scaling companion).

    Built on ``stats.daily_cost_table`` so runs keep their (setting,
    implementation) identity, and split into one line per experiment
    condition (rounds-r / no-com, per implementation) — com and no-com
    communities of the same size are different experiments and must not
    average into one point.
    """
    import re

    from p2pmicrogrid_tpu.analysis.stats import daily_cost_table

    plt = _plt()
    fig, ax = plt.subplots(figsize=(7, 4))
    daily = daily_cost_table(results_df)  # [day x run-label]
    lines = {}  # condition -> [(n, mean cost)]
    for label in daily.columns:
        setting = label.split("[")[0]
        impl = re.search(r"\[([^\]]+)\]$", label)
        n, r = _parse_setting(setting)
        if n is None:
            continue
        cond = f"rounds-{r}" if r is not None else "no-com"
        if impl:
            cond += f" [{impl.group(1)}]"
        lines.setdefault(cond, []).append((n, float(daily[label].mean())))
    for cond, xs in sorted(lines.items()):
        ax.plot(*zip(*sorted(xs)), marker="o", label=cond)
    ax.set_xlabel("Community size [agents]")
    ax.set_ylabel("Avg daily cost per agent [EUR]")
    if ax.lines:
        ax.legend()
    fig.tight_layout()
    return fig


def plot_pv_drop_comparison(results_df, com_setting: str, nocom_setting: str):
    """The PV-drop fault comparison (data_analysis.py:1099-1211): for the
    affected runs ('{n}-agent-{i}-pv-drop-{com,no-com}' settings), the
    communicating community absorbs the lost production through P2P trades
    while the isolated one buys at the tariff — visible in per-slot PV,
    cumulative cost, and indoor temperature of the dropped agent.

    ``results_df``: validation or test results table; the dropped agent index
    is parsed from the setting name.
    """
    import re

    plt = _plt()
    m = re.match(r"^\d+-agent-(\d+)-pv-drop", com_setting)
    agent = int(m.group(1)) if m else 0

    fig, axes = plt.subplots(3, 1, figsize=(12, 8), sharex=True)
    for setting, label in ((com_setting, "com"), (nocom_setting, "no-com")):
        g = results_df[
            (results_df["setting"] == setting) & (results_df["agent"] == agent)
        ]
        if g.empty:
            continue
        # One run only: a second implementation stored under the same setting
        # would interleave rows and double-count the cumulative cost.
        impl = sorted(g["implementation"].unique())[0]
        g = g[g["implementation"] == impl]
        day = g["day"].min()
        g = g[g["day"] == day].sort_values("time")
        hours = g["time"].to_numpy() * 24
        axes[0].plot(hours, g["pv"].to_numpy() / 1e3, label=label)
        axes[1].plot(hours, g["cost"].cumsum().to_numpy(), label=label)
        axes[2].plot(hours, g["temperature"].to_numpy(), label=label)
    axes[0].set_ylabel("PV [kW]")
    axes[1].set_ylabel("Cumulative cost [EUR]")
    axes[2].set_ylabel("Indoor T [degC]")
    axes[2].set_xlabel("Hour")
    axes[2].axhspan(20, 22, alpha=0.15, color="green")
    for ax in axes:
        if ax.lines:
            ax.legend()
    fig.suptitle(f"PV drop on agent {agent}: communicating vs isolated")
    fig.tight_layout()
    return fig


def plot_forecast(slot_hours, pred_load, pred_pv, target_load, target_pv):
    """Predicted vs actual normalized load/PV over the validation timeline —
    the reference's forecaster result plot (ml.py:287-308)."""
    plt = _plt()
    fig, axes = plt.subplots(2, 1, figsize=(12, 6), sharex=True)
    for ax, pred, target, name in (
        (axes[0], pred_load, target_load, "load"),
        (axes[1], pred_pv, target_pv, "PV"),
    ):
        ax.plot(slot_hours, np.asarray(target), label=f"actual {name}", lw=1.2)
        ax.plot(
            slot_hours, np.asarray(pred), label=f"predicted {name}", lw=1.2, ls="--"
        )
        ax.set_ylabel(f"normalized {name}")
        ax.legend()
    axes[1].set_xlabel("Hour")
    fig.tight_layout()
    return fig


def plot_learning_curves(progress_df, settings: Optional[Sequence[str]] = None):
    """Reward / TD-error training curves (data_analysis.py:697-772).

    ``progress_df``: the ``training_progress`` table (ResultsStore).
    """
    plt = _plt()
    df = progress_df
    if settings is not None:
        df = df[df["setting"].isin(list(settings))]
    fig, axes = plt.subplots(1, 2, figsize=(12, 4))
    for (setting, impl), g in df.groupby(["setting", "implementation"]):
        g = g.sort_values("episode")
        axes[0].plot(g["episode"], g["reward"], label=f"{setting} ({impl})")
        axes[1].plot(g["episode"], g["error"], label=f"{setting} ({impl})")
    axes[0].set_xlabel("Episode")
    axes[0].set_ylabel("Average reward")
    axes[0].set_title("Training reward")
    axes[1].set_xlabel("Episode")
    axes[1].set_ylabel("Average error")
    axes[1].set_title("Training error")
    axes[0].legend(fontsize=7)
    fig.tight_layout()
    return fig


def plot_training_health(health_df, settings: Optional[Sequence[str]] = None):
    """Greedy held-out cost AND reward per eval period, with basin/slide
    points flagged — the figure form of the training_health table
    (train/health.py). No reference counterpart: the reference's
    training_progress curves (data_analysis.py:697-772) show training
    reward only, which cannot display the don't-heat basin's signature
    (cost improving while comfort collapses)."""
    plt = _plt()
    df = health_df
    if settings is not None:
        df = df[df["setting"].isin(list(settings))]
    fig, axes = plt.subplots(1, 2, figsize=(12, 4))
    for (setting, impl), g in df.groupby(["setting", "implementation"]):
        g = g.sort_values("episode")
        label = f"{setting} ({impl})"
        axes[0].plot(g["episode"], g["greedy_cost"], label=label)
        axes[1].plot(g["episode"], g["greedy_reward"], label=label)
        basin = g[g["status"] == "basin"]
        slide = g[g["status"] == "slide"]
        for ax, col in ((axes[0], "greedy_cost"), (axes[1], "greedy_reward")):
            ax.scatter(slide["episode"], slide[col], marker="^",
                       color="tab:orange", zorder=3, s=24)
            ax.scatter(basin["episode"], basin[col], marker="x",
                       color="tab:red", zorder=3, s=32)
    axes[0].set_xlabel("Episode")
    axes[0].set_ylabel("Greedy held-out cost (EUR)")
    axes[0].set_title("Greedy cost (x = basin, ^ = slide)")
    axes[1].set_xlabel("Episode")
    axes[1].set_ylabel("Greedy held-out reward")
    axes[1].set_title("Greedy reward (the comfort-collapse signal)")
    axes[0].legend(fontsize=7)
    fig.tight_layout()
    return fig


def plot_cost_comparison(test_df, settings: Optional[Sequence[str]] = None):
    """Average daily cost per setting, with per-day spread
    (data_analysis.py:324-417)."""
    from p2pmicrogrid_tpu.analysis.stats import daily_cost_table

    plt = _plt()
    df = test_df
    if settings is not None:
        df = df[df["setting"].isin(list(settings))]
    daily = daily_cost_table(df).reset_index().melt(
        id_vars="day", var_name="setting", value_name="cost"
    )
    order = sorted(daily["setting"].unique())
    means = [daily.loc[daily["setting"] == s, "cost"].mean() for s in order]
    stds = [daily.loc[daily["setting"] == s, "cost"].std() for s in order]
    fig, ax = plt.subplots(figsize=(max(6, len(order) * 1.2), 4))
    ax.bar(range(len(order)), means, 0.6, yerr=stds, capsize=4)
    ax.set_xticks(range(len(order)))
    ax.set_xticklabels(order, rotation=20, ha="right", fontsize=8)
    ax.set_ylabel("Avg daily cost per agent [€]")
    ax.set_title("Cost comparison")
    fig.tight_layout()
    return fig


def plot_day_traces(test_df, setting: str, day: int, comfort_bounds=(20.0, 22.0)):
    """Per-slot load/pv/temperature/heat-pump/cost traces for one day
    (data_analysis.py:420-694)."""
    plt = _plt()
    df = test_df[(test_df["setting"] == setting) & (test_df["day"] == day)]
    fig, axes = plt.subplots(4, 1, figsize=(9, 11), sharex=True)
    for agent, g in df.groupby("agent"):
        g = g.sort_values("time")
        t = g["time"] * 24
        axes[0].plot(t, g["load"] * 1e-3, label=f"agent {agent}")
        axes[0].plot(t, g["pv"] * 1e-3, "--", alpha=0.6)
        axes[1].plot(t, g["temperature"])
        axes[2].plot(t, g["heatpump"] * 1e-3)
        axes[3].plot(t, g["cost"].cumsum())
    axes[0].set_ylabel("Load / PV [kW]")
    axes[0].legend(fontsize=7)
    axes[1].set_ylabel("T indoor [°C]")
    axes[1].axhspan(*comfort_bounds, alpha=0.15, color="green")
    axes[2].set_ylabel("Heat pump [kW]")
    axes[3].set_ylabel("Cumulative cost [€]")
    axes[3].set_xlabel("Time [h]")
    fig.suptitle(f"{setting} — day {day}")
    fig.tight_layout()
    return fig


def plot_rounds_decisions(rounds_df, setting: str, day: int):
    """Round-by-round heat-pump decisions (data_analysis.py:997-1096)."""
    plt = _plt()
    df = rounds_df[(rounds_df["setting"] == setting) & (rounds_df["day"] == day)]
    agents = sorted(df["agent"].unique())
    fig, axes = plt.subplots(len(agents), 1, figsize=(9, 2.5 * len(agents)), sharex=True, squeeze=False)
    for ax, agent in zip(axes[:, 0], agents):
        g = df[df["agent"] == agent]
        for rnd, gg in g.groupby("round"):
            gg = gg.sort_values("time")
            ax.step(gg["time"] * 24, gg["decision"] * 1e-3, where="post", label=f"round {rnd}")
        ax.set_ylabel(f"agent {agent} [kW]")
        ax.legend(fontsize=7)
    axes[-1, 0].set_xlabel("Time [h]")
    fig.suptitle(f"Per-round decisions — {setting}, day {day}")
    fig.tight_layout()
    return fig


def plot_sweep_curves(sweep_df, metric: str = "training"):
    """Hyperparameter-sweep curves from the ``hyperparameters_single_day``
    table (the reference's DDPG sweep figures, data_analysis.py:1460-1629):
    one line per (settings, trial), episode on x."""
    plt = _plt()
    fig, ax = plt.subplots(figsize=(9, 4.5))
    for (settings, trial), g in sweep_df.groupby(["settings", "trial"]):
        g = g.sort_values("episode")
        ax.plot(g["episode"], g[metric], label=f"{settings} #{trial}", alpha=0.8)
    ax.set_xlabel("Episode")
    ax.set_ylabel(metric)
    ax.set_title(f"Hyperparameter sweep — {metric}")
    ax.legend(fontsize=6)
    fig.tight_layout()
    return fig


def plot_qtable_heatmap(q_table: np.ndarray):
    """Greedy-policy heatmap over (time, temperature), marginalizing the
    balance/p2p state dims (data_analysis.py:1214-1297).

    q_table: one agent's table [nt, ntemp, nb, np2p, n_actions].
    """
    plt = _plt()
    q = np.asarray(q_table)
    # Marginalize balance/p2p by averaging Q before the argmax.
    q2 = q.mean(axis=(2, 3))  # [nt, ntemp, n_actions]
    greedy = q2.argmax(axis=-1)
    fig, axes = plt.subplots(1, 2, figsize=(11, 4))
    im0 = axes[0].pcolormesh(greedy.T, cmap="viridis")
    axes[0].set_title("Greedy action (0=off, 2=full)")
    axes[0].set_xlabel("Time bin")
    axes[0].set_ylabel("Temperature bin")
    fig.colorbar(im0, ax=axes[0])
    im1 = axes[1].pcolormesh(q2.max(axis=-1).T, cmap="magma")
    axes[1].set_title("Max Q-value")
    axes[1].set_xlabel("Time bin")
    fig.colorbar(im1, ax=axes[1])
    fig.tight_layout()
    return fig
