"""Post-run analysis and reporting (reference: microgrid/data_analysis.py).

Host-side: pandas/matplotlib/scipy over the relational results store
(data/results.py) and raw simulator outputs. Figures are returned (and
optionally saved), never ``plt.show()``-n — this layer must run headless.
"""

from p2pmicrogrid_tpu.analysis.report import (
    community_summary,
    analyse_community_output,
)
from p2pmicrogrid_tpu.analysis.stats import (
    paired_cost_ttest,
    statistics_community_scale,
    statistics_nr_rounds,
    statistical_tests,
)
from p2pmicrogrid_tpu.analysis.plots import (
    plot_cost_vs_community_size,
    plot_forecast,
    plot_learning_curves,
    plot_training_health,
    plot_pv_drop_comparison,
    plot_scaling,
    plot_cost_comparison,
    plot_day_traces,
    plot_rounds_decisions,
    plot_qtable_heatmap,
    plot_sweep_curves,
)

__all__ = [
    "community_summary",
    "analyse_community_output",
    "paired_cost_ttest",
    "statistics_community_scale",
    "statistics_nr_rounds",
    "statistical_tests",
    "plot_cost_vs_community_size",
    "plot_forecast",
    "plot_learning_curves",
    "plot_training_health",
    "plot_pv_drop_comparison",
    "plot_scaling",
    "plot_cost_comparison",
    "plot_day_traces",
    "plot_rounds_decisions",
    "plot_qtable_heatmap",
]
