"""Statistical significance tests over result tables.

Reference: data_analysis.py:1300-1457 — paired per-day t-tests between
settings, Levene variance tests and one-way ANOVA across community scales and
negotiation round counts. Rebuilt generically: the reference hardcodes its
thesis setting strings; here any list of settings works, with the reference's
groupings expressible as calls.
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Sequence

import numpy as np
from scipy import stats


def _labelled(df):
    """Add a run-identity ``label`` column: the setting alone when only one
    implementation was stored under it, else ``setting[implementation]`` —
    so baseline and RL runs sharing a setting never aggregate together."""
    df = df.copy()
    multi = df.groupby("setting")["implementation"].nunique()
    df["label"] = np.where(
        df["setting"].map(multi) > 1,
        df["setting"] + "[" + df["implementation"] + "]",
        df["setting"],
    )
    return df


def daily_cost_table(df):
    """Pivot test-result rows into a [day x run-label] cost table.

    Reference pattern (data_analysis.py:1326-1331): sum cost over slots per
    (run, day, agent), then average over agents. Rows are grouped by
    (setting, implementation) so two implementations stored under one setting
    (e.g. 'rule-based' baseline vs 'tabular' eval) stay separate columns
    instead of being summed together.
    """
    g = (
        _labelled(df)[["label", "day", "agent", "cost"]]
        .groupby(["label", "day", "agent"]).sum()
        .groupby(["label", "day"]).mean()
    )
    return g.reset_index().pivot(index="day", columns="label", values="cost")


def mean_cost_per_setting_agent(df):
    """Per-(run-label, agent) mean daily cost (the reference's scale/rounds
    aggregation, data_analysis.py:1383-1387,1421-1424)."""
    out = (
        _labelled(df)[["label", "agent", "day", "cost"]]
        .groupby(["label", "agent", "day"]).sum()
        .groupby(["label", "agent"]).mean()
        .reset_index()
    )
    return out.rename(columns={"label": "setting"})


def _ttest_from_table(table, setting_a: str, setting_b: str) -> Dict[str, float]:
    costs = table[[setting_a, setting_b]].dropna()
    diff = np.asarray(costs[setting_a]) - np.asarray(costs[setting_b])
    t, p = stats.ttest_1samp(diff, 0)
    return {
        "mean_diff": float(diff.mean()),
        "t": float(t),
        "p": float(p),
        "n_days": int(len(diff)),
    }


def paired_cost_ttest(
    df, setting_a: str, setting_b: str
) -> Dict[str, float]:
    """Paired per-day t-test of total daily cost between two run labels
    (data_analysis.py:1310-1320,1339-1349). A label is the setting string, or
    ``setting[implementation]`` when several implementations share a setting
    (see ``_labelled``) — this is how baseline-vs-RL comparisons are keyed.
    Days present in only one run are dropped (and counted) rather than
    silently poisoning the test with NaN."""
    return _ttest_from_table(daily_cost_table(df), setting_a, setting_b)


def statistics_community_scale(
    df, settings: Optional[Sequence[str]] = None
) -> Dict[str, float]:
    """Levene + ANOVA of per-agent mean cost across community sizes
    (data_analysis.py:1378-1401). Setting strings must start with the agent
    count (the reference's ``{n}-multi-agent-...`` naming)."""
    if settings is not None:
        df = df[df["setting"].isin(list(settings))]
    costs = mean_cost_per_setting_agent(df)
    costs["agents"] = costs["setting"].map(
        lambda s: int(re.match(r"^([0-9]+)-", s).groups()[0])
    )
    samples = [
        np.asarray(costs.loc[costs["agents"] == n, "cost"])
        for n in sorted(costs["agents"].unique())
    ]
    _, p_levene = stats.levene(*samples)
    _, p_anova = stats.f_oneway(*samples)
    out = {"p_levene": float(p_levene), "p_anova": float(p_anova)}
    if len(samples) > 2:
        _, p_reduced = stats.f_oneway(*samples[1:])
        out["p_anova_without_smallest"] = float(p_reduced)
    return out


def statistics_nr_rounds(
    df, settings: Optional[Sequence[str]] = None
) -> Dict[str, float]:
    """Levene + ANOVA + pairwise t-tests across negotiation round counts
    (data_analysis.py:1404-1437). Settings follow the reference naming
    ``...rounds-{r}-...``."""
    if settings is not None:
        df = df[df["setting"].isin(list(settings))]
    costs = mean_cost_per_setting_agent(df)
    costs["rounds"] = costs["setting"].map(
        lambda s: int(re.search(r"rounds-([0-9]+)", s).groups()[0])
    )
    rounds_sorted = sorted(costs["rounds"].unique())
    samples = [
        np.asarray(costs.loc[costs["rounds"] == r, "cost"]) for r in rounds_sorted
    ]
    _, p_levene = stats.levene(*samples)
    _, p_anova = stats.f_oneway(*samples)
    out = {"p_levene": float(p_levene), "p_anova": float(p_anova)}
    for i in range(len(samples)):
        for j in range(i + 1, len(samples)):
            _, p = stats.ttest_ind(samples[i], samples[j])
            out[f"p_rounds_{rounds_sorted[i]}_vs_{rounds_sorted[j]}"] = float(p)
    return out


def default_comparison_pairs(df) -> list:
    """The reference's thesis comparisons, derived from whatever the results
    table holds (data_analysis.py:1300-1330): each RL "com" run vs each of
    its ``baseline-``-prefixed twins and vs its "no-com" counterparts.

    Works on run LABELS, not bare settings: a setting holding several
    implementations (e.g. tabular and dqn evaluated under one community
    setting, or the two baseline kinds) contributes one label per
    implementation, and every RL label pairs against every twin label.
    """
    by_setting = (
        _labelled(df).groupby("setting")["label"].unique().to_dict()
    )
    pairs = []
    for s in sorted(by_setting):
        m = re.match(r"^([0-9]+)-multi-agent-com-rounds-[0-9]+-(homo|hetero)$", s)
        if not m:
            continue
        nocom = f"{m.group(1)}-multi-agent-no-com-{m.group(2)}"
        twins = sorted(by_setting.get(f"baseline-{s}", [])) + sorted(
            by_setting.get(nocom, [])
        )
        for rl in sorted(by_setting[s]):
            pairs.extend((rl, twin) for twin in twins)
    return pairs


def statistical_tests(store, settings_pairs=None) -> Dict[str, Dict[str, float]]:
    """Run the available test battery over a ResultsStore's test results
    (the reference's ``statistical_tests`` driver, data_analysis.py:1440-1457).

    ``settings_pairs``: optional list of (setting_a, setting_b) for paired
    t-tests; by default the reference's thesis comparisons are derived from
    the table itself (``default_comparison_pairs``). Scale/rounds analyses
    run when >= 2 matching settings exist.
    """
    df = store.get_test_results()
    results: Dict[str, Dict[str, float]] = {}
    if df.empty:
        return results

    if settings_pairs is None:
        settings_pairs = default_comparison_pairs(df)
    if settings_pairs:
        table = daily_cost_table(df)  # one pivot for every derived pair
        for a, b in settings_pairs:
            results[f"ttest[{a} vs {b}]"] = _ttest_from_table(table, a, b)

    # Scale analysis over a MATCHED family only — same com/rounds/population
    # treatment, varying community size ONLY (the reference compares its
    # rounds-1 com settings across sizes, data_analysis.py:1378-1401).
    # Pooling no-com / rounds-3 / homo-vs-hetero runs into a size group
    # would confound the test; heterogeneity is pinned per pool like rounds.
    for hom in ("hetero", "homo"):
        scale_settings = sorted(
            s
            for s in df["setting"].unique()
            if re.match(rf"^[0-9]+-multi-agent-com-rounds-1-{hom}$", s)
        )
        if len({re.match(r"^([0-9]+)-", s).groups()[0] for s in scale_settings}) >= 2:
            # First qualifying pool takes the canonical key; a second
            # population's pool gets its own key — which population each
            # analysis covers is recorded either way.
            key = (
                "community_scale"
                if "community_scale" not in results
                else f"community_scale_{hom}"
            )
            results[key] = {
                **statistics_community_scale(df, scale_settings),
                "population": hom,
            }

    # Rounds analysis within ONE (community size, population) cell at a time
    # (the reference varies rounds at fixed size, data_analysis.py:1404-1437).
    # EVERY qualifying cell gets analyzed — the smallest takes the canonical
    # key (mirrors the community_scale convention above), the rest get
    # nr_rounds_{size}_{population} keys — and each records which cell it
    # covers, so a DB holding e.g. both 2- and 3-agent round families yields
    # both analyses instead of silently dropping one (round-3 advisor).
    by_cell: Dict[tuple, list] = {}
    for s in df["setting"].unique():
        m = re.match(r"^([0-9]+)-multi-agent-com-rounds-[0-9]+-(homo|hetero)$", s)
        if m:
            by_cell.setdefault((int(m.group(1)), m.group(2)), []).append(s)
    for cell in sorted(by_cell):
        group = sorted(by_cell[cell])
        if len({re.search(r"rounds-([0-9]+)", s).groups()[0] for s in group}) >= 2:
            key = (
                "nr_rounds"
                if "nr_rounds" not in results
                else f"nr_rounds_{cell[0]}_{cell[1]}"
            )
            results[key] = {
                **statistics_nr_rounds(df, group),
                "cell": {"n_agents": cell[0], "population": cell[1]},
            }

    return results
