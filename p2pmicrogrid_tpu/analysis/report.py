"""Community run report (reference: data_analysis.py:188-304).

``community_summary`` computes the quantities the reference prints and plots
after a run — per-agent energy, cost, self-consumption — from simulator
outputs; ``analyse_community_output`` renders the reference's figure set
(cost bars, self-consumption bars, grid-load day x slot heatmap, per-agent
profile/temperature/heat-pump traces).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np


def community_summary(
    outputs,
    arrays,
    slot_hours: float = 0.25,
    comfort_bounds: tuple = (20.0, 22.0),
) -> Dict[str, np.ndarray]:
    """Per-agent summary over an evaluated span.

    outputs/arrays leaves: [D, T, ...] (or [T, ...]; a leading day axis is
    added if missing). Mirrors data_analysis.py:194-197: power = what each
    agent drew (grid + p2p), self-consumption = PV used on site.
    ``comfort_bounds`` defaults to the reference's 21 +/- 1 °C band
    (heating.py:90-94); pass ``(cfg.thermal.lower_bound,
    cfg.thermal.upper_bound)`` for non-default thermal configs.
    """
    def _flat(x):
        x = np.asarray(x)
        return x.reshape(-1, x.shape[-1]) if x.ndim > 2 else x

    power = _flat(outputs.p_grid) + _flat(outputs.p_p2p)   # [D*T, A]
    production = _flat(arrays.pv_w)
    load = _flat(arrays.load_w)
    cost = _flat(outputs.cost)
    t_in = _flat(outputs.t_in)

    # data_analysis.py:195: PV production covered on-site. When the agent
    # injects (power < 0) the self-consumed part is production + power;
    # when it draws, all production is consumed on site.
    self_consumption = np.where(power < 0, production + power, production)

    with np.errstate(invalid="ignore", divide="ignore"):
        sc_ratio = self_consumption.sum(axis=0) / production.sum(axis=0)

    lo, hi = comfort_bounds
    return {
        "energy_consumed_kwh": power.sum(axis=0) * slot_hours * 1e-3,
        "load_energy_kwh": load.sum(axis=0) * slot_hours * 1e-3,
        "pv_energy_kwh": production.sum(axis=0) * slot_hours * 1e-3,
        "total_cost_eur": cost.sum(axis=0),
        "self_consumption_ratio": sc_ratio,
        "mean_temperature": t_in.mean(axis=0),
        "comfort_violation_frac": ((t_in < lo) | (t_in > hi)).mean(axis=0),
    }


def analyse_community_output(
    days,
    outputs,
    arrays,
    save_dir: Optional[str] = None,
    slot_hours: float = 0.25,
    comfort_bounds: tuple = (20.0, 22.0),
):
    """The reference's post-run figure set (data_analysis.py:188-304).

    Returns (summary dict, {figure_name: Figure}). Saves PNGs when
    ``save_dir`` is given.
    """
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    summary = community_summary(outputs, arrays, slot_hours, comfort_bounds)
    figures = {}

    power = np.asarray(outputs.p_grid) + np.asarray(outputs.p_p2p)
    if power.ndim == 2:
        power = power[None]
    n_days, T, A = power.shape
    agent_ids = np.arange(A)

    # Cost bars (plot_costs, data_analysis.py:247-254).
    fig, ax = plt.subplots()
    ax.bar(agent_ids, summary["total_cost_eur"], 0.35)
    ax.set_title("Electricity costs")
    ax.set_xlabel("Agent")
    ax.set_ylabel("Cost [€]")
    figures["costs"] = fig

    # Self-consumption bars (plot_selfconsumption, data_analysis.py:257-263).
    fig, ax = plt.subplots()
    ax.bar(agent_ids, summary["self_consumption_ratio"] * 100, 0.35)
    ax.set_title("Self consumption")
    ax.set_xlabel("Agent")
    ax.set_ylabel("%")
    figures["self_consumption"] = fig

    # Grid load day x slot heatmap (plot_grid_load, data_analysis.py:266-304).
    fig, ax = plt.subplots()
    grid_power = power.sum(axis=-1) * 1e-3  # [D, T] kW
    pcm = ax.pcolormesh(grid_power, cmap="magma")
    ax.set_title("Grid load")
    ax.set_xlabel("Time slot")
    ax.set_ylabel("Day")
    fig.colorbar(pcm, ax=ax, orientation="horizontal", label="Power [kW]")
    figures["grid_load"] = fig

    # Per-agent traces for the first evaluated day (data_analysis.py:212-240).
    day0 = int(np.asarray(days).reshape(-1)[0]) if days is not None else 0
    t = np.arange(T) * slot_hours
    t_in = np.asarray(outputs.t_in)
    hp = np.asarray(outputs.hp_power_w)
    pv = np.asarray(arrays.pv_w)
    if t_in.ndim == 2:
        t_in, hp, pv = t_in[None], hp[None], pv[None]
    for i in range(A):
        fig, axes = plt.subplots(3, 1, figsize=(8, 9), sharex=True)
        axes[0].plot(t, power[0, :, i] * 1e-3, label="Loads")
        axes[0].plot(t, pv[0, :, i] * 1e-3, label="PV")
        axes[0].set_ylabel("Power [kW]")
        axes[0].set_title(f"Agent profiles (agent {i}, day {day0})")
        axes[0].legend()
        axes[1].plot(t, t_in[0, :, i])
        axes[1].axhspan(*comfort_bounds, alpha=0.15, color="green")
        axes[1].set_ylabel("Temperature [°C]")
        axes[1].set_title(f"Indoor temperature (agent {i}, day {day0})")
        axes[2].plot(t, hp[0, :, i])
        axes[2].set_ylabel("Power [W]")
        axes[2].set_xlabel("Time [h]")
        axes[2].set_title(f"Heat pump power (agent {i}, day {day0})")
        figures[f"agent_{i}"] = fig

    if save_dir:
        os.makedirs(save_dir, exist_ok=True)
        for name, fig in figures.items():
            fig.savefig(os.path.join(save_dir, f"{name}.png"), dpi=120)
    for fig in figures.values():
        plt.close(fig)
    return summary, figures
