"""Continual training: fine-tune the live bundle on fresh serve traces.

The training half of the flywheel (ROADMAP item 5). Production policies
go stale as load/PV/price regimes drift; the warehouse records every
decision the live bundle made (data/trace_export.py); this module turns
those decisions back into a CANDIDATE bundle:

1. **Warm start from the incumbent.** A policy bundle freezes only the
   greedy subtree (serve/export.py), so ``state_from_bundle`` rebuilds a
   full learner state around it: fresh optimizer/replay/exploration
   scaffolding, the bundle's greedy parameters grafted in (DQN/DDPG
   targets hard-copied from the grafted online/actor — fine-tuning must
   not bootstrap against random targets).
2. **Off-policy pretraining on the traces.** ``offpolicy_pretrain`` runs
   jitted TD/Bellman/actor-critic steps on minibatches sampled from the
   exported transitions — the SAME update rules the per-slot learners use
   (models/tabular.tabular_update, models/dqn.apply_td_update,
   models/ddpg.ddpg_learn_batch), so trace training cannot drift from
   episode-training semantics.
3. **Chunked simulator fine-tune under the guard.** ``train_continual``
   then runs the donated-carry chunked pipeline (PR 4) through
   ``train_chunked_with_rollback`` (PRs 7/9): the divergence guard trips
   on non-finite counters or basin verdicts, rollback restores the last
   verified checkpoint with dropped lrs on a fresh key branch — a
   continually-retrained candidate can never emerge from a diverged run.
4. **Candidate export.** The result freezes into a bundle whose config
   carries a bumped ``train.starting_episodes`` (continual generations
   CONTINUE the episode count), giving the candidate a config_hash
   distinct from the incumbent's — the registry/canary routing key — with
   full provenance (incumbent hash, trace window, rollbacks) in the
   manifest ``source``.

Nothing here pushes traffic: the candidate must pass the promotion gate
and canary (serve/promotion.py) before a household ever sees it.

Host-sync note: this module is on the training dispatch path
(tools/check_host_sync.py); the pretrain loop is one jitted scan and the
chunked phase inherits the async pipeline's discipline.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from p2pmicrogrid_tpu.train.resilience import GuardPolicy, RollbackRecord


@dataclass
class ContinualResult:
    """What one continual-training run produced."""

    candidate_dir: str
    candidate_hash: str
    incumbent_hash: Optional[str]
    episode0: int
    episodes: int
    trace_steps: int
    trace_loss_final: Optional[float]
    trace_summary: dict = field(default_factory=dict)
    rollbacks: List[RollbackRecord] = field(default_factory=list)

    def summary(self) -> dict:
        return {
            "candidate_dir": self.candidate_dir,
            "candidate_hash": self.candidate_hash,
            "incumbent_hash": self.incumbent_hash,
            "episode0": self.episode0,
            "episodes": self.episodes,
            "trace_steps": self.trace_steps,
            "trace_loss_final": self.trace_loss_final,
            "rollbacks": len(self.rollbacks),
            **{f"trace_{k}": v for k, v in self.trace_summary.items()},
        }


def _check_bundle_matches(cfg, manifest: dict) -> None:
    impl = manifest.get("implementation")
    if impl != cfg.train.implementation:
        raise ValueError(
            f"bundle implements {impl!r} but the config trains "
            f"{cfg.train.implementation!r} — continual training must "
            "fine-tune the SAME policy class it serves"
        )
    n_agents = manifest.get("n_agents")
    if n_agents != cfg.sim.n_agents:
        raise ValueError(
            f"bundle serves {n_agents} agents but the config simulates "
            f"{cfg.sim.n_agents}"
        )


def state_from_bundle(cfg, manifest: dict, params: dict, key):
    """Full shared learner state (what the chunked trainer carries —
    parallel/scenarios.init_shared_pol_state) warm-started from a
    bundle's greedy subtree. Fresh optimizer/exploration scaffolding;
    bootstrap targets hard-copied from the grafted parameters."""
    import jax
    import jax.numpy as jnp

    from p2pmicrogrid_tpu.parallel.scenarios import init_shared_pol_state

    _check_bundle_matches(cfg, manifest)
    impl = cfg.train.implementation
    as_f32 = lambda tree: jax.tree_util.tree_map(
        lambda x: jnp.asarray(x, dtype=jnp.float32), tree
    )
    state = init_shared_pol_state(cfg, key)
    if impl == "tabular":
        q = as_f32(params["q_table"])
        if q.shape != state.q_table.shape:
            raise ValueError(
                f"bundle q_table {q.shape} != config table "
                f"{state.q_table.shape}"
            )
        return state._replace(q_table=q)
    if impl == "dqn":
        online = as_f32(params)
        target = jax.tree_util.tree_map(lambda x: x, online)
        return state._replace(online=online, target=target)
    # ddpg: the bundle is the actor; the critic trains fresh from init
    # (it was never exported), targets copy their live twins.
    share = bool(manifest.get("model", {}).get("share_across_agents"))
    if share != bool(cfg.ddpg.share_across_agents):
        raise ValueError(
            f"bundle share_across_agents={share} but config says "
            f"{cfg.ddpg.share_across_agents}"
        )
    actor = as_f32(params)
    return state._replace(
        actor=actor,
        actor_target=jax.tree_util.tree_map(lambda x: x, actor),
        critic_target=jax.tree_util.tree_map(lambda x: x, state.critic),
    )


def _frac_to_action_index(frac):
    """Served hp fractions {0.0, 0.5, 1.0} back to the discrete action
    index (models/dqn.ACTION_VALUES); nearest bin, so a float16 bundle's
    quantized fractions still map correctly."""
    import jax.numpy as jnp

    return jnp.clip(jnp.round(frac * 2.0), 0, 2).astype(jnp.int32)


def make_trace_update_fn(cfg, dataset, batch_size: Optional[int] = None):
    """Jitted one-step off-policy update over the trace transitions.

    Returns ``update(pol_state, key) -> (pol_state, loss)`` closed over
    the dataset as device constants. Each step draws ``batch_size``
    transition slots uniformly and applies the implementation's OWN
    learn rule — there is exactly one copy of the update semantics in the
    repo and this reuses it.
    """
    import jax
    import jax.numpy as jnp

    impl = cfg.train.implementation
    n = dataset.n_transitions
    obs = jnp.asarray(dataset.obs)          # [N, A, 4]
    action = jnp.asarray(dataset.action)    # [N, A]
    reward = jnp.asarray(dataset.reward)    # [N, A]
    next_obs = jnp.asarray(dataset.next_obs)

    if impl == "tabular":
        from p2pmicrogrid_tpu.models.tabular import tabular_update

        b = min(batch_size or 32, n)
        act_idx = _frac_to_action_index(action)

        def update(state, key):
            idx = jax.random.randint(key, (b,), 0, n)

            def one(st, i):
                return tabular_update(
                    cfg.qlearning, st, obs[i], act_idx[i], reward[i],
                    next_obs[i],
                ), 0.0

            state, _ = jax.lax.scan(one, state, idx)
            return state, jnp.zeros(())

        return jax.jit(update)

    if impl == "dqn":
        from p2pmicrogrid_tpu.models.dqn import (
            ACTION_VALUES,
            _td_loss,
            apply_td_update,
        )
        from p2pmicrogrid_tpu.models.networks import QNetwork

        b = min(batch_size or cfg.dqn.batch_size, n)
        net = QNetwork(hidden=cfg.dqn.hidden)
        act_frac = ACTION_VALUES[_frac_to_action_index(action)][..., None]

        def update(state, key):
            idx = jax.random.randint(key, (b,), 0, n)
            # [B, A, ...] -> per-agent batches [A, B, ...].
            s = jnp.swapaxes(obs[idx], 0, 1)
            a = jnp.swapaxes(act_frac[idx], 0, 1)
            r = jnp.swapaxes(reward[idx], 0, 1)
            ns = jnp.swapaxes(next_obs[idx], 0, 1)

            def learn_one(params, target_params, opt_state, s, a, r, ns):
                return apply_td_update(
                    cfg.dqn,
                    lambda p: _td_loss(
                        cfg.dqn, net, p, target_params, s, a, r, ns
                    ),
                    params, target_params, opt_state,
                )

            online, target, opt_state, loss, _ = jax.vmap(learn_one)(
                state.online, state.target, state.opt_state, s, a, r, ns
            )
            return state._replace(
                online=online, target=target, opt_state=opt_state
            ), jnp.mean(loss)

        return jax.jit(update)

    if impl == "ddpg":
        from p2pmicrogrid_tpu.models.ddpg import ddpg_learn_batch

        b = min(batch_size or cfg.ddpg.batch_size, n)
        act_col = action[..., None]  # [N, A, 1]

        def update(params, key):
            idx = jax.random.randint(key, (b,), 0, n)
            s, a = obs[idx], act_col[idx]          # [B, A, ...]
            r, ns = reward[idx], next_obs[idx]
            if cfg.ddpg.share_across_agents:
                flat = lambda x: x.reshape((-1,) + x.shape[2:])
                pa, pc, pat, pct, oa, oc, _, sq = ddpg_learn_batch(
                    cfg.ddpg,
                    params.actor, params.critic,
                    params.actor_target, params.critic_target,
                    params.actor_opt, params.critic_opt,
                    flat(s), flat(a), flat(r), flat(ns),
                )
            else:
                pool = lambda x: jnp.moveaxis(x, 1, 0)  # [A, B, ...]
                pa, pc, pat, pct, oa, oc, _, sq = jax.vmap(
                    lambda *args: ddpg_learn_batch(cfg.ddpg, *args)
                )(
                    params.actor, params.critic,
                    params.actor_target, params.critic_target,
                    params.actor_opt, params.critic_opt,
                    pool(s), pool(a), pool(r), pool(ns),
                )
            return params._replace(
                actor=pa, critic=pc, actor_target=pat, critic_target=pct,
                actor_opt=oa, critic_opt=oc,
            ), jnp.mean(sq)

        return jax.jit(update)

    raise ValueError(f"unknown implementation {impl!r}")


def offpolicy_pretrain(
    cfg,
    pol_state,
    dataset,
    key,
    steps: int,
    batch_size: Optional[int] = None,
) -> Tuple[object, np.ndarray]:
    """``steps`` off-policy updates on the trace transitions; returns
    ``(pol_state, losses [steps])``. One jitted scan — the whole pretrain
    is a single device dispatch regardless of step count."""
    import jax

    if steps <= 0:
        return pol_state, np.zeros((0,), dtype=np.float32)
    update = make_trace_update_fn(cfg, dataset, batch_size=batch_size)

    def body(state, k):
        return update(state, k)

    keys = jax.random.split(key, steps)
    pol_state, losses = jax.lax.scan(body, pol_state, keys)
    # host-sync: pretrain result readback at the phase boundary — the
    # chunked fine-tune (and its guard) consumes the finished state.
    return pol_state, np.asarray(losses, dtype=np.float32)


def continual_cfg(cfg, episode0: int, incumbent_hash: Optional[str]):
    """The candidate's config: the incumbent's experiment with
    ``train.starting_episodes`` advanced to ``episode0``. Continual
    generations CONTINUE the episode count, which (a) keys the chunked
    trainer's episode streams off fresh absolute episodes and (b) gives
    the candidate a distinct ``config_hash`` — the identity every
    routing/attribution layer keys on. If the hash still collides with
    the incumbent's (an episode0 that matches the incumbent's own
    export), the episode origin is advanced deterministically until it
    does not."""
    from p2pmicrogrid_tpu.telemetry import config_hash

    for bump in range(64):
        candidate = cfg.replace(
            train=dataclasses.replace(
                cfg.train, starting_episodes=episode0 + bump
            )
        )
        if incumbent_hash is None or config_hash(candidate) != incumbent_hash:
            return candidate
    raise RuntimeError("could not derive a distinct candidate config_hash")


def train_continual(
    cfg,
    incumbent_dir: str,
    dataset,
    out_dir: str,
    ckpt_dir: str,
    n_episodes: int = 20,
    n_chunks: int = 1,
    eval_every: int = 10,
    trace_steps: int = 200,
    trace_batch: Optional[int] = None,
    episode0: Optional[int] = None,
    guard_policy: GuardPolicy = GuardPolicy(),
    telemetry=None,
    dtype: str = "float32",
    s_eval: int = 8,
    pipeline: bool = True,
) -> ContinualResult:
    """The continual-training driver: incumbent bundle + fresh traces ->
    candidate bundle.

    Phases (module docstring): warm start, ``trace_steps`` off-policy
    updates on ``dataset``, then ``n_episodes`` of the chunked pipeline
    under the divergence guard with rollback, then export to ``out_dir``.
    ``n_episodes=0`` skips the simulator phase (pure trace fine-tune —
    the fast path for tests and tight retraining cadences).

    The returned ``ContinualResult`` carries the candidate's
    ``config_hash`` — the id the promotion pipeline (serve/promotion.py)
    gates and ramps.
    """
    import jax

    from p2pmicrogrid_tpu.envs import make_ratings
    from p2pmicrogrid_tpu.serve.export import (
        export_policy_bundle,
        load_policy_bundle,
    )
    from p2pmicrogrid_tpu.telemetry import config_hash
    from p2pmicrogrid_tpu.train.resilience import train_chunked_with_rollback

    manifest, params = load_policy_bundle(incumbent_dir)
    incumbent_hash = manifest.get("config_hash")
    if episode0 is None:
        source = manifest.get("source") or {}
        src_ep = source.get("episode")
        episode0 = (
            src_ep + 1 if isinstance(src_ep, int) and src_ep >= 0
            else cfg.train.starting_episodes
        )
    cand_cfg = continual_cfg(cfg, episode0, incumbent_hash)
    episode0 = cand_cfg.train.starting_episodes
    key = jax.random.PRNGKey(cand_cfg.train.seed)
    key, k_warm, k_trace, k_train = jax.random.split(key, 4)
    pol_state = state_from_bundle(cand_cfg, manifest, params, k_warm)

    if telemetry is not None:
        telemetry.event(
            "continual",
            phase="start",
            incumbent=incumbent_hash,
            episode0=episode0,
            trace_transitions=dataset.n_transitions,
            trace_steps=trace_steps,
            n_episodes=n_episodes,
        )
    pol_state, trace_losses = offpolicy_pretrain(
        cand_cfg, pol_state, dataset, k_trace,
        steps=trace_steps, batch_size=trace_batch,
    )
    trace_loss_final = (
        float(trace_losses[-1]) if trace_losses.size else None
    )
    if telemetry is not None:
        telemetry.event(
            "continual",
            phase="trace_pretrain",
            steps=int(trace_losses.size),
            loss_final=trace_loss_final,
        )
        telemetry.counter("continual.trace_steps", int(trace_losses.size))

    rollbacks: List[RollbackRecord] = []
    if n_episodes > 0:
        rng = np.random.default_rng(cand_cfg.train.seed)
        ratings = make_ratings(cand_cfg, rng)
        (pol_state, _, _, _, _), rollbacks = train_chunked_with_rollback(
            cand_cfg, pol_state, ratings, k_train, ckpt_dir,
            n_episodes=n_episodes, n_chunks=n_chunks,
            eval_every=eval_every, episode0=episode0,
            guard_policy=guard_policy,
            telemetry=telemetry,
            s_eval=s_eval, pipeline=pipeline,
        )

    export_policy_bundle(
        cand_cfg, pol_state, out_dir,
        source={
            "kind": "continual",
            "incumbent": incumbent_hash,
            "incumbent_dir": os.path.abspath(incumbent_dir),
            "episode": episode0 + n_episodes - 1,
            "trace_transitions": dataset.n_transitions,
            "trace_runs": list(dataset.run_ids),
            "trace_steps": int(trace_losses.size),
            # The export window (decision timestamps) this candidate
            # trained on — the audit link between a promoted bundle and
            # the leased warehouse window that produced it (ISSUE 11).
            "trace_window": [
                getattr(dataset, "window_start_ts", None),
                getattr(dataset, "window_end_ts", None),
            ],
            "sim_episodes": n_episodes,
            "rollbacks": len(rollbacks),
        },
        dtype=dtype,
    )
    cand_hash = config_hash(cand_cfg)
    if telemetry is not None:
        telemetry.event(
            "continual",
            phase="exported",
            candidate=cand_hash,
            incumbent=incumbent_hash,
            out_dir=os.path.abspath(out_dir),
            rollbacks=len(rollbacks),
        )
    return ContinualResult(
        candidate_dir=out_dir,
        candidate_hash=cand_hash,
        incumbent_hash=incumbent_hash,
        episode0=episode0,
        episodes=n_episodes,
        trace_steps=int(trace_losses.size),
        trace_loss_final=trace_loss_final,
        trace_summary=dataset.summary(),
        rollbacks=rollbacks,
    )
