"""Day-granular training for the recurrent (LSTM) DDPG actor.

The slot-level trainers (train/loop.py, parallel/scenarios.py) never route
through ``models/ddpg_recurrent.py`` — the recurrent variant is the
reference's day-episodic design: the critic values a WHOLE day sequence and
learning happens once per day (ddpg_recurrent.py module docstring). This
driver gives that policy class the missing train half of the
train -> export -> serve chain (ISSUE 14):

* **Rollouts run the real physics.** Each episode is one day of the same
  synthetic October traces the slot-level trainers use
  (``data.synthetic_traces`` -> ``build_episode_arrays``), stepped through
  the env's OWN pieces — ``grid_prices`` / ``make_observation`` /
  ``normalized_temperature`` / ``compute_costs`` / ``comfort_penalty`` /
  ``thermal_step`` — at the no-com granularity (grid-only settlement, zero
  p2p observation feature), which is exactly the ``trading=False`` branch
  of ``slot_dynamics``. The rollout's per-slot forward is
  ``recurrent_actor_step`` — the SAME function the serving engine runs —
  so a trained bundle serves the policy that was trained, not a cousin.
* **Learning is episodic** (``recurrent_ddpg_learn``): critic regresses the
  day's summed reward plus a bootstrapped next-day value over the [A]-agent
  batch of day sequences; the actor ascends the critic. Exploration is the
  reference's OU noise (``cfg.ddpg.ou_*``), drawn per-slot inside the
  rollout scan from the episode key.
* **Deterministic**: one host key chain (``fold_in`` per episode), jitted
  rollout + learn, no data-dependent host branching — same seed, same
  final state.

``train_recurrent_community`` returns the final state (and optionally
checkpoints it under the ``ddpg_recurrent`` implementation dir so
``export-bundle --implementation ddpg_recurrent`` finds it like any other
checkpoint). The ``train-recurrent`` CLI command wraps it.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from p2pmicrogrid_tpu.config import ExperimentConfig
from p2pmicrogrid_tpu.data import synthetic_traces
from p2pmicrogrid_tpu.envs.community import (
    AgentRatings,
    EpisodeArrays,
    PhysState,
    build_episode_arrays,
    init_physical,
    make_ratings,
)
from p2pmicrogrid_tpu.ops.market import compute_costs
from p2pmicrogrid_tpu.ops.tariff import grid_prices, p2p_price
from p2pmicrogrid_tpu.ops.thermal import (
    comfort_penalty,
    normalized_temperature,
    thermal_step,
)
from p2pmicrogrid_tpu.models.ddpg_recurrent import (
    RecurrentDDPGState,
    recurrent_actor_init_hidden,
    recurrent_actor_step,
    recurrent_ddpg_init,
    recurrent_ddpg_learn,
)
from p2pmicrogrid_tpu.ops.obs import make_observation

SLOTS_PER_DAY = 96


class DayRollout(NamedTuple):
    """One day under the recurrent actor, agent-major for the learner."""

    obs_seq: jnp.ndarray     # [A, T, 4]
    act_seq: jnp.ndarray     # [A, T, 1]
    reward_seq: jnp.ndarray  # [A, T]
    cost_eur: jnp.ndarray    # [] community day cost
    phys: PhysState          # end-of-day physical state
    hidden: jnp.ndarray      # [A, H] end-of-day actor carry


def rollout_day(
    cfg: ExperimentConfig,
    actor_params: dict,
    phys: PhysState,
    day: EpisodeArrays,
    ratings: AgentRatings,
    key: jax.Array,
    explore: bool = True,
    hidden: Optional[jnp.ndarray] = None,
) -> DayRollout:
    """Scan one day's slots under the recurrent actor (grid-only / no-com
    settlement), threading the flat LSTM carry exactly like serving does.

    ``hidden=None`` starts the day from the deterministic fresh carry
    (zeros) — the same re-init a serving session eviction applies."""
    th = cfg.thermal
    # lstm_features read off the params, like the serving engine does.
    lstm_features = int(actor_params["OptimizedLSTMCell_0"]["hf"]["bias"].shape[0])
    A = int(ratings.max_in.shape[0])
    if hidden is None:
        hidden = recurrent_actor_init_hidden((A,), lstm_features)
    ou0 = jnp.zeros((A,))
    keys = jax.random.split(key, day.time.shape[0])

    def step(carry, x):
        phys, hidden, ou = carry
        time_norm, t_out, load_w, pv_w, k = x
        buy, inj = grid_prices(cfg.tariff, time_norm)
        trade = p2p_price(buy, inj)
        balance_w = load_w - pv_w
        norm_balance = balance_w / ratings.max_in
        obs = make_observation(
            time_norm,
            normalized_temperature(th, phys.t_in),
            norm_balance,
            jnp.zeros_like(norm_balance),
        )  # [A, 4]
        action, hidden = recurrent_actor_step(
            actor_params, obs, hidden, lstm_features=lstm_features
        )
        if explore:
            # OU exploration per slot (rl_backup.py:65-85): the noise state
            # rides the scan carry; the decision is clipped back to [0, 1].
            d = cfg.ddpg
            ou = (
                ou
                - d.ou_theta * ou * d.ou_dt
                + d.ou_sigma * jnp.sqrt(d.ou_dt) * jax.random.normal(k, (A,))
            )
            action = jnp.clip(action + ou, 0.0, 1.0)
        hp_power = action * th.hp_max_power
        p_grid = balance_w + hp_power
        p_p2p = jnp.zeros_like(p_grid)
        cost = compute_costs(p_grid, p_p2p, buy, inj, trade, cfg.sim.slot_hours)
        penalty = comfort_penalty(th, phys.t_in)
        reward = -(cost + 10.0 * penalty)
        t_in_new, t_bm_new = thermal_step(
            th, cfg.sim.dt_seconds, t_out, phys.t_in, phys.t_bm, hp_power
        )
        phys = PhysState(
            t_in=t_in_new, t_bm=t_bm_new, soc=phys.soc, hp_frac=action
        )
        return (phys, hidden, ou), (obs, action, reward, cost)

    xs = (day.time, day.t_out, day.load_w, day.pv_w, keys)
    (phys, hidden, _), (obs_t, act_t, rew_t, cost_t) = jax.lax.scan(
        step, (phys, hidden, ou0), xs
    )
    return DayRollout(
        obs_seq=jnp.swapaxes(obs_t, 0, 1),            # [A, T, 4]
        act_seq=jnp.swapaxes(act_t, 0, 1)[..., None],  # [A, T, 1]
        reward_seq=jnp.swapaxes(rew_t, 0, 1),          # [A, T]
        cost_eur=jnp.sum(cost_t),
        phys=phys,
        hidden=hidden,
    )


def _day_arrays(arrays: EpisodeArrays, d: int) -> EpisodeArrays:
    """Day ``d``'s slice of a multi-day episode array set."""
    s = slice(d * SLOTS_PER_DAY, (d + 1) * SLOTS_PER_DAY)
    return EpisodeArrays(*(a[s] for a in arrays))


class RecurrentTrainResult(NamedTuple):
    state: RecurrentDDPGState
    day_rewards: np.ndarray   # [episodes] mean day reward per agent
    day_costs: np.ndarray     # [episodes] community day cost [€]
    losses: np.ndarray        # [episodes - 1] critic loss per learn step


def train_recurrent_community(
    cfg: ExperimentConfig,
    episodes: int,
    key: jax.Array,
    traces=None,
    telemetry=None,
) -> RecurrentTrainResult:
    """Train the recurrent day-granular DDPG on the community physics.

    One episode = one day (cycled over the trace set's days). Day ``e``
    learns from day ``e-1``'s rollout with day ``e``'s observations as the
    bootstrapped next-day sequence — the day-granular TD(0) of
    ``recurrent_ddpg_learn``. Deterministic under ``key``.
    """
    if episodes < 2:
        raise ValueError(f"episodes must be >= 2 (TD needs a next day), got {episodes}")
    if traces is None:
        traces = synthetic_traces()
    rng = np.random.default_rng(cfg.train.seed)
    ratings = make_ratings(cfg, rng)
    arrays = build_episode_arrays(cfg, traces, ratings)
    n_days = arrays.time.shape[0] // SLOTS_PER_DAY
    if n_days < 1:
        raise ValueError("trace set shorter than one day")

    key, k_init, k_phys = jax.random.split(key, 3)
    state = recurrent_ddpg_init(cfg.ddpg, k_init, seq_len=SLOTS_PER_DAY)
    phys = init_physical(cfg, k_phys)

    rollout = jax.jit(
        lambda p, ph, day, k: rollout_day(cfg, p, ph, day, ratings, k)
    )
    learn = jax.jit(
        lambda st, o, a, r, no: recurrent_ddpg_learn(cfg.ddpg, st, o, a, r, no)
    )

    day_rewards, day_costs, losses = [], [], []
    prev: Optional[DayRollout] = None
    for ep in range(episodes):
        day = _day_arrays(arrays, ep % n_days)
        k_ep = jax.random.fold_in(key, ep)
        ro = rollout(state.actor, phys, day, k_ep)
        phys = ro.phys
        if prev is not None:
            day_reward = jnp.sum(prev.reward_seq, axis=-1)  # [A]
            state, loss = learn(
                state, prev.obs_seq, prev.act_seq, day_reward, ro.obs_seq
            )
            # host-sync: per-episode scalar readback — the recurrent driver
            # is day-granular (96 slots per dispatch), not slot-granular;
            # one scalar per day is not the pipeline-killing class.
            losses.append(float(loss))
        mean_r = float(jnp.mean(jnp.sum(ro.reward_seq, axis=-1)))  # host-sync: progress scalar
        cost = float(ro.cost_eur)  # host-sync: progress scalar
        day_rewards.append(mean_r)
        day_costs.append(cost)
        if telemetry is not None:
            telemetry.event(
                "recurrent_progress", episode=ep,
                day_reward=round(mean_r, 4), day_cost_eur=round(cost, 4),
            )
        prev = ro
    return RecurrentTrainResult(
        state=state,
        day_rewards=np.asarray(day_rewards),
        day_costs=np.asarray(day_costs),
        losses=np.asarray(losses),
    )


def recurrent_checkpoint_dir(model_dir: str, setting: str) -> str:
    from p2pmicrogrid_tpu.train.checkpoint import checkpoint_dir

    return checkpoint_dir(model_dir, setting, "ddpg_recurrent")


def save_recurrent_checkpoint(
    model_dir: str, cfg: ExperimentConfig, state: RecurrentDDPGState,
    episode: int,
) -> str:
    """Persist under the standard ``models_ddpg_recurrent/<setting>`` layout
    so ``export-bundle --implementation ddpg_recurrent`` resolves it like
    any other checkpoint (template-free ``restore_raw`` read)."""
    from p2pmicrogrid_tpu.train.checkpoint import save_checkpoint

    return save_checkpoint(
        recurrent_checkpoint_dir(model_dir, cfg.setting), state, episode,
        cfg=cfg,
    )
