"""Adapters from the models layer to the env's ``Policy`` interface.

The reference binds actors to agents by subclassing (``QAgent``/``DQNAgent``
wrap ``QActor``/``ActorModel`` + ``Trainer``, agent.py:255-350). Here a policy
is three pure closures over the experiment config; the policy *state* is the
corresponding model NamedTuple, selected by ``TrainConfig.implementation``
exactly like the reference's ``setup.implementation`` switch
(community.py:241-245).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from p2pmicrogrid_tpu.config import ExperimentConfig
from p2pmicrogrid_tpu.envs.community import Policy
from p2pmicrogrid_tpu.models import (
    ddpg_act,
    ddpg_decay,
    ddpg_init,
    ddpg_update,
    dqn_act,
    dqn_decay,
    dqn_init,
    dqn_update,
    tabular_act,
    tabular_decay,
    tabular_init,
    tabular_update,
)
from p2pmicrogrid_tpu.models.dqn import ACTION_VALUES


def make_tabular_policy(cfg: ExperimentConfig) -> Policy:
    """Tabular Q-learning (QAgent, agent.py:255-298)."""
    q = cfg.qlearning

    def act(pol_state, obs, prev_frac, key, explore):
        action, qv = tabular_act(q, pol_state, obs, key, explore)
        return ACTION_VALUES[action], action.astype(jnp.float32), qv, pol_state

    def learn(pol_state, obs, aux, reward, next_obs, key):
        pol_state = tabular_update(
            q, pol_state, obs, aux.astype(jnp.int32), reward, next_obs
        )
        return pol_state, jnp.zeros_like(reward)  # QAgent.train returns 0 loss

    return Policy(act=act, learn=learn, decay=lambda s: tabular_decay(q, s))


def make_dqn_policy(cfg: ExperimentConfig) -> Policy:
    """Per-agent DQN (DQNAgent, agent.py:301-342)."""
    d = cfg.dqn

    def act(pol_state, obs, prev_frac, key, explore):
        action, qv = dqn_act(d, pol_state, obs, key, explore)
        return ACTION_VALUES[action], action.astype(jnp.float32), qv, pol_state

    def learn(pol_state, obs, aux, reward, next_obs, key):
        return dqn_update(
            d, pol_state, obs, aux.astype(jnp.int32), reward, next_obs, key
        )

    return Policy(act=act, learn=learn, decay=lambda s: dqn_decay(d, s))


def make_ddpg_policy(cfg: ExperimentConfig) -> Policy:
    """Continuous-action actor-critic (capability of rl_backup.py)."""
    d = cfg.ddpg

    def act(pol_state, obs, prev_frac, key, explore):
        frac, qv, pol_state = ddpg_act(d, pol_state, obs, key, explore)
        return frac, frac, qv, pol_state

    def learn(pol_state, obs, aux, reward, next_obs, key):
        return ddpg_update(d, pol_state, obs, aux, reward, next_obs, key)

    return Policy(act=act, learn=learn, decay=lambda s: ddpg_decay(d, s))


_FACTORIES = {
    "tabular": make_tabular_policy,
    "dqn": make_dqn_policy,
    "ddpg": make_ddpg_policy,
}


def make_policy(cfg: ExperimentConfig) -> Policy:
    """Select by ``TrainConfig.implementation`` (setup.py:36,
    community.py:241-245)."""
    try:
        return _FACTORIES[cfg.train.implementation](cfg)
    except KeyError:
        raise ValueError(
            f"unknown implementation {cfg.train.implementation!r}; "
            f"expected one of {sorted(_FACTORIES)}"
        ) from None


def init_policy_state(cfg: ExperimentConfig, key: jax.Array):
    """Fresh learner state for the configured implementation."""
    impl = cfg.train.implementation
    n = cfg.sim.n_agents
    if impl == "tabular":
        return tabular_init(cfg.qlearning, n)
    if impl == "dqn":
        return dqn_init(cfg.dqn, n, key)
    if impl == "ddpg":
        return ddpg_init(cfg.ddpg, n, key)
    raise ValueError(f"unknown implementation {impl!r}")
