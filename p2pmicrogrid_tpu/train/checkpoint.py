"""Crash-safe checkpoint/resume via Orbax + an integrity manifest.

The reference persists each agent's actor separately — tabular Q as ``.npy``
(rl.py:83-87), DQN as Keras weight files plus ``_target`` copies
(rl.py:164-168,278-282) — named by the experiment setting string
(agent.py:248-252), saved every ``save_episodes`` episodes
(community.py:290-298). Here the unit of persistence is the whole community
learner state (one PyTree: all agents' params/targets/optimizers/replay plus
the episode counter), which restores atomically — no per-agent file skew.

Durability contract (the training half of serve/faults.py's resilience
story; see README "Resilient training"):

* **Atomic saves.** ``save_checkpoint`` writes the Orbax tree to a temp
  directory, reads it BACK from disk and verifies a content digest against
  the in-memory state, writes a ``p2p_manifest.json`` (tree structure,
  shapes/dtypes, sha256 content digest, ``config_hash``, git_rev, RNG key,
  episode), fsyncs, and only then renames the temp dir to ``ep_<episode>``
  and prunes older steps. A SIGKILL at ANY instant leaves either the old
  verified steps or old + new — never zero usable checkpoints (the
  pre-rewrite code pruned before any verification, so a crash mid-save
  stranded the run).

* **Verified restores.** ``latest_checkpoint``/``restore_checkpoint``/
  ``restore_raw`` skip incomplete or digest-mismatched steps (and malformed
  ``ep_*`` names) with a warning and fall back to the newest step that
  verifies. Manifest-less steps written by older framework versions are
  accepted with a warning (no digest to check).

* **Exact resume.** The payload optionally carries the host RNG-key chain
  (``rng_key``) and JSON-serializable ``extra`` state (HealthMonitor basin
  record, ...). ``restore_resume_state`` returns everything, so a resumed
  ``train_community`` run replays the surviving episodes bit-identically to
  an uninterrupted one (train/resilience.py; tests/test_resilience.py).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import warnings
from typing import NamedTuple, Optional, Tuple

import jax
import numpy as np

MANIFEST_NAME = "p2p_manifest.json"
MANIFEST_FORMAT_VERSION = 1
_TMP_PREFIX = "_tmp_ep_"


class CheckpointCorrupt(RuntimeError):
    """A step failed integrity verification (digest/manifest/readability)."""


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def checkpoint_dir(base_dir: str, setting: str, implementation: str) -> str:
    """Directory naming mirrors the reference's ``models_{impl}/{setting}``
    layout (rl.py:84-87)."""
    return os.path.join(
        os.path.abspath(base_dir), f"models_{implementation}", setting.replace("-", "_")
    )


# --- content digest ----------------------------------------------------------


def _plain(tree):
    """Normalize a payload tree to nested ``{str: ... | np.ndarray}`` form.

    Orbax restores NamedTuples as field-keyed dicts, tuples as lists, and
    EMPTY containers (e.g. optax's ``EmptyState``) as ``None``; the digest
    must not depend on which side of those round trips a tree is on, so
    both the in-memory payload and the read-back are normalized through
    this before hashing (``None`` and empty containers both become ``{}``).
    """
    if tree is None:
        return {}
    fields = getattr(tree, "_fields", None)
    if fields is not None:
        return {f: _plain(getattr(tree, f)) for f in fields}
    if isinstance(tree, dict):
        return {str(k): _plain(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return {str(i): _plain(v) for i, v in enumerate(tree)}
    return np.asarray(tree)


def _walk_leaves(plain, path=""):
    if isinstance(plain, dict):
        for k in sorted(plain):
            yield from _walk_leaves(plain[k], f"{path}/{k}" if path else k)
    else:
        yield path, plain


def tree_digest(payload) -> Tuple[str, dict]:
    """sha256 content digest + shape/dtype spec of a payload tree.

    Leaves are hashed in sorted-path order as (path, dtype, shape, bytes) —
    bit-exact: two payloads digest equal iff every leaf is bit-identical.
    Returns ``("sha256:<hex>", {path: {"shape": [...], "dtype": str}})``.
    """
    h = hashlib.sha256()
    spec: dict = {}
    for path, leaf in _walk_leaves(_plain(payload)):
        arr = np.ascontiguousarray(leaf)
        spec[path] = {"shape": list(arr.shape), "dtype": str(arr.dtype)}
        h.update(path.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return f"sha256:{h.hexdigest()}", spec


# --- fsync helpers (best-effort on filesystems without dir fsync) ------------


def _fsync_file(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    _fsync_file(path)


def _fsync_tree(root: str) -> None:
    for dirpath, _dirnames, filenames in os.walk(root):
        for f in filenames:
            _fsync_file(os.path.join(dirpath, f))
        _fsync_dir(dirpath)


# --- manifest ----------------------------------------------------------------


def load_manifest(step_path: str) -> Optional[dict]:
    """The step's integrity manifest, or ``None`` for a legacy (pre-manifest)
    step. Raises ``CheckpointCorrupt`` on an unreadable/alien manifest."""
    mpath = os.path.join(step_path, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath) as f:
            m = json.load(f)
    except (OSError, json.JSONDecodeError) as err:
        raise CheckpointCorrupt(f"{step_path}: unreadable manifest ({err})")
    if not isinstance(m, dict) or m.get("kind") != "checkpoint_manifest":
        raise CheckpointCorrupt(f"{step_path}: {MANIFEST_NAME} is not a checkpoint manifest")
    return m


def _verify_step(step_path: str) -> Tuple[Optional[dict], Optional[dict]]:
    """``(manifest, raw_payload)`` after full verification of one step.

    Reads the payload back from disk, recomputes the content digest and
    compares it to the manifest's; the verified raw tree is returned so
    restore paths reuse it instead of paying a second disk read + Orbax
    deserialization (replay buffers dominate the step size). Legacy
    manifest-less steps return ``(None, None)`` — nothing to check, payload
    unread. Raises ``CheckpointCorrupt`` on mismatch or unreadable payload.
    """
    manifest = load_manifest(step_path)
    if manifest is None:
        return None, None
    try:
        raw = _checkpointer().restore(step_path)
    except Exception as err:  # orbax raises various types on partial trees
        raise CheckpointCorrupt(f"{step_path}: payload unreadable ({err})")
    # The manifest itself is not part of the Orbax tree; orbax restores only
    # what it saved, so no exclusion needed.
    digest, _ = tree_digest(raw)
    expected = manifest.get("digest")
    if digest != expected:
        raise CheckpointCorrupt(
            f"{step_path}: content digest mismatch (manifest {expected}, "
            f"disk {digest}) — corrupted or partially-written step"
        )
    if int(manifest.get("episode", -1)) != int(np.asarray(raw.get("episode", -2))):
        raise CheckpointCorrupt(
            f"{step_path}: manifest episode {manifest.get('episode')} != "
            f"payload episode {raw.get('episode')}"
        )
    return manifest, raw


def verify_checkpoint(step_path: str) -> Optional[dict]:
    """Full integrity verification of one step directory; returns the
    manifest (``None`` for a legacy manifest-less step). Raises
    ``CheckpointCorrupt`` on mismatch or unreadable payload."""
    manifest, _raw = _verify_step(step_path)
    return manifest


# --- step listing ------------------------------------------------------------


def _steps_newest_first(path: str):
    """``(episode, step_path)`` for every well-formed ``ep_*`` dir, newest
    first. Malformed names (``ep_banana``) are skipped with a warning instead
    of crashing the listing (stray dirs must not take resume down)."""
    if not os.path.isdir(path):
        return []
    steps = []
    for d in os.listdir(path):
        if not d.startswith("ep_"):
            continue
        try:
            ep = int(d.split("_", 1)[1])
        except (IndexError, ValueError):
            warnings.warn(
                f"skipping malformed checkpoint entry {d!r} under {path} "
                "(not an ep_<int> step directory)",
                stacklevel=3,
            )
            continue
        steps.append((ep, os.path.join(path, d)))
    steps.sort(key=lambda t: t[0], reverse=True)
    return steps


def _verified_steps(path: str):
    """Yield ``(episode, step_path, manifest | None, raw | None)`` newest
    first, full-verifying each step and warning-and-skipping the corrupt
    ones. ``raw`` is the already-deserialized payload of a verified
    manifest-bearing step, for restore paths to reuse."""
    for ep, step in _steps_newest_first(path):
        try:
            manifest, raw = _verify_step(step)
        except CheckpointCorrupt as err:
            warnings.warn(
                f"skipping corrupt checkpoint step: {err} — falling back to "
                "the next newest step",
                stacklevel=3,
            )
            continue
        yield ep, step, manifest, raw


def latest_checkpoint(path: str, verify: bool = True) -> Optional[str]:
    """Newest restorable step under ``path``, or ``None``.

    ``verify`` (default) runs the full digest check and falls back past
    corrupt/incomplete steps; ``verify=False`` is the cheap listing (name
    order only — callers that re-verify at restore time).
    """
    if verify:
        for _ep, step, _m, _raw in _verified_steps(path):
            return step
        return None
    steps = _steps_newest_first(path)
    return steps[0][1] if steps else None


# --- save --------------------------------------------------------------------


def save_checkpoint(
    path: str,
    pol_state,
    episode: int,
    keep_old: bool = False,
    rng_key=None,
    extra: Optional[dict] = None,
    cfg=None,
    keep_last: int = 2,
) -> str:
    """Atomically write the learner state + episode counter; returns the
    step path.

    Write-to-temp → read-back digest verification → manifest → fsync →
    atomic rename → prune. The previous steps are ONLY pruned after the new
    step has passed read-back verification and been renamed into place, so a
    crash at any instant leaves at least one restorable checkpoint.

    ``rng_key`` (the host key chain at this episode boundary) and ``extra``
    (JSON-serializable resume state, e.g. the HealthMonitor record) make the
    step exactly resumable (``restore_resume_state``). ``cfg`` stamps
    ``config_hash`` into the manifest so checkpoints join the telemetry
    warehouse. ``keep_last`` newest steps survive the prune (default 2: the
    newest step plus one fallback for corrupt-step recovery); ``keep_old``
    keeps everything. Steps with a HIGHER episode than this save (stale
    leftovers of a previous, longer run) are always pruned — they must not
    shadow the new save.
    """
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    ckptr = _checkpointer()
    step_name = f"ep_{episode}"
    step_path = os.path.join(path, step_name)
    tmp_path = os.path.join(path, f"{_TMP_PREFIX}{episode}_{os.getpid()}")

    # Stale temp dirs from previously-crashed saves: never restorable (no
    # ep_ prefix), reclaim the disk here. Only OUR pid's leftovers plus
    # clearly-abandoned ones (an hour stale) — the pid suffix exists so a
    # concurrent saver's in-flight temp is never yanked out from under its
    # read-back verification.
    import time as _time

    for d in os.listdir(path):
        if not d.startswith(_TMP_PREFIX):
            continue
        p = os.path.join(path, d)
        stale = False
        if d.endswith(f"_{os.getpid()}"):
            stale = True
        else:
            try:
                stale = _time.time() - os.path.getmtime(p) > 3600.0
            except OSError:
                pass
        if stale:
            shutil.rmtree(p, ignore_errors=True)

    payload = {
        "pol_state": jax.tree_util.tree_map(np.asarray, pol_state),
        "episode": episode,
    }
    if rng_key is not None:
        payload["rng_key"] = np.asarray(rng_key)
    digest, spec = tree_digest(payload)

    ckptr.save(tmp_path, payload, force=True)
    _verify_readback(tmp_path, digest)

    manifest = {
        "kind": "checkpoint_manifest",
        "format_version": MANIFEST_FORMAT_VERSION,
        "episode": int(episode),
        "payload_keys": sorted(payload),
        "rng_key": (
            None if rng_key is None else np.asarray(rng_key).tolist()
        ),
        "digest": digest,
        "tree": spec,
        "config_hash": None,
        "git_rev": None,
        "extra": extra or {},
    }
    if cfg is not None:
        from p2pmicrogrid_tpu.telemetry.registry import config_hash, git_rev

        manifest["config_hash"] = config_hash(cfg)
        manifest["git_rev"] = git_rev()
    mpath = os.path.join(tmp_path, MANIFEST_NAME)
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        try:
            os.fsync(f.fileno())
        except OSError:
            pass
    _fsync_tree(tmp_path)

    if os.path.exists(step_path):
        # Re-saving the same episode: the verified temp replaces it. Not
        # atomic against a concurrent reader of the SAME episode, but older
        # steps remain as fallback and the rename below is still atomic.
        shutil.rmtree(step_path, ignore_errors=True)
    os.rename(tmp_path, step_path)
    _fsync_dir(path)

    # Prune AFTER the new step is verified and in place (the pre-rewrite
    # hazard: prune-then-crash stranded the run with zero checkpoints).
    survivors = {step_name}
    kept_older = 0
    for ep, step in _steps_newest_first(path):
        base = os.path.basename(step)
        if base in survivors:
            continue
        if ep > episode:
            # A stale higher-episode dir from a previous run must not
            # survive and shadow this save.
            shutil.rmtree(step, ignore_errors=True)
            continue
        if keep_old or kept_older < max(keep_last, 1) - 1:
            kept_older += 1
            continue
        shutil.rmtree(step, ignore_errors=True)
    return step_path


def _verify_readback(tmp_path: str, expected_digest: str) -> None:
    """Read the just-written step back from disk and compare digests (the
    write barrier the prune waits on). Split out so tests can simulate a
    failing write path."""
    try:
        raw = _checkpointer().restore(tmp_path)
    except Exception as err:
        shutil.rmtree(tmp_path, ignore_errors=True)
        raise CheckpointCorrupt(
            f"checkpoint write verification failed: {tmp_path} unreadable "
            f"after save ({err}); previous checkpoints left untouched"
        )
    got, _ = tree_digest(raw)
    if got != expected_digest:
        shutil.rmtree(tmp_path, ignore_errors=True)
        raise CheckpointCorrupt(
            f"checkpoint write verification failed: read-back digest {got} "
            f"!= in-memory {expected_digest}; previous checkpoints left "
            "untouched"
        )


# --- restore -----------------------------------------------------------------


def restore_raw(path: str) -> Tuple[dict, int, str]:
    """Structure-free read of the newest VERIFIED checkpoint step under
    ``path``.

    The serving-export hook (serve/export.py): a bundle export needs ONLY
    the greedy parameter subtree, so it reads the checkpoint without a
    learner-state template — no optimizer/replay/target reconstruction, and
    the raw field-keyed dicts orbax returns are exactly what
    ``serve.export.greedy_params`` consumes. Corrupt steps are skipped with
    a warning (falls back to the next newest verified one). Returns
    ``(raw_pol_state, episode, step_path)``.
    """
    for _ep, step_path, _manifest, raw in _verified_steps(path):
        if raw is None:  # legacy manifest-less step: verification read nothing
            raw = _checkpointer().restore(step_path)
        if not isinstance(raw, dict) or "pol_state" not in raw:
            raise RuntimeError(
                f"checkpoint {step_path} has no 'pol_state' tree (root keys: "
                f"{sorted(raw) if isinstance(raw, dict) else type(raw).__name__}); "
                "not a checkpoint of this framework"
            )
        return raw["pol_state"], int(raw.get("episode", 0)), step_path
    raise FileNotFoundError(f"no restorable checkpoint under {path}")


def _graft_old_checkpoint(template, raw):
    """Rebuild ``template``'s structure from a raw orbax tree, filling leaves
    the checkpoint lacks with the template's init defaults.

    Forward-compatibility path for 0.x field additions (e.g. pre-0.2.0 DDPG
    checkpoints have no ``noise_scale``): a checkpoint whose tree is a strict
    SUBSET of the current state restores with the missing leaves at their
    init values. Returns ``(tree, grafted_paths, extra_keys)`` — any
    ``extra_keys`` (checkpoint fields the current state doesn't know) mean
    the file is from a *newer/different* version and must not be grafted.
    """
    grafted: list = []
    extra: list = []

    def walk(tpl, node, path):
        if node is None:
            if not jax.tree_util.tree_leaves(tpl):
                # An empty container (e.g. optax EmptyState) round-trips
                # through orbax as None: nothing is missing, don't flag it.
                return tpl
            grafted.append(path or "<root>")
            return tpl
        fields = getattr(tpl, "_fields", None)
        if fields is not None:  # NamedTuple: raw form is a field-keyed dict
            if not isinstance(node, dict):
                # A leaf where the template has a container is a structural
                # difference, not an older subset: refuse, don't reset.
                extra.append(f"{path} is {type(node).__name__}, expected mapping")
                return tpl
            extra.extend(f"{path}/{k}" for k in node if k not in fields)
            return type(tpl)(
                *(walk(getattr(tpl, f), node.get(f), f"{path}/{f}") for f in fields)
            )
        if isinstance(tpl, dict):
            if not isinstance(node, dict):
                extra.append(f"{path} is {type(node).__name__}, expected mapping")
                return tpl
            extra.extend(f"{path}/{k}" for k in node if k not in tpl)
            return {k: walk(v, node.get(k), f"{path}/{k}") for k, v in tpl.items()}
        if isinstance(tpl, (list, tuple)):
            if not isinstance(node, (list, tuple)):
                extra.append(f"{path} is {type(node).__name__}, expected sequence")
                return tpl
            seq = list(node)
            if len(seq) > len(tpl):
                extra.append(f"{path}[{len(tpl)}:{len(seq)}]")
                seq = seq[: len(tpl)]
            seq += [None] * (len(tpl) - len(seq))
            return type(tpl)(
                walk(t, n, f"{path}[{i}]") for i, (t, n) in enumerate(zip(tpl, seq))
            )
        # Leaf: dtype preserved from the template (orbax may widen scalars).
        if isinstance(node, (dict, list, tuple)):
            extra.append(f"{path} is a container, expected array leaf")
            return tpl
        tpl_arr = np.asarray(tpl)
        src = np.asarray(node)
        if src.dtype != tpl_arr.dtype and (
            src.dtype.itemsize > tpl_arr.dtype.itemsize
            or src.dtype.kind != tpl_arr.dtype.kind
        ):
            # A narrowing (or kind-changing) cast loses checkpoint precision
            # silently — surface it through the same warning channel as
            # missing fields so a lossy restore is visible (round-3 advisor).
            grafted.append(
                f"{path} dtype {src.dtype.name}->{tpl_arr.dtype.name} (narrowed)"
            )
        arr = src.astype(tpl_arr.dtype) if src.dtype != tpl_arr.dtype else src
        if arr.shape != tpl_arr.shape:
            extra.append(f"{path} shape {arr.shape} != {tpl_arr.shape}")
        return arr

    return walk(template, raw, ""), grafted, extra


def _restore_step(
    step_path: str, template_pol_state, manifest: Optional[dict], raw=None
):
    """Restore one (already-verified) step against the learner-state
    template. Returns the full restored payload dict with ``pol_state``
    rebuilt into the template's PyTree structure.

    ``raw`` is the payload tree the digest verification already
    deserialized: when present, the graft walker maps it onto the template
    (field order, dtype preservation, subset grafting) with NO second disk
    read; legacy manifest-less steps (``raw=None``) keep the Orbax
    item-template restore.
    """
    ckptr = _checkpointer()
    template = {
        "pol_state": jax.tree_util.tree_map(np.asarray, template_pol_state),
        "episode": 0,
    }
    if raw is not None:
        if not isinstance(raw, dict) or "pol_state" not in raw:
            raise RuntimeError(
                f"checkpoint {step_path} has no 'pol_state' tree (root keys: "
                f"{sorted(raw) if isinstance(raw, dict) else type(raw).__name__}); "
                "not a checkpoint of this framework"
            )
        pol_state, grafted, extra = _graft_old_checkpoint(
            template["pol_state"], raw["pol_state"]
        )
        if extra:
            raise RuntimeError(
                f"checkpoint {step_path} does not match the current learner "
                f"state structure and is not an older-version subset "
                f"(unknown fields: {extra[:5]}); delete it and retrain, or "
                "restore with the matching version"
            )
        if grafted:
            warnings.warn(
                f"checkpoint {step_path} is an older-version state "
                f"({grafted}); missing fields restored at their init "
                "defaults, narrowed dtypes cast to the template dtype",
                stacklevel=2,
            )
        restored = dict(raw)
        restored["pol_state"] = pol_state
        restored.setdefault("episode", 0)
        return _rebuild_payload(restored, template_pol_state)
    payload_keys = (manifest or {}).get("payload_keys") or ["episode", "pol_state"]
    if "rng_key" in payload_keys:
        rk = (manifest or {}).get("rng_key")
        template["rng_key"] = (
            np.zeros(np.shape(rk), np.uint32) if rk is not None else np.zeros(2, np.uint32)
        )
    try:
        restored = ckptr.restore(step_path, item=template)
    except Exception as e:  # orbax raises various types on tree mismatch
        try:
            raw = ckptr.restore(step_path)  # structure-free read
        except Exception:
            # Corrupted/partial checkpoint: not even readable without a
            # template — keep the actionable message.
            raise CheckpointCorrupt(
                f"checkpoint {step_path} cannot be read (corrupted or "
                f"partial save?); delete it and retrain. Original error: {e}"
            ) from e
        if not isinstance(raw, dict) or "pol_state" not in raw:
            # A root without pol_state is another tool's checkpoint entirely
            # — grafting would "restore" a fresh init and call it success.
            raise RuntimeError(
                f"checkpoint {step_path} has no 'pol_state' tree (root keys: "
                f"{sorted(raw) if isinstance(raw, dict) else type(raw).__name__}); "
                f"not a checkpoint of this framework. Original error: {e}"
            ) from e
        pol_state, grafted, extra = _graft_old_checkpoint(
            template["pol_state"], raw["pol_state"]
        )
        if extra or not grafted:
            raise RuntimeError(
                f"checkpoint {step_path} does not match the current learner "
                f"state structure and is not an older-version subset "
                f"(unknown fields: {extra[:5]}); delete it and retrain, or "
                f"restore with the matching version. Original error: {e}"
            ) from e
        warnings.warn(
            f"checkpoint {step_path} is an older-version state ({grafted}); "
            f"missing fields restored at their init defaults, narrowed "
            f"dtypes cast to the template dtype",
            stacklevel=2,
        )
        restored = dict(raw)
        restored["pol_state"] = pol_state
        restored["episode"] = raw.get("episode", 0)
    return _rebuild_payload(restored, template_pol_state)


def _rebuild_payload(restored: dict, template_pol_state) -> dict:
    """Rebuild the original NamedTuple/PyTree structure with restored
    leaves (the graft walker / item restore already put them in template
    field order)."""
    _, treedef = jax.tree_util.tree_flatten(template_pol_state)
    restored_leaves = jax.tree_util.tree_leaves(restored["pol_state"])
    restored["pol_state"] = jax.tree_util.tree_unflatten(treedef, restored_leaves)
    return restored


def _iter_restorable(path: str):
    """``(episode, step_path, manifest, raw)`` newest-first over verified
    steps, warning when a manifest-less legacy step is accepted
    unverified."""
    any_step = False
    for ep, step, manifest, raw in _verified_steps(path):
        any_step = True
        if manifest is None:
            warnings.warn(
                f"checkpoint {step} predates integrity manifests; restoring "
                "without digest verification",
                stacklevel=3,
            )
        yield ep, step, manifest, raw
    if not any_step:
        raise FileNotFoundError(f"no restorable checkpoint under {path}")


def restore_checkpoint(path: str, template_pol_state) -> Tuple[object, int]:
    """Restore (pol_state, episode) from the newest VERIFIED step under
    ``path``, falling back past corrupt/incomplete steps with a warning.

    ``template_pol_state`` provides the PyTree structure/dtypes (e.g. a fresh
    ``init_policy_state`` result). Checkpoints written by an older framework
    version whose state is a strict subset of the current one (fields added
    since, e.g. DDPG ``noise_scale`` in 0.2.0) restore with the missing
    leaves grafted at their template (init) values, with a warning.
    """
    last_err: Optional[Exception] = None
    for _ep, step, manifest, raw in _iter_restorable(path):
        try:
            restored = _restore_step(step, template_pol_state, manifest, raw)
        except CheckpointCorrupt as err:
            warnings.warn(f"skipping corrupt checkpoint step: {err}", stacklevel=2)
            last_err = err
            continue
        return restored["pol_state"], int(np.asarray(restored["episode"]))
    raise last_err or FileNotFoundError(f"no restorable checkpoint under {path}")


class ResumeState(NamedTuple):
    """Everything a checkpoint knows, for exact resume (train/resilience.py)."""

    pol_state: object
    episode: int
    rng_key: Optional[np.ndarray]   # host key chain at the boundary, or None
    extra: dict                     # JSON extra state (health record, ...)
    step_path: str
    manifest: Optional[dict]


def restore_resume_state(path: str, template_pol_state) -> ResumeState:
    """``restore_checkpoint`` plus the resume payload: RNG-key chain and the
    manifest's ``extra`` record. ``rng_key`` is ``None`` for checkpoints
    saved without one (legacy / scenario paths) — callers fall back to the
    fold_in resume schedule there."""
    last_err: Optional[Exception] = None
    for _ep, step, manifest, raw in _iter_restorable(path):
        try:
            restored = _restore_step(step, template_pol_state, manifest, raw)
        except CheckpointCorrupt as err:
            warnings.warn(f"skipping corrupt checkpoint step: {err}", stacklevel=2)
            last_err = err
            continue
        rng_key = restored.get("rng_key")
        if rng_key is not None:
            rng_key = np.asarray(rng_key)
        return ResumeState(
            pol_state=restored["pol_state"],
            episode=int(np.asarray(restored["episode"])),
            rng_key=rng_key,
            extra=(manifest or {}).get("extra") or {},
            step_path=step,
            manifest=manifest,
        )
    raise last_err or FileNotFoundError(f"no restorable checkpoint under {path}")
