"""Checkpoint/resume via Orbax.

The reference persists each agent's actor separately — tabular Q as ``.npy``
(rl.py:83-87), DQN as Keras weight files plus ``_target`` copies
(rl.py:164-168,278-282) — named by the experiment setting string
(agent.py:248-252), saved every ``save_episodes`` episodes
(community.py:290-298). Here the unit of persistence is the whole community
learner state (one PyTree: all agents' params/targets/optimizers/replay plus
the episode counter), which restores atomically — no per-agent file skew.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import numpy as np


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def checkpoint_dir(base_dir: str, setting: str, implementation: str) -> str:
    """Directory naming mirrors the reference's ``models_{impl}/{setting}``
    layout (rl.py:84-87)."""
    return os.path.join(
        os.path.abspath(base_dir), f"models_{implementation}", setting.replace("-", "_")
    )


def save_checkpoint(
    path: str, pol_state, episode: int, keep_old: bool = False
) -> str:
    """Write the learner state + episode counter. Returns the step path."""
    ckptr = _checkpointer()
    step_path = os.path.join(os.path.abspath(path), f"ep_{episode}")
    payload = {
        "pol_state": jax.tree_util.tree_map(np.asarray, pol_state),
        "episode": episode,
    }
    ckptr.save(step_path, payload, force=True)
    if not keep_old:
        # Prune everything EXCEPT the step just written (not the max-numbered
        # one: a stale higher-episode dir from a previous run must not survive
        # and shadow this save).
        import shutil

        keep = os.path.basename(step_path)
        for d in os.listdir(path):
            if d.startswith("ep_") and d != keep:
                shutil.rmtree(os.path.join(path, d), ignore_errors=True)
    return step_path


def latest_checkpoint(path: str) -> Optional[str]:
    if not os.path.isdir(path):
        return None
    steps = [d for d in os.listdir(path) if d.startswith("ep_")]
    if not steps:
        return None
    return os.path.join(path, max(steps, key=lambda d: int(d.split("_")[1])))


def restore_raw(path: str) -> Tuple[dict, int, str]:
    """Structure-free read of the newest checkpoint step under ``path``.

    The serving-export hook (serve/export.py): a bundle export needs ONLY
    the greedy parameter subtree, so it reads the checkpoint without a
    learner-state template — no optimizer/replay/target reconstruction, and
    the raw field-keyed dicts orbax returns are exactly what
    ``serve.export.greedy_params`` consumes. Returns
    ``(raw_pol_state, episode, step_path)``.
    """
    step_path = latest_checkpoint(path)
    if step_path is None:
        raise FileNotFoundError(f"no checkpoint under {path}")
    raw = _checkpointer().restore(step_path)
    if not isinstance(raw, dict) or "pol_state" not in raw:
        raise RuntimeError(
            f"checkpoint {step_path} has no 'pol_state' tree (root keys: "
            f"{sorted(raw) if isinstance(raw, dict) else type(raw).__name__}); "
            "not a checkpoint of this framework"
        )
    return raw["pol_state"], int(raw.get("episode", 0)), step_path


def _graft_old_checkpoint(template, raw):
    """Rebuild ``template``'s structure from a raw orbax tree, filling leaves
    the checkpoint lacks with the template's init defaults.

    Forward-compatibility path for 0.x field additions (e.g. pre-0.2.0 DDPG
    checkpoints have no ``noise_scale``): a checkpoint whose tree is a strict
    SUBSET of the current state restores with the missing leaves at their
    init values. Returns ``(tree, grafted_paths, extra_keys)`` — any
    ``extra_keys`` (checkpoint fields the current state doesn't know) mean
    the file is from a *newer/different* version and must not be grafted.
    """
    grafted: list = []
    extra: list = []

    def walk(tpl, node, path):
        if node is None:
            grafted.append(path or "<root>")
            return tpl
        fields = getattr(tpl, "_fields", None)
        if fields is not None:  # NamedTuple: raw form is a field-keyed dict
            if not isinstance(node, dict):
                # A leaf where the template has a container is a structural
                # difference, not an older subset: refuse, don't reset.
                extra.append(f"{path} is {type(node).__name__}, expected mapping")
                return tpl
            extra.extend(f"{path}/{k}" for k in node if k not in fields)
            return type(tpl)(
                *(walk(getattr(tpl, f), node.get(f), f"{path}/{f}") for f in fields)
            )
        if isinstance(tpl, dict):
            if not isinstance(node, dict):
                extra.append(f"{path} is {type(node).__name__}, expected mapping")
                return tpl
            extra.extend(f"{path}/{k}" for k in node if k not in tpl)
            return {k: walk(v, node.get(k), f"{path}/{k}") for k, v in tpl.items()}
        if isinstance(tpl, (list, tuple)):
            if not isinstance(node, (list, tuple)):
                extra.append(f"{path} is {type(node).__name__}, expected sequence")
                return tpl
            seq = list(node)
            if len(seq) > len(tpl):
                extra.append(f"{path}[{len(tpl)}:{len(seq)}]")
                seq = seq[: len(tpl)]
            seq += [None] * (len(tpl) - len(seq))
            return type(tpl)(
                walk(t, n, f"{path}[{i}]") for i, (t, n) in enumerate(zip(tpl, seq))
            )
        # Leaf: dtype preserved from the template (orbax may widen scalars).
        if isinstance(node, (dict, list, tuple)):
            extra.append(f"{path} is a container, expected array leaf")
            return tpl
        tpl_arr = np.asarray(tpl)
        src = np.asarray(node)
        if src.dtype != tpl_arr.dtype and (
            src.dtype.itemsize > tpl_arr.dtype.itemsize
            or src.dtype.kind != tpl_arr.dtype.kind
        ):
            # A narrowing (or kind-changing) cast loses checkpoint precision
            # silently — surface it through the same warning channel as
            # missing fields so a lossy restore is visible (round-3 advisor).
            grafted.append(
                f"{path} dtype {src.dtype.name}->{tpl_arr.dtype.name} (narrowed)"
            )
        arr = src.astype(tpl_arr.dtype) if src.dtype != tpl_arr.dtype else src
        if arr.shape != tpl_arr.shape:
            extra.append(f"{path} shape {arr.shape} != {tpl_arr.shape}")
        return arr

    return walk(template, raw, ""), grafted, extra


def restore_checkpoint(path: str, template_pol_state) -> Tuple[object, int]:
    """Restore (pol_state, episode) from the newest step under ``path``.

    ``template_pol_state`` provides the PyTree structure/dtypes (e.g. a fresh
    ``init_policy_state`` result). Checkpoints written by an older framework
    version whose state is a strict subset of the current one (fields added
    since, e.g. DDPG ``noise_scale`` in 0.2.0) restore with the missing
    leaves grafted at their template (init) values, with a warning.
    """
    step_path = latest_checkpoint(path)
    if step_path is None:
        raise FileNotFoundError(f"no checkpoint under {path}")
    ckptr = _checkpointer()
    template = {
        "pol_state": jax.tree_util.tree_map(np.asarray, template_pol_state),
        "episode": 0,
    }
    try:
        restored = ckptr.restore(step_path, item=template)
    except Exception as e:  # orbax raises various types on tree mismatch
        try:
            raw = ckptr.restore(step_path)  # structure-free read
        except Exception:
            # Corrupted/partial checkpoint: not even readable without a
            # template — keep the actionable message.
            raise RuntimeError(
                f"checkpoint {step_path} cannot be read (corrupted or "
                f"partial save?); delete it and retrain. Original error: {e}"
            ) from e
        if not isinstance(raw, dict) or "pol_state" not in raw:
            # A root without pol_state is another tool's checkpoint entirely
            # — grafting would "restore" a fresh init and call it success.
            raise RuntimeError(
                f"checkpoint {step_path} has no 'pol_state' tree (root keys: "
                f"{sorted(raw) if isinstance(raw, dict) else type(raw).__name__}); "
                f"not a checkpoint of this framework. Original error: {e}"
            ) from e
        pol_state, grafted, extra = _graft_old_checkpoint(
            template["pol_state"], raw["pol_state"]
        )
        if extra or not grafted:
            raise RuntimeError(
                f"checkpoint {step_path} does not match the current learner "
                f"state structure and is not an older-version subset "
                f"(unknown fields: {extra[:5]}); delete it and retrain, or "
                f"restore with the matching version. Original error: {e}"
            ) from e
        import warnings

        warnings.warn(
            f"checkpoint {step_path} is an older-version state ({grafted}); "
            f"missing fields restored at their init defaults, narrowed "
            f"dtypes cast to the template dtype",
            stacklevel=2,
        )
        restored = {"pol_state": pol_state, "episode": raw.get("episode", 0)}
    # Rebuild the original NamedTuple/PyTree structure with restored leaves.
    _, treedef = jax.tree_util.tree_flatten(template_pol_state)
    restored_leaves = jax.tree_util.tree_leaves(restored["pol_state"])
    pol_state = jax.tree_util.tree_unflatten(treedef, restored_leaves)
    return pol_state, int(restored["episode"])
