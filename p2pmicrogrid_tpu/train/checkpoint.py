"""Checkpoint/resume via Orbax.

The reference persists each agent's actor separately — tabular Q as ``.npy``
(rl.py:83-87), DQN as Keras weight files plus ``_target`` copies
(rl.py:164-168,278-282) — named by the experiment setting string
(agent.py:248-252), saved every ``save_episodes`` episodes
(community.py:290-298). Here the unit of persistence is the whole community
learner state (one PyTree: all agents' params/targets/optimizers/replay plus
the episode counter), which restores atomically — no per-agent file skew.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import numpy as np


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def checkpoint_dir(base_dir: str, setting: str, implementation: str) -> str:
    """Directory naming mirrors the reference's ``models_{impl}/{setting}``
    layout (rl.py:84-87)."""
    return os.path.join(
        os.path.abspath(base_dir), f"models_{implementation}", setting.replace("-", "_")
    )


def save_checkpoint(
    path: str, pol_state, episode: int, keep_old: bool = False
) -> str:
    """Write the learner state + episode counter. Returns the step path."""
    ckptr = _checkpointer()
    step_path = os.path.join(os.path.abspath(path), f"ep_{episode}")
    payload = {
        "pol_state": jax.tree_util.tree_map(np.asarray, pol_state),
        "episode": episode,
    }
    ckptr.save(step_path, payload, force=True)
    if not keep_old:
        # Prune everything EXCEPT the step just written (not the max-numbered
        # one: a stale higher-episode dir from a previous run must not survive
        # and shadow this save).
        import shutil

        keep = os.path.basename(step_path)
        for d in os.listdir(path):
            if d.startswith("ep_") and d != keep:
                shutil.rmtree(os.path.join(path, d), ignore_errors=True)
    return step_path


def latest_checkpoint(path: str) -> Optional[str]:
    if not os.path.isdir(path):
        return None
    steps = [d for d in os.listdir(path) if d.startswith("ep_")]
    if not steps:
        return None
    return os.path.join(path, max(steps, key=lambda d: int(d.split("_")[1])))


def restore_checkpoint(path: str, template_pol_state) -> Tuple[object, int]:
    """Restore (pol_state, episode) from the newest step under ``path``.

    ``template_pol_state`` provides the PyTree structure/dtypes (e.g. a fresh
    ``init_policy_state`` result).
    """
    step_path = latest_checkpoint(path)
    if step_path is None:
        raise FileNotFoundError(f"no checkpoint under {path}")
    ckptr = _checkpointer()
    template = {
        "pol_state": jax.tree_util.tree_map(np.asarray, template_pol_state),
        "episode": 0,
    }
    try:
        restored = ckptr.restore(step_path, item=template)
    except Exception as e:  # orbax raises various types on tree mismatch
        raise RuntimeError(
            f"checkpoint {step_path} does not match the current learner state "
            f"structure (e.g. it was written by an older framework version "
            f"whose state had different fields); delete it and retrain, or "
            f"restore with the matching version. Original error: {e}"
        ) from e
    # Rebuild the original NamedTuple/PyTree structure with restored leaves.
    _, treedef = jax.tree_util.tree_flatten(template_pol_state)
    restored_leaves = jax.tree_util.tree_leaves(restored["pol_state"])
    pol_state = jax.tree_util.tree_unflatten(treedef, restored_leaves)
    return pol_state, int(restored["episode"])
