"""Runtime training-health surface for the flagship scenario paths.

The reference's one live health signal is the running training reward logged
to ``training_progress`` every decay window
(reference/microgrid/community.py:279-288, database.py:196-209). At the
chunked north-star scale that signal is noise-dominated AND structurally
blind: the shipped capped fast path has a measured metastable "don't-heat"
basin (artifacts/LEARNING_northstar_r04b_seed2_full.json) where the greedy
policy sells PV instead of heating — community COST goes negative (looks
great) while greedy REWARD craters to ~-1700 (comfort collapse, the exact
outcome the reference's reward exists to prevent, agent.py:225-232). Cost-only
or training-reward-only logging cannot see it.

This module makes the greedy held-out eval (previously only in
tools/learning_northstar.py) a first-class training surface:

- ``make_greedy_eval``   jitted greedy (explore=False) episode on a FIXED
                         held-out scenario set -> (community cost, reward).
- ``classify_health``    the measured basin/slide detector (thresholds
                         calibrated on the committed r04 seed curves).
- ``HealthMonitor``      stateful tracker: feeds evals to the classifier,
                         records alerts, serializes for artifacts/stores.
- ``train_chunked_with_health``  block-wise wrapper over
                         ``train_scenarios_chunked`` that evaluates every
                         ``eval_every`` episodes, logs cost AND reward, warns
                         on basin entry, and (opt-in) applies the measured
                         lr-boost mitigation until the policy escapes.

Detector calibration (all numbers from committed artifacts; values are
per-episode sums over ``slots_per_day`` slots, reward mean over agents, cost
summed over the community, both averaged over the held-out scenarios):

===========  ==========  ============  ====================================
state        cost (EUR)   reward        example
===========  ==========  ============  ====================================
healthy      ~1000-1700   -1 .. -2      seed 0 episodes 20-240
untrained    ~2400-4800   -600..-2600   every seed at episode 0 (cost HIGH)
slide        ~500-700     -50..-200     seed 3 episodes 60-100 (recovered)
basin        < 0          -1300..-1733  seed 2 episodes 40-200
===========  ==========  ============  ====================================

The discriminating signature is reward collapse WITH low/negative cost:
untrained policies also have terrible reward but their cost is high (they
heat badly AND trade badly), so the cost condition separates "still
learning" from "profiting by not heating".
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from p2pmicrogrid_tpu.config import ExperimentConfig
from p2pmicrogrid_tpu.envs import init_physical
from p2pmicrogrid_tpu.envs.community import (
    AgentRatings,
    slot_dynamics_batched,
)

# Per-slot reward thresholds (reward here is the per-episode sum over slots,
# so divide by slots_per_day before comparing). Healthy ~-0.01/slot; a deep
# comfort violation costs ~-10/slot (the x10 offset band penalty,
# ops/thermal.py); the basin sits at -14..-18/slot.
BASIN_REWARD_PER_SLOT = -2.0   # >=~20% of agent-slots in deep violation
SLIDE_REWARD_PER_SLOT = -0.25
# Cost conditions, relative to the episode-0 (untrained) greedy cost of the
# same run — scale-free across agent counts and tariffs.
BASIN_COST_FRAC = 0.10   # cost below 10% of untrained => "earning by not heating"
SLIDE_COST_FRAC = 0.50


def make_greedy_eval(
    cfg: ExperimentConfig,
    policy,
    ratings,
    s_eval: int = 8,
    eval_seed: int = 10_000,
    collect_device_metrics: bool = False,
) -> Callable[[object, jax.Array], Tuple[jax.Array, jax.Array]]:
    """Jitted greedy held-out eval: ``fn(pol_state, key) -> (cost, reward)``.

    One explore=False episode over a FIXED set of ``s_eval`` held-out
    scenarios (drawn once from ``eval_seed``, never trained on): returns the
    community cost (EUR, summed over slots+agents, scenario mean) and the
    greedy reward (summed over slots, mean over agents+scenarios) — the two
    numbers whose DIVERGENCE is the basin signature. Works for all three
    shared implementations; DDPG acts through its deterministic actor (no OU
    state is carried, matching tools/learning_northstar.py's evaluator).

    ``collect_device_metrics`` threads a ``telemetry.DeviceCounters`` total
    through the slot scan (NaN Q-values, comfort-band violations, market
    residual — accumulated in-program, one scalar transfer per call) and
    makes the eval return ``(cost, reward, counters)``.
    """
    from p2pmicrogrid_tpu.parallel.device_gen import device_episode_arrays

    eval_arrays = device_episode_arrays(
        cfg, jax.random.PRNGKey(eval_seed), ratings, s_eval
    )
    ratings_j = AgentRatings(*(jnp.asarray(a) for a in ratings))
    impl = cfg.train.implementation

    act_fn = None
    if impl == "ddpg":
        from p2pmicrogrid_tpu.models.ddpg import ddpg_shared_act

        def act_fn(p, obs_s, prev, round_key, ex):
            frac, q, _ = ddpg_shared_act(
                cfg.ddpg, p, obs_s, jnp.zeros(obs_s.shape[:2]),
                round_key, explore=False,
            )
            return frac, frac, q, ex

    if collect_device_metrics:
        from p2pmicrogrid_tpu.telemetry.device_metrics import (
            dc_add,
            dc_from_slot,
            dc_zero,
        )

    @jax.jit
    def greedy_eval(pol_state, key):
        k_phys, k_scan = jax.random.split(key)
        phys = jax.vmap(lambda k: init_physical(cfg, k))(
            jax.random.split(k_phys, s_eval)
        )
        xs = jax.tree_util.tree_map(
            lambda x: jnp.swapaxes(x, 0, 1), eval_arrays
        )
        xs = (xs.time, xs.t_out, xs.load_w, xs.pv_w,
              xs.next_time, xs.next_load_w, xs.next_pv_w)

        def slot(carry, xs_t):
            phys_s, kk, dc = carry
            kk, k_act = jax.random.split(kk)
            phys_s, _, out, _, _ = slot_dynamics_batched(
                cfg, policy, pol_state, phys_s, xs_t, k_act, ratings_j,
                explore=False, act_fn=act_fn,
            )
            if collect_device_metrics:
                dc = dc_add(dc, dc_from_slot(cfg, out))
            return (phys_s, kk, dc), (out.cost, out.reward)

        dc0 = dc_zero() if collect_device_metrics else None
        (_, _, dc), (cost, reward) = jax.lax.scan(
            slot, (phys, k_scan, dc0), xs
        )
        c = jnp.sum(cost, axis=(0, 2)).mean()
        r = jnp.sum(jnp.mean(reward, axis=-1), axis=0).mean()
        return (c, r, dc) if collect_device_metrics else (c, r)

    return greedy_eval


def classify_health(
    cost: float, reward: float, slots: int, initial_cost: float
) -> str:
    """Classify one greedy eval point: 'healthy' | 'slide' | 'basin'.

    ``initial_cost`` is the same run's episode-0 greedy cost (the untrained
    reference point); see the module docstring's calibration table.
    """
    r_slot = reward / max(slots, 1)
    ref = abs(initial_cost)
    if r_slot < BASIN_REWARD_PER_SLOT and cost < BASIN_COST_FRAC * ref:
        return "basin"
    if r_slot < SLIDE_REWARD_PER_SLOT and cost < SLIDE_COST_FRAC * ref:
        return "slide"
    return "healthy"


class HealthPoint(NamedTuple):
    episode: int
    greedy_cost_eur: float
    greedy_reward: float
    status: str


class HealthMonitor:
    """Tracks greedy held-out evals and flags comfort collapse.

    Feed it one ``update(episode, cost, reward)`` per eval; it classifies
    against the UNTRAINED-policy greedy cost (``initial_cost`` — taken from
    the first point when starting fresh, or measured explicitly on a fresh
    init when resuming, see ``train_chunked_with_health``), remembers basin
    entry/exit episodes, and prints a loud warning to stderr on every
    non-healthy point (an alert the user sees within one eval period of
    entry — the committed seed-2 curve enters between episodes 20 and 40
    and is flagged at the first in-basin eval).
    """

    def __init__(
        self, slots: int, warn_stream=None, initial_cost=None, telemetry=None
    ):
        self.slots = slots
        self.warn_stream = warn_stream if warn_stream is not None else sys.stderr
        self.points: list[HealthPoint] = []
        self.initial_cost: Optional[float] = (
            None if initial_cost is None else float(initial_cost)
        )
        self.basin_entries: list[int] = []   # first flagged episode per entry
        self.basin_exits: list[int] = []     # first healthy episode after one
        # Optional telemetry.Telemetry: every eval point and basin
        # entry/exit is emitted as an event, so alerts land in the SAME run
        # directory (metrics.jsonl) as the training metrics instead of a
        # bespoke side file.
        self.telemetry = telemetry

    @property
    def in_basin(self) -> bool:
        return len(self.basin_entries) > len(self.basin_exits)

    def update(self, episode: int, cost: float, reward: float) -> str:
        cost, reward = float(cost), float(reward)
        if self.initial_cost is None:
            self.initial_cost = cost
        status = classify_health(cost, reward, self.slots, self.initial_cost)
        was_in_basin = self.in_basin
        if self.telemetry is not None:
            self.telemetry.event(
                "health",
                episode=episode,
                greedy_cost_eur=cost,
                greedy_reward=reward,
                status=status,
            )
        if status == "basin" and not was_in_basin:
            self.basin_entries.append(episode)
            if self.telemetry is not None:
                self.telemetry.event(
                    "basin_alert",
                    episode=episode,
                    greedy_cost_eur=cost,
                    greedy_reward=reward,
                )
                self.telemetry.counter("health.basin_entries")
            print(
                f"HEALTH ALERT (episode {episode}): greedy reward "
                f"{reward:.0f} with community cost {cost:.0f} EUR — the "
                "policy is profiting by NOT heating (comfort collapse, the "
                "metastable don't-heat basin). Mitigation: --basin-mitigate "
                "lr-boost (default for chunked ddpg; requires --chunks > 1 "
                "— non-chunked runs should rerun chunked to mitigate; "
                "measured 4.25x dwell cut). Do NOT switch to lower lrs: "
                "the 10-seed sweep "
                "(artifacts/BASIN_STATS_r05.json) measured uncapped/half-lr "
                "runs entering MORE often and staying captured at the "
                "240-episode horizon — escape is lr-limited too.",
                file=self.warn_stream, flush=True,
            )
        elif status == "slide" and not was_in_basin:
            print(
                f"health warning (episode {episode}): greedy reward "
                f"{reward:.0f} at cost {cost:.0f} EUR — comfort degrading "
                "while cost falls; watching for basin entry.",
                file=self.warn_stream, flush=True,
            )
        elif status == "healthy" and was_in_basin:
            self.basin_exits.append(episode)
            if self.telemetry is not None:
                self.telemetry.event("basin_exit", episode=episode)
            print(
                f"health: recovered at episode {episode} (greedy reward "
                f"{reward:.0f}, cost {cost:.0f} EUR).",
                file=self.warn_stream, flush=True,
            )
        self.points.append(HealthPoint(episode, cost, reward, status))
        return status

    def to_dict(self) -> dict:
        return {
            "slots": self.slots,
            "initial_cost": self.initial_cost,
            "basin_entries": self.basin_entries,
            "basin_exits": self.basin_exits,
            "points": [p._asdict() for p in self.points],
        }

    @classmethod
    def from_dict(cls, d: dict, warn_stream=None, telemetry=None) -> "HealthMonitor":
        """Rebuild a monitor from ``to_dict()`` output — the exact-resume
        path (train/checkpoint.py stores the record in the step manifest's
        ``extra``): basin entry/exit bookkeeping and the untrained-cost
        calibration survive a preemption instead of being re-derived, so a
        resumed run classifies (and mitigates) exactly like the original
        would have."""
        m = cls(
            slots=int(d.get("slots", 0)),
            warn_stream=warn_stream,
            initial_cost=d.get("initial_cost"),
            telemetry=telemetry,
        )
        m.basin_entries = [int(e) for e in d.get("basin_entries", [])]
        m.basin_exits = [int(e) for e in d.get("basin_exits", [])]
        m.points = [
            HealthPoint(
                int(p["episode"]), float(p["greedy_cost_eur"]),
                float(p["greedy_reward"]), str(p["status"]),
            )
            for p in d.get("points", [])
        ]
        return m

    def emit_summary(self) -> None:
        """Serialize through the telemetry sink (one ``health_summary``
        event in the run's metrics.jsonl) — the replacement for callers
        hand-writing ``to_dict()`` to bespoke side files."""
        if self.telemetry is not None:
            d = self.to_dict()
            d.pop("points")  # every point is already an event of its own
            self.telemetry.event("health_summary", **d)


def untrained_reference_cost(
    cfg: ExperimentConfig, policy, greedy_eval, seed: int = 0
) -> float:
    """Greedy cost of a FRESHLY-initialized shared policy — the classifier's
    calibration reference. Needed when resuming: the restored policy's first
    eval reflects training already done, and seeding ``initial_cost`` from
    it would shrink the slide/basin cost thresholds by ~2-3x (they are
    fractions of the UNTRAINED cost)."""
    from p2pmicrogrid_tpu.parallel import init_shared_pol_state

    ref_ps = init_shared_pol_state(cfg, jax.random.PRNGKey(seed))
    # Cost is element 0 for both eval arities (collect_device_metrics
    # appends a counters element).
    out = greedy_eval(ref_ps, jax.random.PRNGKey(1))
    return float(out[0])


def _lr_boosted_cfg(cfg: ExperimentConfig, mult: float) -> ExperimentConfig:
    """Pin the auto-rule's effective lrs x ``mult`` (mitigation program).

    Same mechanism as tools/learning_northstar.py's NS_LR_MULT probes: scale
    the EFFECTIVE (pooled-batch-rule) lrs and disable the auto rule so the
    episode builder does not rescale them again.
    """
    from p2pmicrogrid_tpu.parallel.scenarios import auto_scale_ddpg_lrs

    scaled = auto_scale_ddpg_lrs(cfg)
    return dataclasses.replace(
        cfg,
        ddpg=dataclasses.replace(
            cfg.ddpg,
            actor_lr=scaled.ddpg.actor_lr * mult,
            critic_lr=scaled.ddpg.critic_lr * mult,
            lr_auto_scale=False,
        ),
    )


def train_chunked_with_health(
    cfg: ExperimentConfig,
    policy,
    pol_state,
    ratings,
    key: jax.Array,
    n_episodes: int,
    n_chunks: int,
    eval_every: int = 10,
    episode0: int = 0,
    episode_cb: Optional[Callable] = None,
    chunk_parallel: int = 1,
    mitigate: str = "warn",
    lr_boost: float = 3.0,
    monitor: Optional[HealthMonitor] = None,
    health_cb: Optional[Callable] = None,
    s_eval: int = 8,
    telemetry="auto",
    pipeline: bool = True,
    carry_sync: Optional[Callable] = None,
    results_db: Optional[str] = None,
    guard=None,
) -> Tuple[object, np.ndarray, np.ndarray, float, HealthMonitor]:
    """``train_scenarios_chunked`` with the health surface on.

    Runs the chunked trainer in blocks of ``eval_every`` episodes; between
    blocks the greedy held-out eval runs (cheap: ``s_eval`` scenarios vs
    n_chunks x S trained per episode — <1% overhead at the north star) and
    the monitor classifies it. ``mitigate``:

    - ``"warn"``  (default): alert on basin entry, keep training unchanged.
    - ``"lr-boost"``: while in the basin, train through an episode program
      with the effective lrs x ``lr_boost``. Rationale (measured, round 4):
      basin ENTRY time scales inversely with step size
      (artifacts/LEARNING_northstar_r04b_seed2_lr0.5.json), i.e. traversal
      of the flat don't-heat region is lr-limited — boosting lr while
      inside accelerates the same traversal outward; the normal program is
      restored at the first healthy eval, so steady-state semantics are
      unchanged for runs that never enter.

    ``health_cb(point: HealthPoint)`` fires after every eval (CLI uses it to
    log to the results store). Returns (pol_state, rewards, losses, seconds,
    monitor); rewards/losses concatenate the per-block outputs.

    ``telemetry``: a ``telemetry.Telemetry`` to emit through, ``None`` to
    disable, or ``"auto"`` (default) to create a run directory under
    ``artifacts/runs/`` (manifest + metrics JSONL + span trace + summary;
    suppressed by ``P2P_TELEMETRY=0``). Every eval point, basin alert and
    per-eval device-counter total (NaN Q-values, comfort violations, market
    residual — accumulated inside the jitted eval scan) is an event; train
    blocks and evals are spans. With telemetry on, the TRAINING episodes
    collect the same in-scan counters too (``device_counters`` events with
    ``phase: "train"``) plus the per-chunk replay fill fraction as the
    ``replay.fill_fraction`` gauge. An auto-created telemetry is closed
    (summary + Chrome trace written) before returning.

    ``results_db``: path to a results SQLite store — an auto-created
    telemetry additionally streams into its warehouse tables via a
    ``SqliteSink`` (the same ``--results-db`` contract the single-scenario
    ``train`` command has; a caller-supplied ``telemetry`` keeps its own
    sinks and ignores this).

    ``pipeline`` (default) runs the training blocks AND the block-boundary
    health evals through one shared async depth-2 drain: the eval is
    dispatched on the live device carry between blocks (before the next
    block's donating dispatch — device-side data dependence keeps it
    exact) and its host readback resolves lagged, so eval boundaries no
    longer stall dispatch — measurable at ``eval_every=1``, where the old
    per-boundary drain serialized every block on the host round trip. The
    drain turns synchronous automatically whenever something READS an
    eval before the next block may start: a divergence ``guard`` (its
    trip must precede the next block's checkpoint persist) or
    ``mitigate="lr-boost"`` (the next block's program keys on
    ``monitor.in_basin``) — those paths keep the pre-pipeline semantics
    bit-for-bit. ``pipeline=False`` is the synchronous escape hatch.
    ``carry_sync`` is forwarded to the chunked driver for callbacks that
    read the carry mid-block (checkpoint cadence).

    ``guard`` (a ``resilience.DivergenceGuard``): every block-boundary eval
    feeds it — the in-scan device counters (nonfinite q/loss) when telemetry
    is on, and the ``classify_health`` verdict always — so a chunked run can
    trip ``DivergenceTripped`` for a rollback driver exactly like the
    single-community path (train/resilience.py).
    """
    from p2pmicrogrid_tpu.parallel.scenarios import (
        make_chunked_episode_runner,
        make_shared_episode_fn,
        train_scenarios_chunked,
    )
    from p2pmicrogrid_tpu.parallel.device_gen import device_episode_arrays

    if mitigate not in ("warn", "lr-boost"):
        raise ValueError(f"mitigate must be 'warn' or 'lr-boost', got {mitigate!r}")
    if mitigate == "lr-boost" and cfg.train.implementation != "ddpg":
        # _lr_boosted_cfg scales the DDPG lrs; a "boosted" dqn/tabular
        # program would silently train with unchanged hyperparameters.
        raise ValueError(
            "basin mitigation 'lr-boost' is only implemented for ddpg "
            f"(got {cfg.train.implementation!r}); use 'warn'"
        )
    S = cfg.sim.n_scenarios

    owns_telemetry = False
    if telemetry == "auto":
        from p2pmicrogrid_tpu.telemetry import SqliteSink, Telemetry

        # With a results DB the run's telemetry ALSO lands in its SQLite
        # warehouse tables (keyed by config_hash) — the chunked/health path
        # now honours the same --results-db contract as `train`
        # (ROADMAP warehouse follow-on).
        extra_sinks = [SqliteSink(results_db)] if results_db else ()
        telemetry = Telemetry.maybe_create(
            "train-chunked",
            cfg=cfg,
            extra_sinks=extra_sinks,
            extra_manifest={
                "n_episodes": n_episodes,
                "n_chunks": n_chunks,
                "aggregate_scenarios": S * n_chunks,
                "mitigate": mitigate,
            },
        )
        owns_telemetry = telemetry is not None
    if telemetry is not None and telemetry.run_dir:
        print(f"telemetry run: {telemetry.run_dir}", file=sys.stderr, flush=True)

    # With telemetry on, the TRAINING episode program also collects the
    # in-scan device counters + per-chunk replay fill (not just the greedy
    # evals — ROADMAP open item), so the runner is built to match.
    collect = telemetry is not None

    def build_runner(run_cfg):
        episode_fn = make_shared_episode_fn(
            run_cfg, policy, None, ratings,
            arrays_fn=lambda k: device_episode_arrays(
                run_cfg, k, ratings, S
            ),
            n_scenarios=S, collect_device_metrics=collect,
        )
        warmup_fn = None
        if run_cfg.train.implementation == "dqn" and run_cfg.dqn.warmup_passes > 0:
            warmup_fn = make_shared_episode_fn(
                run_cfg, policy, None, ratings,
                arrays_fn=lambda k: device_episode_arrays(
                    run_cfg, k, ratings, S
                ),
                n_scenarios=S, record_only=True,
            )
        runner = make_chunked_episode_runner(
            run_cfg, episode_fn, n_chunks, warmup_fn=warmup_fn,
            chunk_parallel=chunk_parallel, collect_device_metrics=collect,
            donate=pipeline,
        )
        return runner, episode_fn

    normal_runner, normal_episode_fn = build_runner(cfg)
    boosted = None  # (runner, episode_fn), built lazily on first basin entry

    greedy_eval = make_greedy_eval(
        cfg, policy, ratings, s_eval=s_eval,
        collect_device_metrics=telemetry is not None,
    )
    monitor = monitor or HealthMonitor(cfg.sim.slots_per_day)
    if monitor.telemetry is None:
        monitor.telemetry = telemetry
    if monitor.initial_cost is None and episode0 > 0:
        # Resuming: calibrate against a fresh init, not the restored policy.
        monitor.initial_cost = untrained_reference_cost(
            cfg, policy, greedy_eval, seed=cfg.train.seed
        )

    # The eval readback rides the SAME software pipeline as the training
    # blocks (ISSUE 11 satellite): the greedy eval is dispatched on the
    # live device carry between blocks, and its host readback resolves
    # LAGGED through a shared AsyncDrain — the next block's dispatch never
    # waits on the eval's host round trip. The drain stays synchronous
    # exactly when something READS the eval before the next block may
    # start: a divergence guard (its trip must precede the next block's
    # checkpoint callback) or the lr-boost mitigation (the next block's
    # PROGRAM depends on monitor.in_basin). ``pipeline=False`` is the
    # depth-1 escape hatch on the same code path.
    from p2pmicrogrid_tpu.telemetry.async_drain import AsyncDrain

    sync_evals = (
        not pipeline or guard is not None or mitigate == "lr-boost"
    )
    drain = AsyncDrain(depth=2 if pipeline else 1, telemetry=telemetry)

    def consume_eval(tag, host):
        ep = tag[1]
        if telemetry is not None:
            from p2pmicrogrid_tpu.telemetry import dc_to_dict

            c, r, dc = host
            dcd = dc_to_dict(dc)
            telemetry.record_device_counters(dcd)
            telemetry.event(
                "device_counters", episode=ep, phase="eval", **dcd
            )
            if guard is not None:
                guard.observe_counters(ep, dcd)
        else:
            c, r = host
        status = monitor.update(ep, float(c), float(r))
        if guard is not None:
            guard.observe_health(ep, status)
        if health_cb:
            health_cb(monitor.points[-1])

    def dispatch_eval(ep):
        # Dispatch-only (no block_until_ready): the span measures the
        # dispatch; the blocking readback lands in the drain's
        # pipeline_drain span one slot later. MUST run before the next
        # block's donating dispatch — the eval reads the carry the next
        # block consumes in place.
        span = (
            telemetry.span("greedy_eval", episode=ep)
            if telemetry is not None else contextlib.nullcontext()
        )
        with span:
            out = greedy_eval(pol_state, jax.random.PRNGKey(1))
        drain.push(("eval", ep), out, consume_eval)
        if sync_evals:
            drain.flush()

    rewards, losses = [], []
    block_arrays: list = []
    seconds = 0.0
    done = 0
    import contextlib

    def push_block_record(ep0, block, r_list, l_list, secs, boosting):
        # A sentinel behind the block's own episode payloads: by FIFO,
        # when it drains, r_list/l_list are fully materialized — so the
        # per-block warehouse record lands within one pipeline slot of
        # the block finishing (NOT deferred to end-of-run: a crashed or
        # guard-tripped run keeps the records of every completed block,
        # which is exactly when they matter). ``secs`` is dispatch time
        # (the drain owns the readback).
        def consume(_tag, _host):
            if telemetry is not None:
                telemetry.event(
                    "train_block",
                    episode0=ep0,
                    episodes=block,
                    seconds=round(secs, 3),
                    mean_reward=float(np.mean(np.stack(r_list))),
                    mean_loss=float(np.mean(np.stack(l_list))),
                    lr_boosted=boosting,
                )
                telemetry.counter("train.episodes", block)
                telemetry.histogram("train.block_seconds", secs)

        drain.push(("block", ep0), (), consume)

    # An auto-created telemetry must close (summary.json + Chrome trace) even
    # when a block crashes — a failed run is exactly when the record matters.
    try:
        dispatch_eval(episode0)
        while done < n_episodes:
            block = min(eval_every, n_episodes - done)
            runner, episode_fn = normal_runner, normal_episode_fn
            # in_basin is current here by construction: lr-boost forces
            # sync_evals, so the eval that gates this block's program was
            # consumed before this line.
            boosting = mitigate == "lr-boost" and monitor.in_basin
            if boosting:
                if boosted is None:
                    boosted = build_runner(_lr_boosted_cfg(cfg, lr_boost))
                runner, episode_fn = boosted
            span = (
                telemetry.span(
                    "train_block", episode0=episode0 + done, episodes=block,
                    lr_boosted=boosting,
                )
                if telemetry is not None
                else contextlib.nullcontext()
            )
            with span:
                pol_state, r, l, secs = train_scenarios_chunked(
                    cfg, policy, pol_state, ratings, key,
                    n_episodes=block, n_chunks=n_chunks,
                    episode0=episode0 + done, episode_cb=episode_cb,
                    episode_fn=episode_fn, runner=runner,
                    telemetry=telemetry,
                    pipeline=pipeline, donate=pipeline,
                    carry_sync=carry_sync,
                    drain=drain, finalize=False,
                )
            # r/l are still-filling lists until their payloads drain;
            # the sentinel emits the block's telemetry as soon as they
            # are real, and the final stack below happens post-flush.
            push_block_record(episode0 + done, block, r, l, secs, boosting)
            block_arrays.append((r, l))
            seconds += secs
            done += block
            dispatch_eval(episode0 + done)
        drain.flush()
        # host-sync: end-of-run barrier so the carry (and timing) is real.
        jax.block_until_ready(pol_state)
        drain.finish()
        for r, l in block_arrays:
            rewards.append(np.stack(r))
            losses.append(np.stack(l))
        if telemetry is not None:
            telemetry.gauge("train.seconds_total", seconds)
            monitor.emit_summary()
    finally:
        if owns_telemetry:
            telemetry.close()
    return (
        pol_state,
        np.concatenate(rewards, axis=0),
        np.concatenate(losses, axis=0),
        seconds,
        monitor,
    )
