"""Crossover-driven device placement for sequential (single-scenario) runs.

The framework is one pure-JAX program and runs on any XLA backend; what
differs is WHERE each configuration is fast. Measured on the round-3
crossover sweep (artifacts/CROSSOVER_r03.json — the same jitted program
placed on each backend): single-scenario TABULAR training never wins on the
TPU up to 250 agents (0.03x the host XLA-CPU rate at 2 agents, 0.42x at
250 — the per-slot scatter-update program is dispatch/iteration bound, not
FLOP bound), while dqn/ddpg win on the TPU from 10 agents and every
scenario-batched mode belongs on the TPU outright.

The benchmark suite already places each config on its best backend
(benchmarks.best_device_steps_per_sec); this module gives the TRAINING CLI
the same knowledge: ``pick_train_device`` returns the host-CPU device for
configs inside the measured CPU-wins region (with the measured ratio for
the log line), and ``None`` — run wherever the default backend is —
elsewhere. ``train --device default`` overrides (round-3 VERDICT weak #3).
"""

from __future__ import annotations

from typing import Optional, Tuple

# Measured cpu-vs-accelerator ratios for single-scenario runs, keyed by
# implementation, as (max_agents_cpu_wins, {n_agents: tpu_over_cpu}).
# Source: artifacts/CROSSOVER_r03.json (TPU v5 lite vs host XLA-CPU).
_CPU_WINS_UP_TO = {"tabular": 250}
_MEASURED_TPU_OVER_CPU = {
    "tabular": {2: 0.03, 10: 0.04, 50: 0.07, 100: 0.19, 250: 0.42},
}


def sequential_cpu_advantage(
    implementation: str, n_agents: int
) -> Optional[float]:
    """If the measured crossover table says host XLA-CPU beats the
    accelerator for this single-scenario config, return the measured
    tpu/cpu throughput ratio at the nearest measured size (< 1 means CPU
    faster); else None."""
    limit = _CPU_WINS_UP_TO.get(implementation)
    if limit is None or n_agents > limit:
        return None
    table = _MEASURED_TPU_OVER_CPU[implementation]
    nearest = min(table, key=lambda a: abs(a - n_agents))
    return table[nearest]


def pick_serve_device(
    implementation: str, n_agents: int, default_backend: Optional[str] = None
) -> Tuple[Optional[object], str]:
    """(device-to-serve-on or None, human-readable reason) — the serving
    counterpart of ``pick_train_device``.

    The serve engine's per-bucket programs are the same per-slot forward
    passes the crossover sweep measured dispatch-bound at small community
    sizes: a tiny community's [B, A, 4] greedy pass cannot fill an
    accelerator, so inside the measured CPU-wins region the engine serves
    from host XLA-CPU the way training places itself
    (artifacts/CROSSOVER_r03.json). ``PolicyEngine(device=...)`` overrides.

    Honest caveat: the table was measured on B=1 sequential TRAINING
    programs, not padded serve batches — a large ``max_batch`` bucket can
    fill an accelerator where the sequential program could not, so for
    high-throughput serving pin ``device='default'`` (or serve-bench
    ``--serve-device default``) until a serve-specific crossover is
    measured (ROADMAP serving follow-on).
    """
    import jax

    backend = default_backend or jax.default_backend()
    if backend == "cpu":
        return None, "default backend is already host XLA-CPU"
    ratio = sequential_cpu_advantage(implementation, n_agents)
    if ratio is None:
        return None, (
            f"no measured CPU advantage for {implementation} at "
            f"{n_agents} agents"
        )
    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError:
        return None, "host XLA-CPU backend unavailable"
    return cpu, (
        f"{implementation} at {n_agents} agents measured {1 / ratio:.0f}x "
        f"faster on host XLA-CPU than on {backend} "
        "(artifacts/CROSSOVER_r03.json); override with device='default'"
    )


def pick_train_device(
    cfg, default_backend: Optional[str] = None
) -> Tuple[Optional[object], str]:
    """(device-to-place-on or None, human-readable reason).

    Returns a host-CPU jax.Device only when ALL of: the default backend is
    an accelerator, the run is single-scenario sequential, and the measured
    crossover table says CPU wins for this (implementation, n_agents).
    """
    import jax

    backend = default_backend or jax.default_backend()
    if backend == "cpu":
        return None, "default backend is already host XLA-CPU"
    if cfg.sim.n_scenarios > 1:
        return None, "scenario-batched modes belong on the accelerator"
    ratio = sequential_cpu_advantage(
        cfg.train.implementation, cfg.sim.n_agents
    )
    if ratio is None:
        return None, (
            f"no measured CPU advantage for single-scenario "
            f"{cfg.train.implementation} at {cfg.sim.n_agents} agents"
        )
    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError:
        return None, "host XLA-CPU backend unavailable"
    return cpu, (
        f"single-scenario {cfg.train.implementation} at "
        f"{cfg.sim.n_agents} agents measured {1 / ratio:.0f}x faster on "
        f"host XLA-CPU than on {backend} (artifacts/CROSSOVER_r03.json); "
        "override with --device default"
    )
