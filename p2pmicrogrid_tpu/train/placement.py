"""Crossover-driven device placement for sequential (single-scenario) runs.

The framework is one pure-JAX program and runs on any XLA backend; what
differs is WHERE each configuration is fast. Measured on the round-3
crossover sweep (artifacts/CROSSOVER_r03.json — the same jitted program
placed on each backend): single-scenario TABULAR training never wins on the
TPU up to 250 agents (0.03x the host XLA-CPU rate at 2 agents, 0.42x at
250 — the per-slot scatter-update program is dispatch/iteration bound, not
FLOP bound), while dqn/ddpg win on the TPU from 10 agents and every
scenario-batched mode belongs on the TPU outright.

The benchmark suite already places each config on its best backend
(benchmarks.best_device_steps_per_sec); this module gives the TRAINING CLI
the same knowledge: ``pick_train_device`` returns the host-CPU device for
configs inside the measured CPU-wins region (with the measured ratio for
the log line), and ``None`` — run wherever the default backend is —
elsewhere. ``train --device default`` overrides (round-3 VERDICT weak #3).
"""

from __future__ import annotations

import glob as _glob
import json as _json
import os
from typing import Optional, Tuple

# Measured cpu-vs-accelerator ratios for single-scenario runs, keyed by
# implementation, as (max_agents_cpu_wins, {n_agents: tpu_over_cpu}).
# Source: artifacts/CROSSOVER_r03.json (TPU v5 lite vs host XLA-CPU).
_CPU_WINS_UP_TO = {"tabular": 250}
_MEASURED_TPU_OVER_CPU = {
    "tabular": {2: 0.03, 10: 0.04, 50: 0.07, 100: 0.19, 250: 0.42},
}

# Committed serve-specific crossover captures (tools/crossover.py --serve):
# the SAME padded-bucket engine program placed on each backend over
# (implementation, n_agents, max_batch). Newest capture wins. A capture
# taken on a host WITHOUT an accelerator carries ``accelerator: false``
# (both placements were XLA-CPU) — it is only trusted when the serving
# process itself runs on the CPU backend; an accelerator host treats it as
# unmeasured rather than inheriting a ratio that measured nothing about
# the accelerator.
_SERVE_CROSSOVER_GLOB = "CROSSOVER_SERVE_*.json"
_serve_table_cache: dict = {}
_serve_table_meta: dict = {}


def _repo_artifacts_dir() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))),
        "artifacts",
    )


def load_serve_crossover(artifacts_dir: Optional[str] = None) -> dict:
    """{(implementation, n_agents, max_batch): tpu_over_cpu} from the
    newest committed ``artifacts/CROSSOVER_SERVE_*.json`` capture (empty
    dict when none has been measured yet). Cached per directory."""
    root = artifacts_dir or _repo_artifacts_dir()
    if root in _serve_table_cache:
        return _serve_table_cache[root]
    table: dict = {}
    meta = {"accelerator": True}
    paths = sorted(_glob.glob(os.path.join(root, _SERVE_CROSSOVER_GLOB)))
    if paths:
        try:
            with open(paths[-1]) as f:
                doc = _json.load(f)
            # Captures predating the flag were accelerator-vs-CPU by
            # construction (the sweep refused to run without one).
            meta["accelerator"] = bool(doc.get("accelerator", True))
            for row in doc.get("rows", []):
                table[
                    (
                        row["implementation"],
                        int(row["n_agents"]),
                        int(row["max_batch"]),
                    )
                ] = float(row["tpu_over_cpu"])
        except (OSError, ValueError, KeyError, TypeError):
            table = {}  # a malformed capture must not break placement
    _serve_table_cache[root] = table
    _serve_table_meta[root] = meta
    return table


def serve_crossover_is_host_only(artifacts_dir: Optional[str] = None) -> bool:
    """True when the newest committed serve-crossover capture was measured
    WITHOUT an accelerator (accelerator hosts must not trust its ratios)."""
    root = artifacts_dir or _repo_artifacts_dir()
    load_serve_crossover(artifacts_dir)
    return not _serve_table_meta.get(root, {}).get("accelerator", True)


def serve_cpu_advantage(
    implementation: str,
    n_agents: int,
    max_batch: int,
    artifacts_dir: Optional[str] = None,
) -> Optional[Tuple[float, str]]:
    """(measured tpu_over_cpu at the nearest measured (n_agents,
    max_batch), source-file label) from the serve-specific crossover
    table, or None when nothing is measured for this implementation."""
    table = load_serve_crossover(artifacts_dir)
    candidates = [
        (a, b) for (impl, a, b) in table if impl == implementation
    ]
    if not candidates:
        return None
    # Nearest measured point in log-ish space: both axes span orders of
    # magnitude, so compare multiplicative distance, not absolute.
    import math

    def dist(point):
        a, b = point
        return (
            abs(math.log(max(a, 1)) - math.log(max(n_agents, 1)))
            + abs(math.log(max(b, 1)) - math.log(max(max_batch, 1)))
        )

    nearest = min(candidates, key=dist)
    return (
        table[(implementation, nearest[0], nearest[1])],
        f"measured at A={nearest[0]}, max_batch={nearest[1]}",
    )


def sequential_cpu_advantage(
    implementation: str, n_agents: int
) -> Optional[float]:
    """If the measured crossover table says host XLA-CPU beats the
    accelerator for this single-scenario config, return the measured
    tpu/cpu throughput ratio at the nearest measured size (< 1 means CPU
    faster); else None."""
    limit = _CPU_WINS_UP_TO.get(implementation)
    if limit is None or n_agents > limit:
        return None
    table = _MEASURED_TPU_OVER_CPU[implementation]
    nearest = min(table, key=lambda a: abs(a - n_agents))
    return table[nearest]


def pick_serve_device(
    implementation: str,
    n_agents: int,
    max_batch: int = 1,
    default_backend: Optional[str] = None,
    artifacts_dir: Optional[str] = None,
) -> Tuple[Optional[object], str]:
    """(device-to-serve-on or None, human-readable reason) — the serving
    counterpart of ``pick_train_device``, batch-width aware.

    Placement consults, in order:

    1. The serve-specific crossover table (``tools/crossover.py --serve``,
       committed as ``artifacts/CROSSOVER_SERVE_*.json``): the SAME padded
       bucket program placed on each backend over (n_agents, max_batch).
       The nearest measured point decides.
    2. With no serve table, the B=1 sequential-training crossover
       (``artifacts/CROSSOVER_r03.json``) — but ONLY for ``max_batch == 1``
       serving, where the serve program IS a B=1 forward pass. A padded
       bucket of 64+ communities can fill an accelerator the sequential
       program could not, so wide-batch configs without a serve
       measurement stay on the default backend instead of inheriting the
       training table's CPU pin.

    ``PolicyEngine(device=...)`` / ``serve-bench --serve-device`` override.
    """
    import jax

    backend = default_backend or jax.default_backend()
    if backend == "cpu":
        return None, "default backend is already host XLA-CPU"
    measured = serve_cpu_advantage(
        implementation, n_agents, max_batch, artifacts_dir
    )
    if measured is not None and serve_crossover_is_host_only(artifacts_dir):
        # The committed capture measured CPU-vs-CPU (no accelerator on the
        # capture host): it exercises the loader but says nothing about
        # THIS accelerator — fall through to the unmeasured heuristics.
        measured = None
    if measured is not None:
        ratio, source = measured
        if ratio >= 1.0:
            return None, (
                f"serve crossover: {backend} wins for {implementation} at "
                f"{n_agents} agents, max_batch {max_batch} ({source}, "
                f"{ratio:.2f}x CPU)"
            )
        try:
            cpu = jax.devices("cpu")[0]
        except RuntimeError:
            return None, "host XLA-CPU backend unavailable"
        # A very CPU-favorable point rounds to tpu_over_cpu == 0.0 in the
        # committed capture — report the bound, don't divide by it.
        speedup = f"{1 / ratio:.0f}x" if ratio > 0 else ">1000x"
        return cpu, (
            f"serve crossover: host XLA-CPU {speedup} faster for "
            f"{implementation} at {n_agents} agents, max_batch {max_batch} "
            f"({source}); override with device='default'"
        )
    if max_batch > 1:
        return None, (
            f"no serve-specific crossover measured for max_batch="
            f"{max_batch} (tools/crossover.py --serve); padded batches may "
            f"fill the accelerator, staying on {backend}"
        )
    ratio = sequential_cpu_advantage(implementation, n_agents)
    if ratio is None:
        return None, (
            f"no measured CPU advantage for {implementation} at "
            f"{n_agents} agents"
        )
    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError:
        return None, "host XLA-CPU backend unavailable"
    return cpu, (
        f"{implementation} at {n_agents} agents measured {1 / ratio:.0f}x "
        f"faster on host XLA-CPU than on {backend} "
        "(artifacts/CROSSOVER_r03.json, B=1); override with device='default'"
    )


def pick_train_device(
    cfg, default_backend: Optional[str] = None
) -> Tuple[Optional[object], str]:
    """(device-to-place-on or None, human-readable reason).

    Returns a host-CPU jax.Device only when ALL of: the default backend is
    an accelerator, the run is single-scenario sequential, and the measured
    crossover table says CPU wins for this (implementation, n_agents).
    """
    import jax

    backend = default_backend or jax.default_backend()
    if backend == "cpu":
        return None, "default backend is already host XLA-CPU"
    if cfg.sim.n_scenarios > 1:
        return None, "scenario-batched modes belong on the accelerator"
    ratio = sequential_cpu_advantage(
        cfg.train.implementation, cfg.sim.n_agents
    )
    if ratio is None:
        return None, (
            f"no measured CPU advantage for single-scenario "
            f"{cfg.train.implementation} at {cfg.sim.n_agents} agents"
        )
    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError:
        return None, "host XLA-CPU backend unavailable"
    return cpu, (
        f"single-scenario {cfg.train.implementation} at "
        f"{cfg.sim.n_agents} agents measured {1 / ratio:.0f}x faster on "
        f"host XLA-CPU than on {backend} (artifacts/CROSSOVER_r03.json); "
        "override with --device default"
    )
