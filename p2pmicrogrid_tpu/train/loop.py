"""Training and evaluation loops.

Reference analogues: ``community.main``'s episode loop (community.py:272-298),
``init_buffers`` DQN warmup (community.py:125-147), and ``load_and_run``'s
per-day greedy evaluation (community.py:364-412).

The TPU-native shape: the entire episode (96 slots x negotiation x learning)
is one jitted ``lax.scan``; optionally ``episodes_per_jit_block`` episodes are
fused into a single device call with an outer scan, so the Python loop only
handles the exploration-decay schedule, metric recording, and checkpoints.
Evaluation vmaps the per-day runs into one device call.
"""

from __future__ import annotations

import collections
import contextlib
import functools
import statistics
import time as _time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from p2pmicrogrid_tpu.config import ExperimentConfig
from p2pmicrogrid_tpu.data.traces import TraceSet
from p2pmicrogrid_tpu.envs.community import (
    AgentRatings,
    EpisodeArrays,
    PhysState,
    Policy,
    SlotOutputs,
    build_episode_arrays,
    draw_rating_scales,
    init_physical,
    run_episode,
)
from p2pmicrogrid_tpu.models import dqn_initialize_target
from p2pmicrogrid_tpu.models.dqn import ACTION_VALUES, DQNState
from p2pmicrogrid_tpu.models.replay import replay_add


@dataclass
class TrainResult:
    """What ``main`` accumulates: per-episode reward/error plus the periodic
    training-progress records (community.py:276-296)."""

    pol_state: object
    phys: PhysState
    episode_rewards: List[float] = field(default_factory=list)
    episode_losses: List[float] = field(default_factory=list)
    progress: List[Tuple[int, float, float]] = field(default_factory=list)
    train_seconds: float = 0.0
    env_steps: int = 0
    # End-of-run host RNG-key chain: the final checkpoint saves it so a
    # completed run's checkpoint is exactly resumable too (train/resilience).
    rng_key: Optional[object] = None

    @property
    def env_steps_per_sec(self) -> float:
        return self.env_steps / self.train_seconds if self.train_seconds else 0.0


def _episode_metrics(outputs: SlotOutputs) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Episode reward = sum over slots of the agent-mean reward
    (community.py:179); loss = mean (community.py:180)."""
    return (
        jnp.sum(jnp.mean(outputs.reward, axis=-1)),
        jnp.mean(outputs.loss),
    )


def make_train_step(
    cfg: ExperimentConfig,
    policy: Policy,
    arrays: EpisodeArrays,
    ratings: AgentRatings,
    block: Optional[int] = None,
    collect_device_metrics: bool = False,
    donate: bool = False,
) -> Callable:
    """Jitted function running ``block`` training episodes (defaults to
    ``episodes_per_jit_block``).

    ``donate`` donates the policy-state argument: the learner trees update
    in place block-to-block instead of allocating fresh buffers every call.
    A donated ``pol_state`` is CONSUMED — callers must not reuse it
    (``train_community`` copies its incoming state once, so its public API
    is unaffected; see README "Training pipeline").

    Each episode starts from a freshly drawn physical state (the reference
    re-randomizes indoor temperatures on every reset, heating.py:145-152) and
    scans the slots; the block scans the episodes. The exploration-decay
    schedule (every ``min_episodes_criterion`` episodes, community.py:279-287)
    runs *inside* the block via ``lax.cond`` keyed on the global episode index,
    so fused blocks follow the reference schedule exactly.

    With ``collect_device_metrics`` each episode also accumulates the
    in-program ``telemetry.DeviceCounters`` (run_episode threads them through
    the slot scan) and the block returns a 5th element: the block-total
    counters, reduced on device.
    """
    if block is None:
        block = cfg.train.episodes_per_jit_block
    criterion = cfg.train.min_episodes_criterion
    if collect_device_metrics:
        from p2pmicrogrid_tpu.telemetry.device_metrics import dc_add, dc_zero

    def one_episode(pol_state, key):
        k_phys, k_ep = jax.random.split(key)
        phys = init_physical(cfg, k_phys)
        out = run_episode(
            cfg, policy, pol_state, phys, arrays, ratings, k_ep, training=True,
            collect_device_metrics=collect_device_metrics,
        )
        phys, pol_state, outputs = out[:3]
        reward, loss = _episode_metrics(outputs)
        dc = out[3] if collect_device_metrics else None
        return pol_state, phys, reward, loss, dc

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def train_block(pol_state, episode0, key):
        keys = jax.random.split(key, block)

        def body(carry, xs):
            pol_state, dc_tot = carry
            i, k = xs
            pol_state, phys, reward, loss, dc = one_episode(pol_state, k)
            if collect_device_metrics:
                dc_tot = dc_add(dc_tot, dc)
            pol_state = jax.lax.cond(
                (episode0 + i) % criterion == 0, policy.decay, lambda s: s, pol_state
            )
            return (pol_state, dc_tot), (reward, loss, phys)

        dc0 = dc_zero() if collect_device_metrics else None
        (pol_state, dc_tot), (rewards, losses, physes) = jax.lax.scan(
            body, (pol_state, dc0), (jnp.arange(block), keys)
        )
        last_phys = jax.tree_util.tree_map(lambda x: x[-1], physes)
        if collect_device_metrics:
            return pol_state, last_phys, rewards, losses, dc_tot
        return pol_state, last_phys, rewards, losses

    return train_block


def init_dqn_buffers(
    cfg: ExperimentConfig,
    policy: Policy,
    pol_state: DQNState,
    arrays: EpisodeArrays,
    ratings: AgentRatings,
    key: jax.Array,
) -> DQNState:
    """DQN replay warmup (community.py:125-147): ``warmup_passes`` full
    epsilon-greedy passes that only *record* transitions (no gradient steps),
    then a hard online->target copy.

    Implemented by swapping the policy's ``learn`` for a buffer-only write.
    """
    def record_only(pol_state, obs, aux, reward, next_obs, _key):
        act_frac = ACTION_VALUES[aux.astype(jnp.int32)][:, None]
        replay = replay_add(pol_state.replay, obs, act_frac, reward, next_obs)
        return pol_state._replace(replay=replay), jnp.zeros_like(reward)

    warmup_policy = Policy(act=policy.act, learn=record_only, decay=policy.decay)

    @jax.jit
    def one_pass(pol_state, key):
        k_phys, k_ep = jax.random.split(key)
        phys = init_physical(cfg, k_phys)
        _, pol_state, _ = run_episode(
            cfg, warmup_policy, pol_state, phys, arrays, ratings, k_ep, training=True
        )
        return pol_state

    for k in jax.random.split(key, cfg.dqn.warmup_passes):
        pol_state = one_pass(pol_state, k)
    return dqn_initialize_target(pol_state)


def train_community(
    cfg: ExperimentConfig,
    policy: Policy,
    pol_state,
    traces: TraceSet,
    ratings: AgentRatings,
    key: jax.Array,
    progress_cb: Optional[Callable[[int, float, float], None]] = None,
    checkpoint_cb: Optional[Callable[[int, object], None]] = None,
    verbose: bool = False,
    telemetry=None,
    pipeline: bool = True,
    guard=None,
    fault_hook: Optional[Callable[[int, object], object]] = None,
    warmup: bool = True,
) -> TrainResult:
    """The reference's training driver (community.py:248-298).

    Every ``min_episodes_criterion`` episodes: decay exploration and emit a
    running-average progress record (community.py:279-288). Every
    ``save_episodes`` episodes: invoke the checkpoint callback
    (community.py:290-292). Returns final states plus metric histories.

    **Crash-safe resume** (train/resilience.py): a ``checkpoint_cb`` that
    accepts a third argument additionally receives the host RNG-key chain
    as it stands AFTER the block's split — saving it alongside the learner
    state (``save_checkpoint(rng_key=...)``) makes the checkpoint exactly
    resumable: restore the state, set ``starting_episodes = episode + 1``,
    pass the saved key back as ``key`` with ``warmup=False``, and the
    surviving episodes replay bit-identically to an uninterrupted run
    (the block schedule is a pure function of the episode index, and the
    DQN replay contents ride inside ``pol_state``). ``warmup=False`` skips
    the DQN replay warmup AND its key split — both already happened before
    the checkpoint was taken. ``TrainResult.rng_key`` is the end-of-run
    chain for the final save.

    ``guard`` (a ``resilience.DivergenceGuard``) observes each block's
    in-program device counters BEFORE any checkpoint for that block is
    saved — a divergence trip raises out of the loop without persisting
    the poisoned state. ``fault_hook(episode, pol_state)`` runs at each
    block boundary (the deterministic crash harness, train/faults.py); a
    non-``None`` return replaces the carry (NaN poisoning).

    ``telemetry`` (a ``telemetry.Telemetry``) turns the run observable:
    progress records become ``progress`` events, each fused block runs under
    a ``train_block`` span, and the in-program device counters (NaN/comfort/
    market totals accumulated inside the jitted block) are reduced and
    recorded per block as ``device.*`` counters.

    ``pipeline`` (default) runs the depth-2 async driver: block b+1 is
    dispatched (with a DONATED policy-state carry — the learner trees update
    in place) before block b's rewards/losses/counters are read back, so the
    device never idles on the host round trip; progress records and windowed
    averages consume the lagged results with exactly the sync driver's
    values. Blocks ending on a checkpoint boundary drain synchronously
    BEFORE the next dispatch, so ``checkpoint_cb`` always sees live,
    episode-exact state. ``pipeline=False`` is the synchronous escape hatch
    (bit-identical final state; only readback timing moves).
    """
    t = cfg.train
    arrays = build_episode_arrays(cfg, traces, ratings)

    if t.implementation == "dqn" and warmup:
        key, k_warm = jax.random.split(key)
        pol_state = init_dqn_buffers(cfg, policy, pol_state, arrays, ratings, k_warm)

    collect_dc = telemetry is not None or guard is not None
    train_block = make_train_step(
        cfg, policy, arrays, ratings, collect_device_metrics=collect_dc,
        donate=pipeline,
    )
    block = t.episodes_per_jit_block

    result = TrainResult(pol_state=pol_state, phys=None)
    window_r = collections.deque(maxlen=t.min_episodes_criterion)
    window_l = collections.deque(maxlen=t.min_episodes_criterion)

    if pipeline:
        # The donating block program consumes its carry; copy once so the
        # caller's passed-in state survives (README donation contract).
        from p2pmicrogrid_tpu.parallel.scenarios import _copy_carry

        pol_state = _copy_carry(pol_state)

    from p2pmicrogrid_tpu.telemetry.async_drain import AsyncDrain

    drain = AsyncDrain(depth=2 if pipeline else 1, telemetry=telemetry)

    start = _time.time()
    episode = t.starting_episodes
    phys = None
    step_fns = {block: train_block}  # compiled lazily per distinct size

    def step_of(size: int):
        if size not in step_fns:
            step_fns[size] = make_train_step(
                cfg, policy, arrays, ratings, block=size,
                collect_device_metrics=collect_dc, donate=pipeline,
            )
        return step_fns[size]

    # A checkpoint callback that accepts (ep, pol_state, rng_key) gets the
    # post-split key chain for exact resume; the 2-arg form stays supported.
    ckpt_wants_key = False
    if checkpoint_cb is not None:
        import inspect

        try:
            params = [
                p
                for p in inspect.signature(checkpoint_cb).parameters.values()
                if p.kind
                in (
                    inspect.Parameter.POSITIONAL_ONLY,
                    inspect.Parameter.POSITIONAL_OR_KEYWORD,
                    inspect.Parameter.VAR_POSITIONAL,
                )
            ]
            ckpt_wants_key = len(params) >= 3 or any(
                p.kind is inspect.Parameter.VAR_POSITIONAL for p in params
            )
        except (TypeError, ValueError):
            ckpt_wants_key = False

    def consume_block(episode0_b, host, pol_state_b, key_b):
        rewards, losses = host[0], host[1]
        if collect_dc:
            from p2pmicrogrid_tpu.telemetry import dc_to_dict

            dcd = dc_to_dict(host[2])
            if telemetry is not None:
                telemetry.record_device_counters(dcd)
            if guard is not None:
                # BEFORE the per-episode loop below: a trip here raises out
                # of the drain before the poisoned block's checkpoint
                # callback can persist the diverged state.
                guard.observe_counters(
                    episode0_b + rewards.shape[0] - 1, dcd
                )
        for i in range(rewards.shape[0]):
            window_r.append(float(rewards[i]))
            window_l.append(float(losses[i]))
            result.episode_rewards.append(float(rewards[i]))
            result.episode_losses.append(float(losses[i]))
            ep = episode0_b + i

            # Exploration decay already happened in-block; emit the progress
            # record on the same cadence (community.py:279-288).
            if ep % t.min_episodes_criterion == 0:
                avg_r = statistics.mean(window_r)
                avg_l = statistics.mean(window_l)
                result.progress.append((ep, avg_r, avg_l))
                if progress_cb:
                    progress_cb(ep, avg_r, avg_l)
                if telemetry is not None:
                    telemetry.event(
                        "progress", episode=ep, avg_reward=avg_r, avg_error=avg_l
                    )
                if verbose:
                    print(f"episode {ep}: avg reward {avg_r:.3f}, avg error {avg_l:.3f}")

            # Episode-exact: block ends are aligned to the save cadence
            # below, so pol_state_b here IS the state after episode ep (the
            # loop drains synchronously before the next dispatch can donate
            # it whenever a block ends on a save boundary).
            if (ep + 1) % t.save_episodes == 0 and checkpoint_cb:
                if ckpt_wants_key:
                    checkpoint_cb(ep, pol_state_b, key_b)
                else:
                    checkpoint_cb(ep, pol_state_b)

    profiled = False
    while episode < t.max_episodes:
        if fault_hook is not None:
            # Deterministic crash harness (train/faults.py): kill fires here
            # (SIGKILL / SimulatedPreemption), poison replaces the carry.
            mutated = fault_hook(episode, pol_state)
            if mutated is not None:
                pol_state = mutated
        key, k_block = jax.random.split(key)
        # Clamp the final block so exactly max_episodes episodes run (a full
        # extra block would overshoot the configured count).
        step_size = min(block, t.max_episodes - episode)
        if checkpoint_cb:
            # Align block ends to the save cadence so every checkpoint is
            # EPISODE-EXACT (round-3 VERDICT weak #7): without this, a
            # save_episodes boundary inside a fused block could only hand
            # the callback end-of-block state, and a resume silently
            # replayed up to block-1 episodes. Distinct sizes cycle with
            # lcm(block, save_episodes), so the compiled-step cache stays
            # small.
            to_boundary = t.save_episodes - episode % t.save_episodes
            step_size = min(step_size, to_boundary)
        step_fn = step_of(step_size)
        if telemetry is not None and not profiled:
            # Compile-profile the episode-scan program ONCE (HLO flops/bytes
            # + executable buffer sizes -> profile.episode_scan.* gauges).
            # The AOT-compiled executable replaces the jitted wrapper in the
            # step cache — same shapes every call — so the profile costs no
            # second compile. P2P_PROFILE=0 skips.
            profiled = True
            from p2pmicrogrid_tpu.telemetry.profiling import (
                profile_and_compile,
                profiling_enabled,
            )

            if profiling_enabled():
                step_fn, _ = profile_and_compile(
                    step_fn, pol_state, jnp.asarray(episode), k_block,
                    label="episode_scan", telemetry=telemetry,
                    extra={"episodes_per_block": step_size,
                           "slots_per_episode": arrays.n_slots},
                )
                step_fns[step_size] = step_fn
        block_span = (
            telemetry.span("train_block", episode0=episode, episodes=step_size)
            if telemetry is not None
            else contextlib.nullcontext()
        )
        with block_span, drain.dispatch_span(episode=episode):
            out = step_fn(pol_state, jnp.asarray(episode), k_block)
            pol_state, phys = out[0], out[1]
        payload = out[2:4] + ((out[4],) if collect_dc else ())
        drain.push(
            episode,
            payload,
            lambda e0, host, ps=pol_state, k=key: consume_block(e0, host, ps, k),
        )
        if checkpoint_cb and (episode + step_size) % t.save_episodes == 0:
            # This block's consumption will checkpoint: drain before the
            # next dispatch donates the state the callback must serialize.
            drain.flush()
        episode += step_size

    drain.flush()
    # host-sync: end-of-run barrier so the timing is honest.
    jax.block_until_ready(pol_state)
    drain.finish()
    result.train_seconds = _time.time() - start
    result.env_steps = (episode - t.starting_episodes) * arrays.n_slots
    result.pol_state = pol_state
    result.phys = phys
    result.rng_key = key
    if telemetry is not None:
        telemetry.gauge("train.seconds_total", result.train_seconds)
        telemetry.gauge("train.env_steps_per_sec", result.env_steps_per_sec)
    return result


def evaluate_community(
    cfg: ExperimentConfig,
    policy: Policy,
    pol_state,
    traces: TraceSet,
    ratings: AgentRatings,
    key: jax.Array,
    redraw_profile_scales: bool = True,
    rng: Optional[np.random.Generator] = None,
    arrays_transform: Optional[Callable[[EpisodeArrays], EpisodeArrays]] = None,
) -> Tuple[np.ndarray, SlotOutputs, EpisodeArrays]:
    """Greedy per-day evaluation (community.py:364-412): each day runs from a
    fresh physical state so bad decisions don't propagate (community.py:380).

    All days evaluate in ONE device call (vmap over the day axis) — the
    reference loops days on the host.

    ``redraw_profile_scales`` mirrors community.py:386-391: at eval time the
    per-agent load/PV profile scales are re-drawn ~N(0.7,0.2)/N(4,0.2) kW
    (homogeneous: fixed means), independent of the training ratings.

    Returns (days, outputs, day_arrays): SlotOutputs leaves are
    [n_days, slots_per_day, ...]; day_arrays are the stacked per-day
    EpisodeArrays (same leading shape) for persisting load/PV traces.
    """
    by_day = traces.split_by_day()
    days = np.array(sorted(by_day), dtype=np.int32)

    gen = rng if rng is not None else np.random.default_rng(0)
    day_arrays = []
    for d in days:
        day_traces = by_day[int(d)]
        r = ratings
        if redraw_profile_scales:
            load_r, pv_r = draw_rating_scales(cfg, gen)
            r = AgentRatings(
                load_rating_w=(load_r * 1e3).astype(np.float32),
                pv_rating_w=(pv_r * 1e3).astype(np.float32),
                max_in=ratings.max_in,
                max_out=ratings.max_out,
            )
        arrays = build_episode_arrays(cfg, day_traces, r)
        if arrays_transform is not None:
            arrays = arrays_transform(arrays)  # e.g. with_pv_drop fault injection
        day_arrays.append(arrays)

    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *day_arrays)
    ratings_j = AgentRatings(*(jnp.asarray(a) for a in ratings))

    @jax.jit
    def eval_all(pol_state, stacked, keys):
        def one_day(arrays, k):
            # Independent keys for the initial temperatures and the episode —
            # greedy eval consumes no episode randomness today, but correlated
            # keys would silently bias any future stochastic-eval path.
            k_phys, k_ep = jax.random.split(k)
            phys = init_physical(cfg, k_phys)
            _, _, outputs = run_episode(
                cfg, policy, pol_state, phys, arrays, ratings_j, k_ep, training=False
            )
            return outputs

        return jax.vmap(one_day)(stacked, keys)

    keys = jax.random.split(key, len(days))
    # stacked as an argument, not a closure capture — capture would
    # constant-fold the per-day episode arrays into the executable.
    outputs = eval_all(pol_state, stacked, keys)
    return days, outputs, stacked
