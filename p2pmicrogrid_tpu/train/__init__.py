"""Training layer: policy adapters, episode loops, warmup, checkpointing.

Reference analogues: community.py:248-321 (``main``), :125-147
(``init_buffers``), :364-412 (``load_and_run``), rl.py:251-359 (``Trainer``),
setup.py:29-32 (loop knobs).
"""

from p2pmicrogrid_tpu.train.policies import (
    make_tabular_policy,
    make_dqn_policy,
    make_ddpg_policy,
    init_policy_state,
    make_policy,
)
from p2pmicrogrid_tpu.train.loop import (
    TrainResult,
    train_community,
    evaluate_community,
    init_dqn_buffers,
)
from p2pmicrogrid_tpu.train.checkpoint import (
    checkpoint_dir,
    save_checkpoint,
    restore_checkpoint,
    restore_resume_state,
    latest_checkpoint,
    verify_checkpoint,
)
from p2pmicrogrid_tpu.train.continual import (
    ContinualResult,
    offpolicy_pretrain,
    state_from_bundle,
    train_continual,
)

__all__ = [
    "ContinualResult",
    "offpolicy_pretrain",
    "state_from_bundle",
    "train_continual",
    "checkpoint_dir",
    "save_checkpoint",
    "restore_checkpoint",
    "restore_resume_state",
    "latest_checkpoint",
    "verify_checkpoint",
    "make_tabular_policy",
    "make_dqn_policy",
    "make_ddpg_policy",
    "init_policy_state",
    "make_policy",
    "TrainResult",
    "train_community",
    "evaluate_community",
    "init_dqn_buffers",
]
