"""Training-side resilience: divergence rollback, exact resume, supervision.

PR 6 made the SERVING tier survive kills, stalls and corruption behind a
replayable fault harness (serve/faults.py); this module is the mirror for
the training tier that produces every served bundle. Three layers:

* **DivergenceGuard** — watches the in-program ``nonfinite_q``/
  ``nonfinite_loss`` device counters (telemetry/device_metrics.py) and the
  ``classify_health`` basin verdicts (train/health.py) and raises
  ``DivergenceTripped`` the moment training goes non-finite or enters the
  don't-heat basin with rollback armed. ``train_community`` runs the guard
  BEFORE each block's checkpoint callback, so a diverged state is never
  persisted as "good".

* **train_community_with_rollback** — the self-healing driver: on a trip it
  restores the newest VERIFIED checkpoint (train/checkpoint.py falls back
  past corrupt steps), applies a deterministic perturbation — the effective
  learning rates x ``lr_drop**attempt`` plus a fresh ``fold_in`` branch of
  the restored RNG chain — and re-enters the loop, up to ``max_rollbacks``
  times. Every rollback lands in the telemetry warehouse (``train.rollback``
  counter, ``rollback`` event + span) joinable on ``config_hash``
  (``telemetry-query --rollbacks``).

* **supervise** — the preemption harness: relaunches a training child
  process on crash with capped exponential backoff, appending ``--resume``
  from the second attempt on and exporting ``P2P_TRAIN_ATTEMPT`` so the
  deterministic fault plan (train/faults.py) does not re-fire. With exact
  resume (``prepare_resume``) the supervised run's final params are
  bit-identical to an uninterrupted run — the acceptance capture
  (artifacts/RESILIENCE_r08.jsonl) asserts it.

Host-sync note: this module sits on the training dispatch path
(tools/check_host_sync.py); everything here runs at block/crash boundaries
where blocking is the point.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

ROLLBACK_KEY_SALT = 7919  # fixed prime: rollback r trains on fold_in(key, SALT + r)


class DivergenceTripped(RuntimeError):
    """Training diverged (non-finite counters / basin verdict)."""

    def __init__(self, episode: int, reason: str, counters: Optional[dict] = None):
        super().__init__(f"divergence at episode {episode}: {reason}")
        self.episode = episode
        self.reason = reason
        self.counters = counters or {}


class RollbackExhausted(RuntimeError):
    """The rollback budget ran out without recovering."""


@dataclass(frozen=True)
class GuardPolicy:
    """When to trip and how to perturb on rollback."""

    nonfinite_q_tolerance: int = 0      # trip when a block exceeds this
    nonfinite_loss_tolerance: int = 0
    trip_on_basin: bool = False         # also trip on a 'basin' health verdict
    max_rollbacks: int = 3
    lr_drop: float = 0.5                # effective lrs x lr_drop**attempt


class DivergenceGuard:
    """Feeds block counters / health verdicts to the trip rule.

    ``observe_counters(episode, counters)`` takes the per-block device-
    counter dict (``dc_to_dict``); ``observe_health(episode, status)`` takes
    a ``classify_health`` verdict. Both raise ``DivergenceTripped`` on trip
    (once — a tripped guard is spent; the rollback driver builds a fresh one
    per attempt). Trips are recorded as ``train.divergence`` counters +
    ``divergence`` events when telemetry is attached.
    """

    def __init__(self, policy: GuardPolicy = GuardPolicy(), telemetry=None):
        self.policy = policy
        self.telemetry = telemetry
        self.tripped: Optional[DivergenceTripped] = None
        self.observations = 0

    def _trip(self, episode: int, reason: str, counters: Optional[dict] = None):
        trip = DivergenceTripped(episode, reason, counters)
        self.tripped = trip
        if self.telemetry is not None:
            self.telemetry.counter("train.divergence")
            self.telemetry.event(
                "divergence", episode=episode, reason=reason, **(counters or {})
            )
        raise trip

    def observe_counters(self, episode: int, counters: dict) -> None:
        if self.tripped is not None:
            return
        self.observations += 1
        nq = int(counters.get("nonfinite_q", 0) or 0)
        nl = int(counters.get("nonfinite_loss", 0) or 0)
        if nq > self.policy.nonfinite_q_tolerance or nl > self.policy.nonfinite_loss_tolerance:
            self._trip(
                episode,
                f"nonfinite_q={nq} nonfinite_loss={nl}",
                {"nonfinite_q": nq, "nonfinite_loss": nl},
            )

    def observe_health(self, episode: int, status: str) -> None:
        if self.tripped is not None:
            return
        self.observations += 1
        if self.policy.trip_on_basin and status == "basin":
            self._trip(episode, "health classifier verdict 'basin'")


# --- deterministic perturbation ----------------------------------------------


def scaled_lr_cfg(cfg, scale: float):
    """The rollback perturbation's LR half: the implementation's effective
    learning rates x ``scale`` (tabular alpha, DQN learning_rate, DDPG
    actor/critic lrs — the auto-scale rule, where active, applies on top of
    the scaled bases, so the drop composes deterministically)."""
    if scale == 1.0:
        return cfg
    impl = cfg.train.implementation
    if impl == "tabular":
        return cfg.replace(
            qlearning=dataclasses.replace(cfg.qlearning, alpha=cfg.qlearning.alpha * scale)
        )
    if impl == "dqn":
        return cfg.replace(
            dqn=dataclasses.replace(cfg.dqn, learning_rate=cfg.dqn.learning_rate * scale)
        )
    if impl == "ddpg":
        return cfg.replace(
            ddpg=dataclasses.replace(
                cfg.ddpg,
                actor_lr=cfg.ddpg.actor_lr * scale,
                critic_lr=cfg.ddpg.critic_lr * scale,
            )
        )
    return cfg


# --- exact resume ------------------------------------------------------------


@dataclass
class ResumePlan:
    """What ``prepare_resume`` decided (feeds ``train_community`` directly)."""

    pol_state: object
    cfg: object
    key: object
    warmup: bool
    resumed: bool
    exact: bool
    episode: int = -1           # checkpoint episode (-1 = fresh start)
    extra: dict = field(default_factory=dict)


def prepare_resume(cfg, ckpt_dir: str, template_pol_state, base_key) -> ResumePlan:
    """Resolve a ``--resume`` request against what the checkpoint knows.

    A checkpoint carrying its RNG-key chain resumes EXACTLY: the saved key
    replaces the chain, the DQN warmup is skipped (its effect — replay
    contents + target copy — rides inside the restored state), and the
    surviving episodes replay bit-identically to an uninterrupted run. A
    legacy checkpoint (no key) falls back to the historical semantics:
    ``fold_in(base_key, episode0)`` and a fresh warmup pass — a valid
    continuation, but a different stream than the original run's.

    No restorable checkpoint at all returns a fresh-start plan (the
    supervisor relaunches with ``--resume`` unconditionally; a child that
    died before its first save must start over, not crash-loop).
    """
    import jax
    import jax.numpy as jnp

    from p2pmicrogrid_tpu.train.checkpoint import restore_resume_state

    try:
        st = restore_resume_state(ckpt_dir, template_pol_state)
    except FileNotFoundError:
        return ResumePlan(
            pol_state=template_pol_state, cfg=cfg, key=base_key,
            warmup=True, resumed=False, exact=False,
        )
    episode0 = st.episode + 1
    cfg = cfg.replace(
        train=dataclasses.replace(cfg.train, starting_episodes=episode0)
    )
    if st.rng_key is not None:
        key = jnp.asarray(st.rng_key)
        return ResumePlan(
            pol_state=st.pol_state, cfg=cfg, key=key, warmup=False,
            resumed=True, exact=True, episode=st.episode, extra=st.extra,
        )
    key = jax.random.fold_in(base_key, episode0)
    return ResumePlan(
        pol_state=st.pol_state, cfg=cfg, key=key, warmup=True,
        resumed=True, exact=False, episode=st.episode, extra=st.extra,
    )


def checkpoint_callback(
    ckpt_dir: str,
    cfg,
    injector=None,
    extra_fn: Optional[Callable[[], dict]] = None,
    keep_last: int = 2,
) -> Callable:
    """The resumable checkpoint callback for ``train_community``: saves the
    learner state WITH the RNG-key chain (3-arg form — the loop hands the
    post-split key over) and the ``extra_fn()`` record, stamps the config
    hash, and runs the fault injector's post-save hooks (checkpoint
    corruption, callback stalls — train/faults.py)."""
    from p2pmicrogrid_tpu.train.checkpoint import save_checkpoint

    def cb(ep, ps, rng_key=None):
        step = save_checkpoint(
            ckpt_dir, ps, ep,
            rng_key=rng_key,
            extra=extra_fn() if extra_fn else None,
            cfg=cfg, keep_last=keep_last,
        )
        if injector is not None:
            injector.on_checkpoint_saved(ep, step)
            injector.on_callback(ep)
        return step

    return cb


# --- divergence rollback driver ----------------------------------------------


@dataclass
class RollbackRecord:
    index: int                 # 1-based rollback count
    tripped_episode: int
    reason: str
    restored_episode: int      # -1 = restored the initial state
    lr_scale: float


def train_community_with_rollback(
    cfg,
    pol_state,
    traces,
    ratings,
    key,
    ckpt_dir: str,
    policy_factory: Optional[Callable] = None,
    guard_policy: GuardPolicy = GuardPolicy(),
    telemetry=None,
    fault_injector=None,
    on_rollback: Optional[Callable[[RollbackRecord], None]] = None,
    warmup: bool = True,
    extra_fn: Optional[Callable[[], dict]] = None,
    keep_last: int = 2,
    **train_kw,
) -> Tuple[object, List[RollbackRecord]]:
    """``train_community`` under the divergence guard, with capped rollback.

    On a ``DivergenceTripped``: restore the newest verified checkpoint
    (or the caller's initial state when none exists yet), drop the
    effective lrs by ``lr_drop**attempt``, branch the restored RNG chain
    with ``fold_in(key, ROLLBACK_KEY_SALT + attempt)`` (a fresh,
    deterministic stream — replaying the exact trajectory that diverged
    would diverge again), and re-enter. ``policy_factory(cfg)`` rebuilds
    the policy for the perturbed config (defaults to ``train.make_policy``).
    Raises ``RollbackExhausted`` after ``max_rollbacks`` failed recoveries.

    Returns ``(TrainResult, rollback_records)``. ``**train_kw`` forwards to
    ``train_community`` (pipeline, progress_cb, verbose, ...).
    """
    import jax

    from p2pmicrogrid_tpu.train import make_policy, train_community
    from p2pmicrogrid_tpu.train.checkpoint import restore_resume_state

    if policy_factory is None:
        policy_factory = make_policy
    base_cfg, base_key = cfg, key
    cur_cfg, cur_ps, cur_key, cur_warmup = cfg, pol_state, key, warmup
    rollbacks: List[RollbackRecord] = []
    attempt = 0
    while True:
        guard = DivergenceGuard(guard_policy, telemetry=telemetry)
        policy = policy_factory(cur_cfg)
        ckpt_cb = checkpoint_callback(
            ckpt_dir, cur_cfg, injector=fault_injector, extra_fn=extra_fn,
            keep_last=keep_last,
        )
        fault_hook = (
            fault_injector.on_block_start if fault_injector is not None else None
        )
        try:
            result = train_community(
                cur_cfg, policy, cur_ps, traces, ratings, cur_key,
                checkpoint_cb=ckpt_cb, telemetry=telemetry, guard=guard,
                fault_hook=fault_hook, warmup=cur_warmup, **train_kw,
            )
            return result, rollbacks
        except DivergenceTripped as trip:
            attempt += 1
            if attempt > guard_policy.max_rollbacks:
                raise RollbackExhausted(
                    f"divergence persisted through {guard_policy.max_rollbacks} "
                    f"rollback(s); last trip: {trip}"
                ) from trip
            span = (
                telemetry.span("rollback", attempt=attempt, episode=trip.episode)
                if telemetry is not None
                else contextlib.nullcontext()
            )
            with span:
                try:
                    st = restore_resume_state(ckpt_dir, pol_state)
                    restored_ep, cur_ps = st.episode, st.pol_state
                    restore_key = (
                        jax.numpy.asarray(st.rng_key)
                        if st.rng_key is not None
                        else jax.random.fold_in(base_key, st.episode + 1)
                    )
                    episode0 = st.episode + 1
                    cur_warmup = False
                except FileNotFoundError:
                    # Tripped before the first save: the initial state is
                    # the last good one.
                    restored_ep, cur_ps = -1, pol_state
                    restore_key = base_key
                    episode0 = base_cfg.train.starting_episodes
                    cur_warmup = warmup
            lr_scale = guard_policy.lr_drop ** attempt
            cur_cfg = scaled_lr_cfg(base_cfg, lr_scale).replace(
                train=dataclasses.replace(
                    base_cfg.train, starting_episodes=episode0
                )
            )
            cur_key = jax.random.fold_in(restore_key, ROLLBACK_KEY_SALT + attempt)
            record = RollbackRecord(
                index=attempt,
                tripped_episode=trip.episode,
                reason=trip.reason,
                restored_episode=restored_ep,
                lr_scale=lr_scale,
            )
            rollbacks.append(record)
            if telemetry is not None:
                telemetry.counter("train.rollback")
                telemetry.event(
                    "rollback",
                    attempt=attempt,
                    episode=trip.episode,
                    restored_episode=restored_ep,
                    lr_scale=lr_scale,
                    reason=trip.reason,
                )
            if on_rollback is not None:
                on_rollback(record)


def train_chunked_with_rollback(
    cfg,
    pol_state,
    ratings,
    key,
    ckpt_dir: str,
    n_episodes: int,
    n_chunks: int,
    eval_every: int = 10,
    episode0: int = 0,
    guard_policy: GuardPolicy = GuardPolicy(),
    telemetry=None,
    policy_factory: Optional[Callable] = None,
    on_rollback: Optional[Callable[[RollbackRecord], None]] = None,
    save_every: Optional[int] = None,
    keep_last: int = 2,
    health_cb: Optional[Callable] = None,
    episode_cb: Optional[Callable] = None,
    carry_sync: Optional[Callable] = None,
    monitor=None,
    pipeline: bool = True,
    chunk_parallel: int = 1,
    mitigate: str = "warn",
    s_eval: int = 8,
) -> Tuple[tuple, List[RollbackRecord]]:
    """``train_chunked_with_health`` under the divergence guard, with the
    same restore/perturb/re-enter discipline as
    ``train_community_with_rollback`` (the chunked half of the ROADMAP
    training-resilience follow-on — the guard hooks existed, this is the
    driver that acts on them).

    Each attempt runs the chunked trainer with a fresh ``DivergenceGuard``
    fed by the block-boundary evals. On a trip: restore the newest
    VERIFIED checkpoint under ``ckpt_dir`` (falling back to the caller's
    initial state before the first save), scale the effective lrs by
    ``lr_drop**attempt``, and re-enter from the restored episode on a
    ``fold_in(base_key, SALT + attempt)`` branch. Chunked runs key every
    chunk by ABSOLUTE episode off the base key (scenarios.py
    ``chunk_key_fn``), so branching the base key re-keys the surviving
    episodes onto a fresh deterministic stream — replaying the exact
    stream that diverged would diverge again.

    Without a caller ``episode_cb``, the driver checkpoints the carry on
    the ``save_every`` cadence itself (and installs the matching
    ``carry_sync`` so pipelined runs drain the carry on save episodes).
    Returns ``((pol_state, rewards, losses, seconds, monitor),
    rollback_records)`` — the trainer outputs are the FINAL attempt's.
    """
    import jax

    from p2pmicrogrid_tpu.train import make_policy
    from p2pmicrogrid_tpu.train.checkpoint import (
        restore_resume_state,
        save_checkpoint,
    )
    from p2pmicrogrid_tpu.train.health import train_chunked_with_health

    if policy_factory is None:
        policy_factory = make_policy
    save_every = save_every or cfg.train.save_episodes
    base_cfg, base_key = cfg, key
    cur_cfg, cur_ps, cur_key = cfg, pol_state, key
    base_episode0 = episode0
    end_episode = episode0 + n_episodes
    rollbacks: List[RollbackRecord] = []
    attempt = 0
    while True:
        guard = DivergenceGuard(guard_policy, telemetry=telemetry)
        policy = policy_factory(cur_cfg)
        if episode_cb is None:
            ckpt_cfg = cur_cfg

            def _cb(ep, r, l, carry, _cfg=ckpt_cfg):
                if (ep + 1) % save_every == 0:
                    save_checkpoint(
                        ckpt_dir, carry, ep, cfg=_cfg, keep_last=keep_last
                    )

            cb = _cb
            sync = carry_sync or (lambda ep: (ep + 1) % save_every == 0)
        else:
            cb, sync = episode_cb, carry_sync
        try:
            result = train_chunked_with_health(
                cur_cfg, policy, cur_ps, ratings, cur_key,
                n_episodes=end_episode - episode0,
                n_chunks=n_chunks,
                eval_every=eval_every,
                episode0=episode0,
                episode_cb=cb,
                chunk_parallel=chunk_parallel,
                mitigate=mitigate,
                health_cb=health_cb,
                # The caller's monitor (checkpoint-restored basin state on
                # --resume) rides the FIRST attempt only: after a trip its
                # history reflects the diverged trajectory, so rollback
                # attempts recalibrate fresh (episode0 > 0 triggers the
                # untrained-reference recalibration in the health driver).
                monitor=monitor if attempt == 0 else None,
                s_eval=s_eval,
                telemetry=telemetry,
                pipeline=pipeline,
                carry_sync=sync,
                guard=guard,
            )
            return result, rollbacks
        except DivergenceTripped as trip:
            attempt += 1
            if attempt > guard_policy.max_rollbacks:
                raise RollbackExhausted(
                    f"divergence persisted through "
                    f"{guard_policy.max_rollbacks} rollback(s); "
                    f"last trip: {trip}"
                ) from trip
            span = (
                telemetry.span("rollback", attempt=attempt,
                               episode=trip.episode)
                if telemetry is not None
                else contextlib.nullcontext()
            )
            with span:
                try:
                    st = restore_resume_state(ckpt_dir, pol_state)
                    restored_ep, cur_ps = st.episode, st.pol_state
                    episode0 = st.episode + 1
                except FileNotFoundError:
                    # Tripped before the first save: the initial state is
                    # the last good one.
                    restored_ep, cur_ps = -1, pol_state
                    episode0 = base_episode0
            lr_scale = guard_policy.lr_drop ** attempt
            cur_cfg = scaled_lr_cfg(base_cfg, lr_scale)
            cur_key = jax.random.fold_in(
                base_key, ROLLBACK_KEY_SALT + attempt
            )
            record = RollbackRecord(
                index=attempt,
                tripped_episode=trip.episode,
                reason=trip.reason,
                restored_episode=restored_ep,
                lr_scale=lr_scale,
            )
            rollbacks.append(record)
            if telemetry is not None:
                telemetry.counter("train.rollback")
                telemetry.event(
                    "rollback",
                    attempt=attempt,
                    episode=trip.episode,
                    restored_episode=restored_ep,
                    lr_scale=lr_scale,
                    reason=trip.reason,
                )
            if on_rollback is not None:
                on_rollback(record)


# --- crash supervisor ---------------------------------------------------------


ATTEMPT_ENV = "P2P_TRAIN_ATTEMPT"


@dataclass
class SuperviseResult:
    exit_code: int
    attempts: List[dict] = field(default_factory=list)
    kills: int = 0              # attempts that died to a signal
    resumes: int = 0            # relaunches (attempts after the first)
    rollbacks: int = 0          # train_rollback rows seen in child stdout

    @property
    def succeeded(self) -> bool:
        return self.exit_code == 0


def supervise(
    child_argv: List[str],
    max_restarts: int = 8,
    backoff_s: float = 0.5,
    backoff_cap_s: float = 8.0,
    resume_flag: Optional[str] = "--resume",
    env: Optional[dict] = None,
    emit: Optional[Callable[[dict], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    passthrough=None,
) -> SuperviseResult:
    """Run a training child under crash supervision.

    The child is relaunched on any non-zero exit (SIGKILL preemption, OOM,
    divergence the child could not roll back from) with deterministic capped
    exponential backoff (``min(backoff_cap_s, backoff_s * 2**restarts)`` —
    no jitter: replayability over thundering herds of one). From the second
    attempt on ``resume_flag`` is appended (unless already present) so the
    child continues from its newest verified checkpoint, and every attempt
    exports ``P2P_TRAIN_ATTEMPT`` so a deterministic fault plan
    (train/faults.py) fires each crash exactly once.

    Child stdout is streamed through (``passthrough``, default this
    process's stdout) and scanned for ``train_rollback`` metric rows so the
    harness can report rollback counts without a side channel. ``emit`` (if
    given) receives one ``supervise_attempt`` metric row per attempt.
    """
    out = passthrough if passthrough is not None else sys.stdout
    result = SuperviseResult(exit_code=1)
    attempt = 0
    while True:
        argv = list(child_argv)
        if attempt > 0 and resume_flag and resume_flag not in argv:
            argv.append(resume_flag)
        child_env = dict(os.environ)
        child_env.update(env or {})
        child_env[ATTEMPT_ENV] = str(attempt)
        t0 = time.time()
        proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=None, text=True, env=child_env
        )
        rollbacks_this = 0
        assert proc.stdout is not None
        for line in proc.stdout:
            out.write(line)
            if '"train_rollback"' in line:
                try:
                    row = json.loads(line)
                    if isinstance(row, dict) and row.get("metric") == "train_rollback":
                        rollbacks_this += 1
                except json.JSONDecodeError:
                    pass
        rc = proc.wait()
        duration = time.time() - t0
        row = {
            "metric": "supervise_attempt",
            "value": attempt,
            "unit": "attempt",
            "vs_baseline": 0.0,
            "exit_code": rc,
            "signal": -rc if rc < 0 else 0,
            "duration_s": round(duration, 3),
            "resumed": attempt > 0,
            "rollbacks": rollbacks_this,
        }
        result.attempts.append(row)
        result.rollbacks += rollbacks_this
        if rc < 0:
            result.kills += 1
        if attempt > 0:
            result.resumes += 1
        if emit is not None:
            emit(row)
        if rc == 0:
            result.exit_code = 0
            return result
        if attempt >= max_restarts:
            result.exit_code = rc if rc > 0 else 1
            return result
        sleep(min(backoff_cap_s, backoff_s * (2 ** attempt)))
        attempt += 1
