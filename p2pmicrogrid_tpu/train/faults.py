"""Deterministic fault injection for the TRAINING tier.

The serve fleet's chaos harness (serve/faults.py) made every injected fault
a pure function of a seed so failing runs replay exactly; this module is the
training-side mirror. A crash-resilience claim ("SIGKILL mid-training,
auto-resume, bit-identical final params") is only testable when the kill
lands at the SAME episode every run — wall-clock kill timers would turn the
acceptance test into a flake.

Fault kinds (all single-shot per event, applied at episode boundaries):

* ``kill``                SIGKILL the training process when the loop reaches
                          the event's episode (block granularity — the hook
                          runs between fused jit blocks). ``kill_mode="raise"``
                          raises ``SimulatedPreemption`` instead, so tier-1
                          tests can exercise the full save→die→restore→resume
                          path in one process.
* ``corrupt_checkpoint``  after the checkpoint save at/after the event's
                          episode, flip bytes in the step's largest payload
                          file — the restore-time digest verification must
                          catch it and fall back (train/checkpoint.py).
* ``stall_callback``      sleep ``stall_s`` inside the host callback (the
                          preemption-window widener: a slow host callback is
                          exactly when SIGKILL likes to land).
* ``poison_nan``          overwrite every floating leaf of the learner carry
                          with NaN at the event's episode — the divergence
                          the rollback guard (train/resilience.py) must
                          detect via the in-program ``nonfinite_q``/
                          ``nonfinite_loss`` counters and roll back from.

**Attempts.** Crash faults must not re-fire after the supervisor relaunches
the run (a kill that fires on every attempt is a crash loop, useful only for
testing the supervisor's restart cap). Each event carries an ``attempt``
index: ``None`` fires on every attempt; ``k`` fires only when the injector
is constructed with ``attempt == k`` (the supervisor exports
``P2P_TRAIN_ATTEMPT`` to the child). ``kill_plan``'s k-th kill fires on
attempt k, so a plan of N kills crashes exactly N times and then completes.

**Determinism.** ``kill_plan`` derives its kill episodes from
``sha256(seed : kill : k)`` mapped into the run's episode range — no RNG
state, no wall clock. JSON round-trip (``TrainFaultPlan.to_json`` /
``from_json``) matches serve/faults.py so chaos runs are shareable artifacts
and CLI inputs (``train --fault-plan plan.json``).
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import time
from dataclasses import asdict, dataclass
from typing import List, Optional, Tuple

FAULT_KINDS = ("kill", "corrupt_checkpoint", "stall_callback", "poison_nan")


class SimulatedPreemption(RuntimeError):
    """Raised instead of SIGKILL in ``kill_mode="raise"`` (in-process tests)."""

    def __init__(self, episode: int):
        super().__init__(f"simulated preemption at episode {episode}")
        self.episode = episode


@dataclass(frozen=True)
class TrainFaultEvent:
    """One training fault. ``episode`` is the trigger boundary (the event
    fires at the first block whose start episode is >= it); ``attempt``
    scopes it to one supervisor attempt (``None`` = every attempt)."""

    kind: str
    episode: int
    attempt: Optional[int] = 0
    stall_s: float = 0.0

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown train fault kind {self.kind!r}")
        if self.episode < 0:
            raise ValueError(f"episode must be >= 0, got {self.episode}")
        if self.kind == "stall_callback" and self.stall_s <= 0.0:
            raise ValueError("stall_callback events need stall_s > 0")


@dataclass(frozen=True)
class TrainFaultPlan:
    """A seed plus an ordered tuple of events — one whole chaos run."""

    seed: int
    events: Tuple[TrainFaultEvent, ...] = ()

    def __post_init__(self):
        if not isinstance(self.events, tuple):
            object.__setattr__(self, "events", tuple(self.events))

    def to_json(self) -> str:
        return json.dumps(
            {
                "kind": "train_fault_plan",
                "seed": self.seed,
                "events": [asdict(e) for e in self.events],
            },
            indent=2,
        )

    @classmethod
    def from_json(cls, text: str) -> "TrainFaultPlan":
        doc = json.loads(text)
        if not isinstance(doc, dict) or doc.get("kind") != "train_fault_plan":
            raise ValueError("not a train_fault_plan document")
        events = tuple(
            TrainFaultEvent(**{str(k): v for k, v in e.items()})
            for e in doc.get("events", [])
        )
        return cls(seed=int(doc["seed"]), events=events)


def _episode_of(seed: int, label: str, k: int, lo: int, hi: int) -> int:
    """Deterministic episode in [lo, hi) for the k-th event of a kind."""
    if hi <= lo:
        return lo
    digest = hashlib.sha256(f"{seed}:{label}:{k}".encode()).digest()
    return lo + int.from_bytes(digest[:8], "big") % (hi - lo)


def kill_plan(
    seed: int,
    n_episodes: int,
    n_kills: int = 1,
    min_episode: int = 1,
) -> TrainFaultPlan:
    """The canonical preemption plan: ``n_kills`` SIGKILLs at seed-derived
    episodes in [``min_episode``, ``n_episodes``), the k-th firing on
    supervisor attempt k — so the supervised run crashes exactly
    ``n_kills`` times, resumes each time, and completes on attempt
    ``n_kills``."""
    events = tuple(
        TrainFaultEvent(
            kind="kill",
            episode=_episode_of(seed, "kill", k, min_episode, max(n_episodes, min_episode + 1)),
            attempt=k,
        )
        for k in range(n_kills)
    )
    return TrainFaultPlan(seed=seed, events=events)


def corrupt_step_files(step_path: str, n_bytes: int = 4) -> Optional[str]:
    """Flip ``n_bytes`` in the middle of the step's largest payload file
    (deterministic: same step layout → same bytes). Returns the corrupted
    file's path, or ``None`` when the step has no file large enough. The
    integrity manifest itself is left intact — the DIGEST must catch this,
    not a JSON parse error."""
    from p2pmicrogrid_tpu.train.checkpoint import MANIFEST_NAME

    candidates = []
    for dirpath, _dirs, files in os.walk(step_path):
        for f in files:
            if f == MANIFEST_NAME:
                continue
            p = os.path.join(dirpath, f)
            try:
                candidates.append((os.path.getsize(p), p))
            except OSError:
                continue
    candidates.sort(reverse=True)
    for size, p in candidates:
        if size < n_bytes:
            continue
        with open(p, "r+b") as f:
            f.seek(size // 2)
            chunk = f.read(n_bytes)
            f.seek(size // 2)
            f.write(bytes(b ^ 0xFF for b in chunk))
        return p
    return None


def poison_pol_state(pol_state):
    """Every floating leaf of the carry becomes NaN (integer leaves —
    replay cursors, episode counters — survive, so the poisoned state still
    runs and the divergence surfaces through the in-program counters)."""
    import jax
    import jax.numpy as jnp

    def leaf(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return jnp.full_like(x, jnp.nan)
        return x

    return jax.tree_util.tree_map(leaf, pol_state)


class TrainFaultInjector:
    """Applies a plan's events against one training process.

    Hook points (train/loop.py + the CLI checkpoint callback):

    * ``on_block_start(episode, pol_state)`` — at each fused-block boundary;
      fires ``kill`` (SIGKILL / ``SimulatedPreemption``) and ``poison_nan``
      (returns the poisoned carry, else ``None``).
    * ``on_checkpoint_saved(episode, step_path)`` — after a save; fires
      ``corrupt_checkpoint``.
    * ``on_callback(episode)`` — inside host callbacks; fires
      ``stall_callback``.

    Every event is single-shot (``fired``); ``history`` records
    ``(kind, episode, event_index)`` for replay assertions.
    """

    def __init__(
        self,
        plan: TrainFaultPlan,
        attempt: int = 0,
        kill_mode: str = "sigkill",
        sleep=time.sleep,
    ):
        if kill_mode not in ("sigkill", "raise"):
            raise ValueError(f"kill_mode must be 'sigkill' or 'raise', got {kill_mode!r}")
        self.plan = plan
        self.attempt = attempt
        self.kill_mode = kill_mode
        self._sleep = sleep
        self._fired: set = set()
        self.history: List[Tuple[str, int, int]] = []

    def _pending(self, kind: str, episode: int):
        for i, e in enumerate(self.plan.events):
            if e.kind != kind or i in self._fired:
                continue
            if e.attempt is not None and e.attempt != self.attempt:
                continue
            if episode >= e.episode:
                yield i, e

    def _fire(self, i: int, e: TrainFaultEvent, episode: int) -> None:
        self._fired.add(i)
        self.history.append((e.kind, episode, i))

    def on_block_start(self, episode: int, pol_state=None):
        for i, e in self._pending("kill", episode):
            self._fire(i, e, episode)
            if self.kill_mode == "raise":
                raise SimulatedPreemption(episode)
            os.kill(os.getpid(), signal.SIGKILL)
        poisoned = None
        for i, e in self._pending("poison_nan", episode):
            self._fire(i, e, episode)
            if pol_state is not None:
                poisoned = poison_pol_state(
                    pol_state if poisoned is None else poisoned
                )
        return poisoned

    def on_checkpoint_saved(self, episode: int, step_path: str) -> None:
        for i, e in self._pending("corrupt_checkpoint", episode):
            self._fire(i, e, episode)
            corrupt_step_files(step_path)

    def on_callback(self, episode: int) -> None:
        for i, e in self._pending("stall_callback", episode):
            self._fire(i, e, episode)
            self._sleep(e.stall_s)
