"""Million-household scale tier (ROADMAP item 4).

The serving stack's correctness story was proven at tens of households
per replica; this package is where the same stack is exercised — and
audited — at a MILLION household ids:

* ``population``: a deterministic synthetic household population —
  stable ids over a seeded 1M-id space, Zipf-skewed request mix shaped
  by per-household rate classes, join/leave churn — usable as a drop-in
  arrival source for the fleet loadgen and the virtual-clock scale
  bench.
* ``bench``: the virtual-clock fleet bench behind ``serve-bench --fleet
  --population``: real per-replica ``plan_open_loop`` dispatch over a
  measured engine service model, real consistent-hash ring placement,
  real per-replica SQLite shard ingest — sustained rps/replica, p99 and
  warehouse ingest lag at 1M households plus the replica-scaling rows.
* ``audit``: structural O(1)-per-request audits of the router, registry
  and session ring — the checks that nothing on the request path (or in
  a stats snapshot) iterates or materializes the household id space.
"""

from p2pmicrogrid_tpu.scale.audit import (
    audit_registry_scalability,
    audit_ring_scalability,
    audit_router_scalability,
    run_scale_audit,
)
from p2pmicrogrid_tpu.scale.bench import serve_bench_scale
from p2pmicrogrid_tpu.scale.population import (
    Population,
    PopulationConfig,
    RATE_CLASSES,
)

__all__ = [
    "Population",
    "PopulationConfig",
    "RATE_CLASSES",
    "serve_bench_scale",
    "audit_registry_scalability",
    "audit_ring_scalability",
    "audit_router_scalability",
    "run_scale_audit",
]
