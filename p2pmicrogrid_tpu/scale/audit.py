"""Structural O(1)-per-request audits of the serving stack at 1M ids.

"Scales to a million households" is not a benchmark claim alone — it is
a set of structural properties of the request path and the
observability path, each of which a later refactor could silently
break:

* the consistent-hash ring's lookup table is sized by ``replicas x
  vnodes``, never by households;
* the router's pin map records only FAILOVER placements (bounded by
  failover events, not population), and its snapshot API is capped;
* the registry's ``stats()`` never iterates the id-keyed pin map — the
  per-bundle tallies are maintained incrementally on the route path;
* the continuous batcher's host tables are bounded by ``max_slots``
  regardless of how many distinct households ever joined.

The audits here verify those properties directly. The iteration checks
use ``_NoIterDict`` — a dict whose Python-level iteration RAISES — so a
stats snapshot that regresses to scanning the id space fails loudly in
tests/test_scale.py instead of shipping as an O(households) poll.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

import numpy as np


class _NoIterDict(dict):
    """A dict that forbids Python-level iteration (``len``/``get``/
    ``[]``/``pop``/membership stay allowed): the tripwire planted in
    place of an id-keyed map while auditing that a code path is O(1) in
    the map's size. ``allow()`` scopes the intentional, BOUNDED
    iterations (e.g. the capped ``pinned_households`` snapshot)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._iter_ok = 0

    def _refuse(self, what: str):
        if not self._iter_ok:
            raise AssertionError(
                f"O(1) audit tripped: {what} iterated an id-keyed map "
                f"of {len(self)} entries on a path that must not scale "
                "with the household population"
            )

    def allow(self):
        audit = self

        class _Ctx:
            def __enter__(self):
                audit._iter_ok += 1

            def __exit__(self, *exc):
                audit._iter_ok -= 1

        return _Ctx()

    def __iter__(self):
        self._refuse("__iter__")
        return super().__iter__()

    def keys(self):
        self._refuse("keys()")
        return super().keys()

    def values(self):
        self._refuse("values()")
        return super().values()

    def items(self):
        self._refuse("items()")
        return super().items()


def audit_ring_scalability(
    ring, sample_ids: Iterable[str], tolerance: float = 0.15
) -> dict:
    """The ring's lookup structure is sized by replicas x vnodes (never
    by households) and spreads a household sample within ``tolerance``
    of even. Returns the audit fields; raises AssertionError on a
    structural violation (spread is REPORTED, judged by the caller —
    it is statistical, not structural)."""
    n_replicas = len(ring._replicas)
    expected = n_replicas * ring.vnodes
    if len(ring._points) != expected or len(ring._owners) != expected:
        raise AssertionError(
            f"ring holds {len(ring._points)} points for {n_replicas} "
            f"replicas x {ring.vnodes} vnodes — the lookup table must be "
            "exactly replicas x vnodes, independent of households routed"
        )
    counts: Dict[str, int] = {}
    n = 0
    for hid in sample_ids:
        owner = ring.lookup(hid)
        counts[owner] = counts.get(owner, 0) + 1
        n += 1
    mean = n / max(1, n_replicas)
    spread = max(
        abs(counts.get(r, 0) - mean) / mean for r in ring._replicas
    ) if n else 0.0
    return {
        "replicas": n_replicas,
        "vnodes": ring.vnodes,
        "ring_points": len(ring._points),
        "sample": n,
        "load_spread": round(float(spread), 4),
        "within_tolerance": bool(spread <= tolerance),
    }


def audit_router_scalability(router, snapshot_limit: int = 1000) -> dict:
    """Pin map bounded by failover events + capped snapshots. Plants a
    ``_NoIterDict`` over the router's pins and exercises the per-request
    bookkeeping (``_record_route``) — a regression that iterates pins on
    the request path raises. Restores the router's real pin map."""
    original = router._pins
    guarded = _NoIterDict(original)
    router._pins = guarded
    try:
        # Home placement must DROP a pin without iterating the map.
        probe = "audit-probe-household"
        home = router._ring.lookup(probe)
        router._record_route(probe, home)
        if probe in guarded:
            raise AssertionError(
                "home placement left a pin: pins must record only "
                "failover placements"
            )
        # Failover placement pins exactly the one household.
        other = next(
            (r for r in router._order if r != home), home
        )
        before = len(guarded)
        if other != home:
            router._record_route(probe, other)
            if len(guarded) != before + 1:
                raise AssertionError(
                    "failover placement must pin exactly the routed "
                    "household"
                )
            router._record_route(probe, home)  # back home: pin drops
        with guarded.allow():
            snap = router.pinned_households(limit=snapshot_limit)
        if len(snap) > snapshot_limit:
            raise AssertionError(
                f"pinned_households returned {len(snap)} entries over "
                f"the {snapshot_limit} cap"
            )
    finally:
        with guarded.allow():
            router._pins = dict(guarded)
    return {
        "pins": len(router._pins),
        "failovers": int(router.counters["failovers"]),
        "repins": int(router.counters["repins"]),
        "snapshot_limit": snapshot_limit,
        "snapshot_len": len(snap),
    }


def audit_registry_scalability(registry) -> dict:
    """``stats()`` is O(bundles): plants a ``_NoIterDict`` over the
    registry's pins, takes a stats snapshot (raises if the snapshot
    iterates the id space) and cross-checks the incremental per-bundle
    tallies against the pin map's size."""
    with registry._lock:
        guarded = _NoIterDict(registry._pins)
        registry._pins = guarded
    try:
        snapshot = registry.stats()
    finally:
        with registry._lock, guarded.allow():
            registry._pins = dict(guarded)
    tallied = sum(
        b["pinned_households"] for b in snapshot["bundles"].values()
    )
    if tallied != len(registry._pins):
        raise AssertionError(
            f"incremental pin tallies sum to {tallied} but the pin map "
            f"holds {len(registry._pins)} households — the route-path "
            "bookkeeping drifted from the map"
        )
    return {
        "bundles": len(snapshot["bundles"]),
        "pinned_total": tallied,
    }


def audit_session_ring(batcher) -> dict:
    """The batcher's host tables are bounded by ``max_slots`` (and the
    spill tracker by its fixed cap) no matter how many distinct
    households have ever joined."""
    with batcher._cv:
        slots = len(batcher._slots)
        resident = len(batcher._by_household)
        evicted = len(batcher._recently_evicted)
        cap = batcher._recently_evicted_cap
    if slots != batcher.max_slots:
        raise AssertionError(
            f"slot table holds {slots} rows for max_slots="
            f"{batcher.max_slots}"
        )
    if resident > batcher.max_slots:
        raise AssertionError(
            f"{resident} resident households exceed max_slots="
            f"{batcher.max_slots} — the ring grew with the population"
        )
    if evicted > cap:
        raise AssertionError(
            f"recently-evicted tracker holds {evicted} > cap {cap}"
        )
    return {
        "max_slots": batcher.max_slots,
        "resident": resident,
        "recently_evicted": evicted,
        "recently_evicted_cap": cap,
        "spill_rejoins": int(batcher.stats["spill_rejoins"]),
    }


def run_scale_audit(
    n_households: int = 1_000_000,
    sample: int = 100_000,
    vnodes: int = 4096,
    replica_counts: Iterable[int] = (3, 10, 30),
    seed: int = 0,
) -> dict:
    """The standalone structural audit at population scale: a fresh ring
    per replica count routed with a real Zipf population sample, plus a
    pin-map-guarded router over the largest fleet. In-process and
    socket-free — the audited objects are the REAL classes, only the
    network endpoints behind them are inert."""
    from p2pmicrogrid_tpu.scale.population import Population
    from p2pmicrogrid_tpu.serve.router import (
        ConsistentHashRing,
        FleetRouter,
        Replica,
    )

    pop = Population(n_households=n_households, seed=seed)
    idx = pop.sample(sample, seed=seed + 1)
    # Spread is a property of hash placement over UNIQUE keys; weighting
    # by request count would conflate it with arrival skew.
    unique_ids = pop.ids(np.unique(idx))

    rings = []
    for n_replicas in replica_counts:
        ring = ConsistentHashRing(vnodes=vnodes)
        for r in range(n_replicas):
            ring.add(f"replica-{r}")
        rings.append(audit_ring_scalability(ring, unique_ids))

    max_replicas = max(replica_counts)
    router = FleetRouter(
        [
            Replica(replica_id=f"replica-{r}", host="127.0.0.1", port=1)
            for r in range(max_replicas)
        ],
        vnodes=vnodes,
    )
    router_audit = audit_router_scalability(router)

    return {
        "n_households": n_households,
        "sample": sample,
        "unique_sampled": len(unique_ids),
        "rings": rings,
        "router": router_audit,
        "population_skew": pop.skew_summary(idx),
    }
