"""Virtual-clock fleet bench at million-household population scale.

The socket-mode fleet bench (``serve_bench_fleet``) measures the REAL
wire — and tops out around a few thousand rps per host, far below the
offered load a metropolitan P2P fleet sees. This bench measures the
same serving policies at 100k+ rps by replaying them on the virtual
clock, keeping every load-bearing component real:

* **Arrivals** come from the synthetic population engine — Zipf x
  rate-class weighted household draws with churn, on the exact
  integer-nanosecond Poisson schedule (``loadgen.poisson_arrivals``).
* **Placement** is the real ``ConsistentHashRing`` (sha256 + bisect),
  one lookup per unique household, at the vnode count under test — the
  replica-spread numbers are hash placement, not a model of it.
* **Dispatch** is the real ``plan_open_loop`` replay of the microbatch
  policy, per replica, over that replica's own arrival subsequence.
* **Service times** are MEASURED per bucket on a warmed ``PolicyEngine``
  (or supplied as an explicit model in tests) — the one modelled
  quantity, and it is a measurement, not an assumption.
* **Warehouse ingest** is real: each replica writes its batch telemetry
  through its own WAL-mode ``SqliteSink`` shard, and the headline's
  ``ingest_lag_ms`` is the sink's own ingest-lag gauge read back from
  the shard files after a ``merge_warehouse_shards`` federation pass.
* **Session spill** is a deterministic LRU replay of each replica's
  household sequence against ``max_slots`` — the measured policy behind
  the continuous batcher's eviction/rejoin accounting.

Emitted rows (headline LAST, ``serve_bench_scale``): one
``scale_replica_sweep`` row per replica count, one ``scale_scaling``
row with the spread-vs-replicas table, one ``scale_spill`` row, then
the headline with sustained rps/replica, p99 and warehouse ingest lag
at the full population. ``tools/check_artifacts_schema.py`` validates
the committed ``artifacts/SCALE_*.jsonl`` against this contract.
"""

from __future__ import annotations

import sqlite3
import time
from collections import OrderedDict
from typing import Callable, Dict, Iterable, List, Optional

import numpy as np

from p2pmicrogrid_tpu.scale.population import Population, PopulationConfig


def _bucket_for(n: int, max_batch: int) -> int:
    """Engine's bucket rule (next power of two, capped) without needing
    an engine — keeps the modeled path usable in engine-less tests."""
    b = 1
    while b < n:
        b <<= 1
    return min(b, max_batch)


def measure_bucket_service_model(
    engine, repeats: int = 5, seed: int = 0
) -> Dict[int, float]:
    """Median measured ``engine.act`` seconds per batch bucket on the
    warmed engine — the service-time model ``plan_open_loop`` replays.
    Median (not min) so a one-off scheduler stall cannot understate, and
    one-off cache luck cannot overstate, sustained capacity."""
    from p2pmicrogrid_tpu.serve.loadgen import synthetic_obs

    engine.warmup(include_step=False)
    model: Dict[int, float] = {}
    for bucket in engine.buckets:
        obs = synthetic_obs(bucket, engine.n_agents, seed=seed)
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            engine.act(obs)
            times.append(time.perf_counter() - t0)
        model[bucket] = float(np.median(times))
    return model


def _assign_replicas(
    pop: Population,
    idx: np.ndarray,
    replica_ids: List[str],
    vnodes: int,
):
    """(per-request replica ordinal [n], ring) — one REAL ring lookup per
    unique household (cached), never per request and never over the full
    id space."""
    from p2pmicrogrid_tpu.serve.router import ConsistentHashRing

    ring = ConsistentHashRing(vnodes=vnodes)
    for rid in replica_ids:
        ring.add(rid)
    ordinal = {rid: i for i, rid in enumerate(replica_ids)}
    unique = np.unique(idx)
    lut = np.empty(unique.shape[0], dtype=np.int32)
    for u, household_index in enumerate(unique):
        lut[u] = ordinal[ring.lookup(pop.household_id(int(household_index)))]
    return lut[np.searchsorted(unique, idx)], ring


def _simulate_lru_spill(
    household_seq: np.ndarray, max_slots: int
) -> Dict[str, int]:
    """Deterministic replay of the continuous batcher's LRU slot policy
    over one replica's household sequence: hits (resident), joins,
    evictions, and rejoins (evicted households returning — each one a
    session re-init the fleet pays for an undersized ring)."""
    resident: OrderedDict = OrderedDict()
    evicted_once: set = set()
    hits = joins = evictions = rejoins = 0
    for h in household_seq:
        h = int(h)
        if h in resident:
            resident.move_to_end(h)
            hits += 1
            continue
        if h in evicted_once:
            rejoins += 1
        joins += 1
        if len(resident) >= max_slots:
            victim, _ = resident.popitem(last=False)
            evicted_once.add(victim)
            evictions += 1
        resident[h] = True
    return {
        "requests": int(household_seq.shape[0]),
        "hits": hits,
        "joins": joins,
        "evictions": evictions,
        "rejoins": rejoins,
    }


def _measure_shard_ingest(
    results_db: str,
    replica_ids: List[str],
    per_replica_batches: List[List[dict]],
    seed: int,
    config_hash: Optional[str] = None,
) -> dict:
    """Write each replica's batch telemetry through its own WAL-mode
    ``SqliteSink`` shard (real inserts, real fsync policy), then run the
    federation merge and read the sinks' own ``telemetry.ingest_lag_ms``
    gauges back out of the shard files. Returns the ingest block the
    headline reports."""
    from p2pmicrogrid_tpu.data.results import (
        merge_warehouse_shards,
        shard_db_path,
    )
    from p2pmicrogrid_tpu.telemetry import SqliteSink, Telemetry, run_manifest
    from p2pmicrogrid_tpu.telemetry.registry import run_stamp

    shard_paths: List[str] = []
    for rid, batches in zip(replica_ids, per_replica_batches):
        shard = shard_db_path(results_db, rid)
        shard_paths.append(shard)
        extra = {"serve_role": "scale-bench"}
        # Carry the served bundle's config_hash so the federated --fleet
        # view can join bench shards the same way it joins replica shards.
        if config_hash is not None:
            extra["config_hash"] = config_hash
        tel = Telemetry(
            run_id=f"scale-bench-{rid}-{run_stamp()}-{seed}",
            sinks=[SqliteSink(shard, shard_id=rid)],
            manifest=run_manifest(extra=extra),
        )
        for b in batches:
            tel.event("scale_batch", **b)
        tel.close()

    lags: List[float] = []
    for shard in shard_paths:
        con = sqlite3.connect(f"file:{shard}?mode=ro", uri=True)
        try:
            for (v,) in con.execute(
                "SELECT value FROM telemetry_points "
                "WHERE name = 'telemetry.ingest_lag_ms'"
            ):
                lags.append(float(v))
        finally:
            con.close()

    con = sqlite3.connect(results_db)
    try:
        merge_stats = merge_warehouse_shards(con, shard_paths)
    finally:
        con.close()
    lag_arr = np.array(lags if lags else [0.0])
    return {
        "shards": len(shard_paths),
        "batches_written": sum(len(b) for b in per_replica_batches),
        "ingest_lag_ms_p50": round(float(np.percentile(lag_arr, 50)), 3),
        "ingest_lag_ms_max": round(float(lag_arr.max()), 3),
        "merged_rows": {
            k: v for k, v in merge_stats.items() if k != "shards"
        },
    }


def serve_bench_scale(
    service_model: Optional[Dict[int, float]] = None,
    engine=None,
    population: Optional[Population] = None,
    n_households: int = 1_000_000,
    rate_hz: float = 100_000.0,
    duration_s: float = 15.0,
    replica_counts: Iterable[int] = (3, 10, 30),
    vnodes: int = 4096,
    max_batch: int = 64,
    max_wait_s: float = 0.002,
    max_slots: int = 256,
    results_db: Optional[str] = None,
    seed: int = 0,
    emit: Optional[Callable[[dict], None]] = None,
    extra_headline: Optional[dict] = None,
) -> List[dict]:
    """The million-household virtual-clock bench (see module docstring).

    Pass either a warmed ``engine`` (its per-bucket service times are
    measured) or an explicit ``service_model`` ``{bucket: seconds}``.
    ``results_db`` enables the real shard-ingest measurement for the
    headline replica count; without it ``ingest_lag_ms`` is reported as
    0.0 with ``ingest.measured = False``.

    The headline (LAST row, ``serve_bench_scale``) reports the LARGEST
    replica count's sustained rps/replica and p99; the scaling row
    reports hash-placement spread for every count — consistent hashing
    must spread the population within a few percent at each size.
    """
    if service_model is None:
        if engine is None:
            raise ValueError("pass an engine or an explicit service_model")
        max_batch = engine.max_batch
        service_model = measure_bucket_service_model(engine, seed=seed)
    pop = population or Population(
        PopulationConfig(n_households=n_households, seed=seed)
    )
    n_requests = int(rate_hz * duration_s)
    if n_requests < 1:
        raise ValueError(
            f"rate_hz x duration_s gives {n_requests} requests"
        )

    from p2pmicrogrid_tpu.serve.loadgen import (
        plan_open_loop,
        poisson_arrivals,
    )

    arrivals = poisson_arrivals(rate_hz, n_requests, seed=seed)
    idx = pop.sample(n_requests, seed=seed + 1)
    skew = pop.skew_summary(idx)

    rows: List[dict] = []

    def push(row: dict) -> None:
        rows.append(row)
        if emit:
            emit(row)

    replica_counts = sorted(set(int(r) for r in replica_counts))
    headline_r = replica_counts[-1]
    spread_by_count: Dict[int, float] = {}
    headline_block: Optional[dict] = None
    ingest_block = {"measured": False, "ingest_lag_ms_max": 0.0,
                    "ingest_lag_ms_p50": 0.0}
    spill_block: Optional[dict] = None

    for n_replicas in replica_counts:
        replica_ids = [f"replica-{r}" for r in range(n_replicas)]
        assign, _ring = _assign_replicas(pop, idx, replica_ids, vnodes)
        counts = np.bincount(assign, minlength=n_replicas)
        mean_load = counts.mean()
        spread = float(np.abs(counts - mean_load).max() / mean_load)
        spread_by_count[n_replicas] = round(spread, 4)

        latencies: List[np.ndarray] = []
        rps: List[float] = []
        per_replica_batches: List[List[dict]] = []
        for r in range(n_replicas):
            mask = assign == r
            rep_arrivals = arrivals[mask]
            if rep_arrivals.shape[0] == 0:
                per_replica_batches.append([])
                continue
            result = plan_open_loop(
                rep_arrivals,
                lambda i, j: service_model[
                    _bucket_for(j - i, max_batch)
                ],
                max_batch=max_batch,
                max_wait_s=max_wait_s,
                bucket_fn=lambda n: _bucket_for(n, max_batch),
            )
            latencies.append(result.latencies_s)
            rps.append(result.throughput_rps)
            per_replica_batches.append([
                {
                    "replica": r,
                    "batch": b,
                    "batch_size": result.batch_sizes[b],
                    "bucket": result.bucket_sizes[b],
                    "dispatch_s": round(result.dispatch_s[b], 6),
                    "service_ms": round(result.service_s[b] * 1e3, 3),
                }
                for b in range(len(result.batch_sizes))
            ])

        lat = np.concatenate(latencies) * 1e3
        offered_per_replica = rate_hz / n_replicas
        sustained = float(np.mean(rps))
        block = {
            "metric": "scale_replica_sweep",
            "value": round(sustained, 1),
            "unit": "requests/sec",
            "vs_baseline": round(sustained / offered_per_replica, 3),
            "replicas": n_replicas,
            "offered_rps_per_replica": round(offered_per_replica, 1),
            "rps_per_replica": round(sustained, 1),
            "saturated": bool(sustained < 0.95 * offered_per_replica),
            "p50_ms": round(float(np.percentile(lat, 50)), 3),
            "p95_ms": round(float(np.percentile(lat, 95)), 3),
            "p99_ms": round(float(np.percentile(lat, 99)), 3),
            "load_spread": spread_by_count[n_replicas],
            "vnodes": vnodes,
        }
        push(block)

        if n_replicas == headline_r:
            headline_block = block
            # Spill policy, measured on the most-loaded replica: the
            # worst-case working set the session ring must absorb.
            hot = int(np.argmax(counts))
            spill = _simulate_lru_spill(idx[assign == hot], max_slots)
            served = max(1, spill["requests"])
            spill_block = {
                "metric": "scale_spill",
                "value": round(spill["hits"] / served, 4),
                "unit": "fraction",
                "vs_baseline": 0.0,
                "replica": hot,
                "max_slots": max_slots,
                **spill,
                "hit_rate": round(spill["hits"] / served, 4),
                "eviction_rate": round(spill["evictions"] / served, 4),
                "rejoin_rate": round(spill["rejoins"] / served, 4),
            }
            if results_db:
                ingest_block = dict(
                    _measure_shard_ingest(
                        results_db, replica_ids, per_replica_batches,
                        seed,
                        config_hash=(extra_headline or {}).get("config_hash"),
                    ),
                    measured=True,
                )

    push({
        "metric": "scale_scaling",
        "value": max(spread_by_count.values()),
        "unit": "fraction",
        "vs_baseline": 0.0,
        "replica_counts": replica_counts,
        "load_spread_by_count": {
            str(k): v for k, v in spread_by_count.items()
        },
        "max_load_spread": max(spread_by_count.values()),
        "vnodes": vnodes,
    })
    if spill_block is not None:
        push(spill_block)

    headline = {
        "metric": "serve_bench_scale",
        "value": headline_block["rps_per_replica"],
        "unit": "requests/sec",
        "vs_baseline": round(
            headline_block["rps_per_replica"]
            / headline_block["offered_rps_per_replica"],
            3,
        ),
        "households": pop.n_households,
        "n_requests": n_requests,
        "rate_hz": rate_hz,
        "duration_s": duration_s,
        "replicas": headline_r,
        "rps_per_replica": headline_block["rps_per_replica"],
        "offered_rps_per_replica": headline_block[
            "offered_rps_per_replica"
        ],
        "saturated": headline_block["saturated"],
        "p50_ms": headline_block["p50_ms"],
        "p99_ms": headline_block["p99_ms"],
        "ingest_lag_ms": ingest_block["ingest_lag_ms_max"],
        "ingest": ingest_block,
        "load_spread": headline_block["load_spread"],
        "scaling": {
            "replica_counts": replica_counts,
            "load_spread_by_count": {
                str(k): v for k, v in spread_by_count.items()
            },
        },
        "population": {
            "n_households": pop.n_households,
            "seed": pop.config.seed,
            "zipf_s": pop.config.zipf_s,
            "churn": pop.config.churn,
            **skew,
        },
        "service_model_ms": {
            str(b): round(s * 1e3, 4)
            for b, s in sorted(service_model.items())
        },
        "max_batch": max_batch,
        "max_wait_s": max_wait_s,
        "vnodes": vnodes,
        "seed": seed,
    }
    if extra_headline:
        headline.update(extra_headline)
    push(headline)
    return rows
