"""Deterministic synthetic household population at million-id scale.

The fleet loadgen's default arrival mix — round-robin over a handful of
``house-%04d`` ids — cannot exercise any of the properties that matter
at scale: consistent-hash spread over a large key space, session-ring
eviction under a working set far above ``max_slots``, pin-map growth.
This module is the arrival source that can:

* **Stable ids.** Household ``i`` is always ``house-{i:07d}`` for the
  same config — ids never depend on sampling order, so two benches (or a
  bench and a later federated telemetry query) agree on identity.
* **Zipf-skewed popularity.** A seeded permutation assigns each id a
  popularity rank; request probability falls off as ``rank^-s``. The
  default ``s`` is deliberately MILD (0.6): utility telemetry is
  per-meter polling, not social-media fan-in — and the bench's ring-
  spread claim is about hash placement, which a pathological single-id
  hotspot (s >= 1) would drown in arrival skew instead.
* **Rate classes.** Each id is assigned residential / commercial /
  industrial (seeded, stable) and its weight scaled by the class's
  request-rate multiplier — commercial meters poll a few times as often
  as residential, industrial far more, matching how P2P trading fleets
  meter by tariff class.
* **Churn.** A configurable fraction of requests come from a household
  drawn UNIFORMLY over the whole id space — the long tail of cold
  joiners that defeats any cache sized to the hot set and drives the
  session ring's LRU spill policy.

Everything is host-side numpy over one ``default_rng(seed)`` stream:
same config, same request sequence, bit-for-bit. Sampling is O(log N)
per request (vectorized ``searchsorted`` over a precomputed weight CDF)
after a one-time O(N) setup — the id space is never scanned per draw.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

# (population share, request-rate multiplier) per tariff class. Shares
# sum to 1; multipliers are relative to residential polling cadence.
RATE_CLASSES: Dict[str, Tuple[float, float]] = {
    "residential": (0.85, 1.0),
    "commercial": (0.12, 4.0),
    "industrial": (0.03, 12.0),
}


@dataclass(frozen=True)
class PopulationConfig:
    """Generating parameters — the population is a pure function of
    these (plus nothing else), which is what makes ids stable."""

    n_households: int = 1_000_000
    seed: int = 0
    zipf_s: float = 0.6           # popularity exponent (0 = uniform)
    churn: float = 0.02           # fraction of requests from uniform draws
    rate_classes: Dict[str, Tuple[float, float]] = field(
        default_factory=lambda: dict(RATE_CLASSES)
    )

    def __post_init__(self):
        if self.n_households < 1:
            raise ValueError(
                f"n_households must be >= 1, got {self.n_households}"
            )
        if self.zipf_s < 0:
            raise ValueError(f"zipf_s must be >= 0, got {self.zipf_s}")
        if not 0.0 <= self.churn <= 1.0:
            raise ValueError(f"churn must be in [0, 1], got {self.churn}")
        shares = sum(s for s, _ in self.rate_classes.values())
        if abs(shares - 1.0) > 1e-9:
            raise ValueError(
                f"rate-class shares must sum to 1, got {shares}"
            )


class Population:
    """Sampled household arrival source over a fixed id space.

    One-time setup cost is O(N) time and ~3 int8/float64 arrays of
    length N (~17 MB at 1M); per-request sampling never touches the id
    space again. ``sample``/``ids`` take their own seed so one
    population serves many independent arrival schedules.
    """

    def __init__(self, config: Optional[PopulationConfig] = None, **kw):
        self.config = config or PopulationConfig(**kw)
        cfg = self.config
        n = cfg.n_households
        rng = np.random.default_rng(cfg.seed)
        # Popularity: perm[i] is id i's 0-based popularity rank. The
        # permutation (not sorted ranks) decorrelates popularity from id
        # order — hot households land all over the hash ring.
        perm = rng.permutation(n)
        weights = (perm + 1.0) ** -cfg.zipf_s
        # Rate class per id: seeded categorical by share, then the class
        # multiplier scales the id's request weight.
        names = list(cfg.rate_classes)
        shares = np.array([cfg.rate_classes[c][0] for c in names])
        mults = np.array([cfg.rate_classes[c][1] for c in names])
        self.class_index = rng.choice(
            len(names), size=n, p=shares / shares.sum()
        ).astype(np.int8)
        self.class_names = names
        weights *= mults[self.class_index]
        cdf = np.cumsum(weights)
        self._cdf = cdf / cdf[-1]

    @property
    def n_households(self) -> int:
        return self.config.n_households

    @staticmethod
    def household_id(index: int) -> str:
        """Stable id for household ``index`` — zero-padded so the id
        space sorts lexicographically and never collides with the small
        benches' ``house-%04d`` ids at >= 10k."""
        return f"house-{index:07d}"

    def rate_class(self, index: int) -> str:
        return self.class_names[self.class_index[index]]

    def sample(self, n_requests: int, seed: int = 0) -> np.ndarray:
        """Household INDEX per request (int64 [n_requests]): Zipf x
        rate-class weighted draws, with a ``churn`` fraction replaced by
        uniform draws over the whole id space (cold joiners)."""
        cfg = self.config
        # Seed sequence keyed by (population seed, schedule seed): two
        # schedules over one population are independent streams, and the
        # same schedule seed over two populations differs too.
        rng = np.random.default_rng((cfg.seed, seed))
        idx = np.searchsorted(
            self._cdf, rng.random(n_requests), side="right"
        ).astype(np.int64)
        np.minimum(idx, cfg.n_households - 1, out=idx)
        if cfg.churn > 0:
            cold = rng.random(n_requests) < cfg.churn
            idx[cold] = rng.integers(
                0, cfg.n_households, size=int(cold.sum())
            )
        return idx

    def ids(self, indices: np.ndarray) -> List[str]:
        """Id strings for an index array — the ``household_ids`` form
        ``run_fleet_loadgen`` takes."""
        return [f"house-{int(i):07d}" for i in indices]

    def arrival_ids(self, n_requests: int, seed: int = 0) -> List[str]:
        """Convenience: ``ids(sample(n))`` — one id string per request."""
        return self.ids(self.sample(n_requests, seed=seed))

    def skew_summary(self, indices: np.ndarray) -> dict:
        """Concentration stats of a sampled request sequence — recorded
        next to the bench headline so the generating mix is auditable:
        unique households touched, share of traffic on the hottest id
        and hottest 1% of ids."""
        counts = np.bincount(indices, minlength=self.n_households)
        total = int(counts.sum())
        if total == 0:
            return {"unique": 0, "top1_share": 0.0, "top1pct_share": 0.0}
        hot = np.sort(counts)[::-1]
        k = max(1, self.n_households // 100)
        return {
            "unique": int((counts > 0).sum()),
            "top1_share": round(float(hot[0]) / total, 6),
            "top1pct_share": round(float(hot[:k].sum()) / total, 6),
        }
