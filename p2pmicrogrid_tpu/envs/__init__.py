"""Community simulator: the reference's runtime layer as pure JAX.

Replaces the object-per-agent eager loop of microgrid/community.py and the
process-global ``Environment`` singleton (environment.py) with explicit state
PyTrees and a single ``lax.scan``-able step function.
"""

from p2pmicrogrid_tpu.envs.community import (
    AgentRatings,
    EpisodeArrays,
    PhysState,
    Policy,
    SlotOutputs,
    SlotTransition,
    build_episode_arrays,
    draw_rating_scales,
    init_physical,
    make_ratings,
    run_episode,
    rule_baseline_episode,
    semi_intelligent_baseline_episode,
    slot_dynamics,
    with_pv_drop,
)

__all__ = [
    "semi_intelligent_baseline_episode",
    "with_pv_drop",
    "AgentRatings",
    "EpisodeArrays",
    "PhysState",
    "Policy",
    "SlotOutputs",
    "SlotTransition",
    "build_episode_arrays",
    "draw_rating_scales",
    "init_physical",
    "make_ratings",
    "run_episode",
    "rule_baseline_episode",
    "slot_dynamics",
]
