"""Multi-community simulation with inter-community trading.

BASELINE.md config 5: several communities (e.g. 8 x 128 agents) run in one
device program — communities ride the same leading batch axis the
shared-parameter trainer uses for scenarios — and additionally trade their
*residual* grid power with each other at the P2P midpoint price.

The reference has no multi-community capability at all (SURVEY.md section 2);
the design here reuses the community-level market math one level up: after
intra-community clearing, each community's residual ``r_c = sum_a p_grid``
is offered equally to the other communities, the same sign-opposition
pairwise matching (ops/market.py:clear_market) runs on the [C, C] proposal
matrix, and the matched share of each community's residual settles at the
trade price instead of the grid tariff. Settlement is conservative: the
matched power ``f_c * r_c`` is re-priced pro-rata across only the agents
whose grid power has the residual's sign (they are the ones physically
backing the inter-community exchange), so the energy re-priced at the trade
price equals the matched energy exactly; counter-sign agents settle at the
plain tariff.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from p2pmicrogrid_tpu.config import ExperimentConfig
from p2pmicrogrid_tpu.envs.community import (
    AgentRatings,
    EpisodeArrays,
    SlotOutputs,
    build_episode_arrays,
    draw_rating_scales,
    init_physical,
    slot_dynamics_batched,
)
from p2pmicrogrid_tpu.ops.market import clear_market
from p2pmicrogrid_tpu.parallel.scenarios import (
    make_shared_episode_fn,
    train_scenarios_shared,
)


def inter_community_traded_fraction(p_grid: jnp.ndarray) -> jnp.ndarray:
    """Fraction of each community's grid residual matched with other
    communities.

    p_grid: [C, A] per-agent grid-bound power after intra-community clearing.
    Returns f [C] in [0, 1]: each community offers its residual equally to
    the other C-1 communities; sign-opposition matching clears the [C, C]
    proposals exactly like the intra-community market (community.py:45-54,
    one level up).
    """
    r = jnp.sum(p_grid, axis=-1)  # [C]
    c = r.shape[0]
    if c < 2:
        return jnp.zeros_like(r)  # a lone community has no one to trade with
    eye = jnp.eye(c, dtype=p_grid.dtype)
    proposals = r[:, None] * (1.0 - eye) / (c - 1)
    _, matched = clear_market(proposals)  # matched [C], same sign as r
    safe_r = jnp.where(jnp.abs(r) > 1e-6, r, 1.0)
    f = jnp.where(jnp.abs(r) > 1e-6, matched / safe_r, 0.0)
    return jnp.clip(f, 0.0, 1.0)


def make_inter_community_settlement(cfg: ExperimentConfig) -> Callable:
    """Settlement hook for ``slot_dynamics_batched`` where the leading axis is
    communities: intra-community P2P settles at the trade price as usual, and
    the inter-community-matched share of grid power is re-priced from the
    tariff to the trade price, spread only over the agents that back the
    residual so re-priced energy equals matched energy."""
    slot_hours = cfg.sim.slot_hours

    def settle(p_grid, p_p2p, buy, inj, trade):
        # p_grid/p_p2p [C, A]; buy/inj/trade [C] (identical entries — one
        # tariff; kept per-community for shape uniformity).
        f = inter_community_traded_fraction(p_grid)  # [C]
        r = jnp.sum(p_grid, axis=-1)                 # [C] residual
        matched = f * r                              # [C] power re-priced
        # Only agents whose grid power carries the residual's sign back the
        # inter-community exchange; spreading the matched power over them
        # pro-rata keeps Σ re-priced power == matched power (conservation).
        same_sign = jnp.sign(p_grid) == jnp.sign(r)[:, None]  # [C, A]
        backing = jnp.sum(jnp.where(same_sign, p_grid, 0.0), axis=-1)  # [C]
        safe_b = jnp.where(jnp.abs(backing) > 1e-6, backing, 1.0)
        share = jnp.where(jnp.abs(backing) > 1e-6, matched / safe_b, 0.0)
        # |backing| >= |r| >= |matched|, so share stays in [0, 1].
        frac = jnp.where(same_sign, share[:, None], 0.0)      # [C, A]
        tariff = jnp.where(p_grid >= 0.0, buy[:, None], inj[:, None])
        grid_price = (1.0 - frac) * tariff + frac * trade[:, None]
        cost = (p_grid * grid_price + p_p2p * trade[:, None]) * slot_hours * 1e-3
        return cost

    return settle


def make_multi_community_episode_fn(
    cfg: ExperimentConfig,
    policy,
    arrays_c: EpisodeArrays,
    ratings: AgentRatings,
    donate: bool = False,
) -> Callable:
    """Jitted episode over C communities (leading axis of ``arrays_c``) with
    shared policy parameters and inter-community trading. ``donate``: see
    ``make_shared_episode_fn`` (the carry updates in place; a donated carry
    is consumed by the call)."""
    return make_shared_episode_fn(
        cfg,
        policy,
        arrays_c,
        ratings,
        settlement_hook=make_inter_community_settlement(cfg),
        donate=donate,
    )


def train_multi_community(
    cfg: ExperimentConfig,
    policy,
    pol_state,
    arrays_c: EpisodeArrays,
    ratings: AgentRatings,
    key: jax.Array,
    n_episodes: int,
    replay_s=None,
    episode0: int = 0,
    episode_cb: Optional[Callable] = None,
    pipeline: bool = True,
    telemetry=None,
    carry_sync: Optional[Callable] = None,
) -> Tuple[object, object, np.ndarray, np.ndarray, float]:
    """Train C communities with inter-community trading (shared parameters).

    Same contract as ``train_scenarios_shared`` (returns pol_state,
    scen_state, rewards, losses, seconds) — communities are the leading
    axis of ``arrays_c`` (build with ``stack_scenario_arrays`` over one trace
    draw per community). ``pipeline``/``carry_sync``: the async depth-2
    driver and its carry-read sync predicate (see
    ``train_scenarios_shared``); the episode program is built donation-clean
    when pipelining, so ``episode_cb`` callbacks that READ the carry need
    ``carry_sync`` episodes (the ``multi`` CLI wires its checkpoint
    cadence).
    """
    episode_fn = make_multi_community_episode_fn(
        cfg, policy, arrays_c, ratings, donate=pipeline
    )
    return train_scenarios_shared(
        cfg,
        policy,
        pol_state,
        arrays_c,
        ratings,
        key,
        n_episodes,
        replay_s=replay_s,
        episode_fn=episode_fn,
        episode0=episode0,
        episode_cb=episode_cb,
        pipeline=pipeline,
        donate=pipeline,
        telemetry=telemetry,
        carry_sync=carry_sync,
    )


def evaluate_multi_community(
    cfg: ExperimentConfig,
    policy,
    pol_state,
    traces,
    ratings: AgentRatings,
    key: jax.Array,
    redraw_profile_scales: bool = True,
    rng: Optional[np.random.Generator] = None,
) -> Tuple[np.ndarray, SlotOutputs, EpisodeArrays]:
    """Greedy per-day evaluation of C communities with inter-community
    trading — the reference's ``load_and_run`` (community.py:364-412) lifted
    to BASELINE config 5.

    ``pol_state`` is the shared learner a ``multi`` training run checkpoints
    (TabularState / DQNState / DDPGParams — see ``init_shared_state``). Each
    (day, community) redraws its per-agent load/PV profile scales
    (community.py:386-391; the shared ``max_in``/``max_out`` ratings stay the
    training ones), which differentiates the communities so residuals
    actually trade. All D x C episodes run in ONE device call.

    Returns (days, outputs, day_arrays): SlotOutputs leaves are
    [D, T, C, ...]; day_arrays leaves are [D, C, T, ...].
    """
    C = cfg.sim.n_scenarios
    by_day = traces.split_by_day()
    days = np.array(sorted(by_day), dtype=np.int32)
    gen = rng if rng is not None else np.random.default_rng(0)

    day_arrays = []
    for d in days:
        day_traces = by_day[int(d)]
        per_community = []
        for _ in range(C):
            r = ratings
            if redraw_profile_scales:
                load_r, pv_r = draw_rating_scales(cfg, gen)
                r = AgentRatings(
                    load_rating_w=(load_r * 1e3).astype(np.float32),
                    pv_rating_w=(pv_r * 1e3).astype(np.float32),
                    max_in=ratings.max_in,
                    max_out=ratings.max_out,
                )
            per_community.append(build_episode_arrays(cfg, day_traces, r))
        day_arrays.append(
            jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_community)
        )
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *day_arrays)

    ratings_j = AgentRatings(*(jnp.asarray(a) for a in ratings))
    settle = make_inter_community_settlement(cfg)

    act_fn = None
    if cfg.train.implementation == "ddpg":
        from p2pmicrogrid_tpu.models.ddpg import ddpg_shared_act

        def act_fn(params, obs_s, prev_frac_s, round_key, ex):
            # Greedy: deterministic actor, OU state untouched.
            frac, q, _ = ddpg_shared_act(
                cfg.ddpg, params, obs_s, jnp.zeros(obs_s.shape[:2]),
                round_key, explore=False,
            )
            return frac, frac, q, ex

    @jax.jit
    def eval_all(pol_state, stacked, keys):
        def one_day(arrays_c, k):
            k_phys, k_scan = jax.random.split(k)
            phys_c = jax.vmap(lambda kk: init_physical(cfg, kk))(
                jax.random.split(k_phys, C)
            )

            def slot(carry, xs_t):
                phys_s, kk = carry
                kk, k_act = jax.random.split(kk)
                phys_s, _, outputs_s, _, _ = slot_dynamics_batched(
                    cfg, policy, pol_state, phys_s, xs_t, k_act, ratings_j,
                    explore=False, settlement_hook=settle, act_fn=act_fn,
                )
                return (phys_s, kk), outputs_s

            xs = jax.tree_util.tree_map(
                lambda x: jnp.swapaxes(x, 0, 1), arrays_c
            )
            xs = (
                xs.time,
                xs.t_out,
                xs.load_w,
                xs.pv_w,
                xs.next_time,
                xs.next_load_w,
                xs.next_pv_w,
            )
            (_, _), outputs = jax.lax.scan(
                slot, (phys_c, k_scan), xs, unroll=cfg.sim.slot_unroll
            )
            return outputs  # leaves [T, C, ...]

        return jax.vmap(one_day)(stacked, keys)

    keys = jax.random.split(key, len(days))
    # stacked rides as an argument — a closure capture would constant-fold
    # the whole D x C episode-array stack into the compiled program.
    outputs = eval_all(pol_state, stacked, keys)
    return days, outputs, stacked
