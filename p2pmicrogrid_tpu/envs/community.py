"""The P2P community simulator as one pure, scannable step function.

TPU-native re-design of the reference runtime (microgrid/community.py:33-195 +
environment.py + agent.py's per-agent orchestration): all per-agent state is a
struct-of-arrays PyTree with a leading agent axis, the multi-round price
negotiation is an inner ``lax.scan`` of *vmapped* agent decisions, and an
episode is an outer ``lax.scan`` over time slots. Nothing here touches the
host: one jitted call runs a full episode including per-slot learning.

Reference semantics preserved exactly (SURVEY.md section 7):

* Within a negotiation round every agent sees the *previous* round's p2p
  matrix (community.py:75-86) — agents are embarrassingly parallel.
* The diagonal of the proposal matrix is zeroed at the *start* of each round
  only; a final-round diagonal residue (from divide_power's equal split)
  settles with the grid (community.py:76,91).
* Reward = -(cost + 10 * comfort penalty), penalty offset +1, evaluated at the
  *pre-step* indoor temperature (agent.py:225-232).
* The next-state observation reuses the stale (pre-step) indoor temperature
  and a zero p2p signal (agent.py:293-296, community.py:161) — toggleable via
  ``SimConfig.stale_next_temp``.
* Assets advance after learning (community.py:158-170).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from p2pmicrogrid_tpu.config import ExperimentConfig
from p2pmicrogrid_tpu.data.traces import TraceSet, agent_profiles, next_slot
from p2pmicrogrid_tpu.ops.battery import battery_rule_update
from p2pmicrogrid_tpu.ops.market import (
    clear_market,
    compute_costs,
    divide_power,
    zero_diagonal,
)
from p2pmicrogrid_tpu.ops.obs import make_observation
from p2pmicrogrid_tpu.ops.tariff import grid_prices, p2p_price as p2p_price_fn
from p2pmicrogrid_tpu.ops.thermal import (
    comfort_penalty,
    normalized_temperature,
    thermal_step,
)


class Policy(NamedTuple):
    """A policy as three pure functions (closing over their config).

    act(pol_state, obs [A,4], prev_frac [A], key, explore) ->
        (hp_frac [A], aux [A], q [A], pol_state)
        ``aux`` is whatever ``learn`` needs to identify the action (the
        discrete index for tabular/DQN, the fraction itself for DDPG).
    learn(pol_state, obs, aux, reward, next_obs, key) -> (pol_state, loss [A])
    decay(pol_state) -> pol_state   (exploration schedule, community.py:283-285)
    """

    act: Callable
    learn: Callable
    decay: Callable


class AgentRatings(NamedTuple):
    """Static per-agent ratings, [A] each (community.py:210-228)."""

    load_rating_w: np.ndarray
    pv_rating_w: np.ndarray
    max_in: np.ndarray
    max_out: np.ndarray


class EpisodeArrays(NamedTuple):
    """Time-major per-slot inputs for one episode, precomputed on host.

    The ``next_*`` fields implement the reference's np.roll (state, next_state)
    pairing (dataset.py:98-103): the last slot wraps to the first.
    """

    time: jnp.ndarray       # [T] normalized slot-of-day
    t_out: jnp.ndarray      # [T] outdoor temperature [°C]
    load_w: jnp.ndarray     # [T, A] household load [W]
    pv_w: jnp.ndarray       # [T, A] PV production [W]
    next_time: jnp.ndarray  # [T]
    next_load_w: jnp.ndarray
    next_pv_w: jnp.ndarray

    @property
    def n_slots(self) -> int:
        return self.time.shape[0]


class PhysState(NamedTuple):
    """Physical asset state, [A] each."""

    t_in: jnp.ndarray    # indoor air temperature [°C]
    t_bm: jnp.ndarray    # building-mass temperature [°C]
    soc: jnp.ndarray     # battery state of charge in [0, 1]
    hp_frac: jnp.ndarray  # heat-pump power fraction in [0, 1]


class SlotOutputs(NamedTuple):
    """Per-slot trace recorded by the episode scan (mirrors what the reference
    logs to SQLite: community.py:341-361, database.py:226-312)."""

    cost: jnp.ndarray       # [A] €
    reward: jnp.ndarray     # [A]
    loss: jnp.ndarray       # [A]
    p_grid: jnp.ndarray     # [A] W
    p_p2p: jnp.ndarray      # [A] W
    buy_price: jnp.ndarray  # [] €/kWh
    injection_price: jnp.ndarray
    trade_price: jnp.ndarray
    t_in: jnp.ndarray       # [A] pre-step indoor temperature
    hp_power_w: jnp.ndarray  # [A] final heat-pump electrical power
    decisions: jnp.ndarray  # [rounds+1, A] per-round hp power [W] (community.py:88-89)
    q: jnp.ndarray          # [A] actor value estimate


def draw_rating_scales(
    cfg: ExperimentConfig, rng: np.random.Generator
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-agent load/PV scales in kW: ~N(0.7,0.2)/N(4,0.2), or the means when
    homogeneous (community.py:210-211; redrawn at eval, community.py:386-391)."""
    p = cfg.population
    n = cfg.sim.n_agents
    if cfg.sim.homogeneous:
        return np.full(n, p.load_rating_mean), np.full(n, p.pv_rating_mean)
    return (
        rng.normal(p.load_rating_mean, p.load_rating_std, n),
        rng.normal(p.pv_rating_mean, p.pv_rating_std, n),
    )


def make_ratings(cfg: ExperimentConfig, rng: np.random.Generator) -> AgentRatings:
    """Draw heterogeneous load/PV ratings (community.py:210-228).

    Homogeneous communities pin every agent to the mean (community.py:210-211).
    ``max_out`` uses the multiplicative form — the reference's
    ``-(max_power + safety*1e3)`` (community.py:228) is a typo not copied
    (SURVEY.md section 7).
    """
    p = cfg.population
    load_r, pv_r = draw_rating_scales(cfg, rng)
    max_power = np.maximum(load_r, pv_r)
    return AgentRatings(
        load_rating_w=(load_r * 1e3).astype(np.float32),
        pv_rating_w=(pv_r * 1e3).astype(np.float32),
        max_in=(max_power * p.safety * 1e3).astype(np.float32),
        max_out=(-max_power * p.safety * 1e3).astype(np.float32),
    )


def build_episode_arrays(
    cfg: ExperimentConfig, traces: TraceSet, ratings: AgentRatings
) -> EpisodeArrays:
    """Denormalize per-agent profiles and precompute the next-slot pairing."""
    load_w, pv_w = agent_profiles(
        traces,
        cfg.sim.n_agents,
        ratings.load_rating_w,
        ratings.pv_rating_w,
        homogeneous=cfg.sim.homogeneous,
    )
    return EpisodeArrays(
        time=jnp.asarray(traces.time),
        t_out=jnp.asarray(traces.t_out),
        load_w=jnp.asarray(load_w),
        pv_w=jnp.asarray(pv_w),
        next_time=jnp.asarray(next_slot(traces.time)),
        next_load_w=jnp.asarray(next_slot(load_w)),
        next_pv_w=jnp.asarray(next_slot(pv_w)),
    )


def with_pv_drop(
    arrays: EpisodeArrays,
    agent: int,
    start_slot: int = 0,
    factor: float = 0.0,
) -> EpisodeArrays:
    """Fault injection: scale one agent's PV production from ``start_slot``
    onward (the reference's artificial "PV drop" scenario, analyzed at
    data_analysis.py:1099-1211 under settings ``2-agent-1-pv-drop-{com,no-com}``
    — its generating code was never shipped; here it is a first-class
    transform)."""
    n_agents = arrays.pv_w.shape[1]
    if not 0 <= agent < n_agents:
        # JAX scatter silently drops out-of-bounds indices; fail loudly here.
        raise ValueError(f"agent {agent} out of range [0, {n_agents})")
    mask = (jnp.arange(arrays.time.shape[0]) >= start_slot).astype(jnp.float32)
    scale = 1.0 - (1.0 - factor) * mask  # 1 before the drop, `factor` after
    pv_w = arrays.pv_w.at[:, agent].multiply(scale)
    # next_pv_w[t] mirrors pv_w[t+1] (np.roll pairing), so its scale is the
    # rolled one — the slot-(start-1) transition must already see the fault.
    next_pv_w = arrays.next_pv_w.at[:, agent].multiply(jnp.roll(scale, -1))
    return arrays._replace(pv_w=pv_w, next_pv_w=next_pv_w)


def init_physical(cfg: ExperimentConfig, key: jax.Array) -> PhysState:
    """Initial temperatures: setpoint exactly (homogeneous) or
    N(setpoint, 0.3) per agent (heating.py:101-104); battery at init SoC."""
    n = cfg.sim.n_agents
    th = cfg.thermal
    if cfg.sim.homogeneous:
        t_in = jnp.full((n,), th.setpoint, dtype=jnp.float32)
        t_bm = jnp.full((n,), th.setpoint, dtype=jnp.float32)
    else:
        k1, k2 = jax.random.split(key)
        t_in = th.setpoint + th.init_temp_std * jax.random.normal(k1, (n,))
        t_bm = th.setpoint + th.init_temp_std * jax.random.normal(k2, (n,))
    return PhysState(
        t_in=t_in,
        t_bm=t_bm,
        soc=jnp.full((n,), cfg.battery.init_soc, dtype=jnp.float32),
        hp_frac=jnp.zeros((n,), dtype=jnp.float32),  # HeatPump(power=0), community.py:226
    )


def _negotiate(
    cfg: ExperimentConfig,
    policy: Policy,
    pol_state,
    phys: PhysState,
    ratings: AgentRatings,
    time_norm: jnp.ndarray,
    balance_w: jnp.ndarray,
    key: jax.Array,
    explore: bool,
):
    """The multi-round negotiation loop (community.py:75-89).

    Every round: zero the diagonal, let all agents (vmapped) observe the
    previous round's proposals and re-decide, rebuild the proposal matrix.
    Returns the final matrix plus the last round's (obs, aux) for learning.
    """
    n = cfg.sim.n_agents
    th = cfg.thermal
    norm_balance = balance_w / ratings.max_in

    def round_body(carry, round_key):
        p2p, hp_frac, pol_state = carry
        p2p = zero_diagonal(p2p)

        # powers seen by agent i = -p2p[:, i]  (community.py:81)
        powers = -jnp.swapaxes(p2p, -1, -2)
        p2p_mean = jnp.mean(powers, axis=-1) / ratings.max_in  # agent.py:203

        obs = make_observation(
            time_norm, normalized_temperature(th, phys.t_in), norm_balance, p2p_mean
        )
        hp_frac, aux, q, pol_state = policy.act(
            pol_state, obs, hp_frac, round_key, explore
        )

        hp_power = hp_frac * th.hp_max_power
        p_out = divide_power(balance_w + hp_power, powers)  # [A, A], row i = agent i
        return (p_out, hp_frac, pol_state), (obs, aux, q, hp_power)

    keys = jax.random.split(key, cfg.sim.rounds + 1)
    (p2p, hp_frac, pol_state), (obs_r, aux_r, q_r, hp_power_r) = jax.lax.scan(
        round_body,
        (jnp.zeros((n, n)), phys.hp_frac, pol_state),
        keys,
        unroll=cfg.sim.rounds + 1,  # <= 3 rounds: always cheaper unrolled
    )
    # Learning uses the LAST round's observation/action (the reference
    # overwrites _current_state/_last_action every round, agent.py:200-213).
    return p2p, hp_frac, pol_state, obs_r[-1], aux_r[-1], q_r[-1], hp_power_r


class SlotTransition(NamedTuple):
    """The learning transition a slot produces (agent.py:293-296): last-round
    observation/action, reward, and the next-slot observation."""

    obs: jnp.ndarray       # [A, 4]
    aux: jnp.ndarray       # [A] action identifier (index or fraction)
    reward: jnp.ndarray    # [A]
    next_obs: jnp.ndarray  # [A, 4]


def slot_dynamics(
    cfg: ExperimentConfig,
    policy: Policy,
    pol_state,
    phys: PhysState,
    xs,
    key: jax.Array,
    ratings: AgentRatings,
    explore: bool,
):
    """Everything in a slot except learning: negotiate -> clear -> settle ->
    reward -> step assets (community.py:149-157,170).

    Split out from ``community_slot`` so scenario-sharded training can vmap
    the dynamics while applying a single *shared* parameter update across
    scenarios (parallel/scenarios.py).

    Returns (phys', pol_state', outputs, transition).
    """
    time_norm, t_out, load_w, pv_w, next_time, next_load_w, next_pv_w = xs

    buy, inj = grid_prices(cfg.tariff, time_norm)
    trade = p2p_price_fn(buy, inj)

    balance_w = load_w - pv_w
    soc = phys.soc
    if cfg.battery.enabled:
        # Modelled-but-dormant battery (storage.py, agent.py:138-153) as an
        # opt-in: greedily absorb/cover the balance before trading.
        soc, balance_w = battery_rule_update(
            cfg.battery, soc, balance_w, cfg.sim.dt_seconds
        )

    if cfg.sim.trading:
        p2p, hp_frac, pol_state, obs, aux, q, hp_power_rounds = _negotiate(
            cfg, policy, pol_state, phys, ratings, time_norm, balance_w, key,
            explore=explore,
        )
        p_grid, p_p2p = clear_market(p2p)
    else:
        # No-communication community (the reference's "no-com" settings):
        # a single decision pass with a zero p2p signal, all power settles
        # with the grid.
        obs = make_observation(
            time_norm,
            normalized_temperature(cfg.thermal, phys.t_in),
            balance_w / ratings.max_in,
            jnp.zeros_like(balance_w),
        )
        hp_frac, aux, q, pol_state = policy.act(
            pol_state, obs, phys.hp_frac, key, explore
        )
        p_grid = balance_w + hp_frac * cfg.thermal.hp_max_power
        p_p2p = jnp.zeros_like(p_grid)
        hp_power_rounds = (hp_frac * cfg.thermal.hp_max_power)[None, :]
    cost = compute_costs(p_grid, p_p2p, buy, inj, trade, cfg.sim.slot_hours)

    # Reward at pre-step indoor temperature (agent.py:225-232).
    penalty = comfort_penalty(cfg.thermal, phys.t_in)
    reward = -(cost + 10.0 * penalty)

    # Advance thermal state with the final round's heat-pump power and the
    # current slot's outdoor temperature (heating.py:126-143).
    hp_power = hp_frac * cfg.thermal.hp_max_power
    t_in_pre = phys.t_in
    t_in_new, t_bm_new = thermal_step(
        cfg.thermal, cfg.sim.dt_seconds, t_out, phys.t_in, phys.t_bm, hp_power
    )

    next_temp = phys.t_in if cfg.sim.stale_next_temp else t_in_new
    next_balance = (next_load_w - next_pv_w) / ratings.max_in
    next_obs = make_observation(
        next_time,
        normalized_temperature(cfg.thermal, next_temp),
        next_balance,
        jnp.zeros_like(next_balance),  # zero p2p signal (community.py:161)
    )

    phys = PhysState(t_in=t_in_new, t_bm=t_bm_new, soc=soc, hp_frac=hp_frac)
    outputs = SlotOutputs(
        cost=cost,
        reward=reward,
        loss=jnp.zeros_like(reward),
        p_grid=p_grid,
        p_p2p=p_p2p,
        buy_price=buy,
        injection_price=inj,
        trade_price=trade,
        t_in=t_in_pre,
        hp_power_w=hp_power,
        decisions=hp_power_rounds,
        q=q,
    )
    transition = SlotTransition(obs=obs, aux=aux, reward=reward, next_obs=next_obs)
    return phys, pol_state, outputs, transition


def resolve_use_pallas(cfg: ExperimentConfig) -> bool:
    """Resolve ``SimConfig.use_pallas``'s None auto-default.

    Auto: the fused kernels win on TPU (+39% at A=1000, measured) but would
    run in the slow interpreter on other backends. A bfloat16 market-matrix
    request only takes effect on the Pallas path (the jnp fallback always
    carries float32 matrices), so that combination warns instead of silently
    delivering no HBM saving.
    """
    use_pallas = cfg.sim.use_pallas
    if use_pallas is None:
        import os

        # P2P_DISABLE_PALLAS pins the auto choice off. The benchmark suite's
        # host-CPU retry needs it: ``jax.default_device(cpu)`` places arrays
        # on the host but ``jax.default_backend()`` still reports "tpu", so
        # without the override the retry would compile Mosaic TPU kernels for
        # a CPU-placed program and fail again.
        if os.environ.get("P2P_DISABLE_PALLAS", "") not in ("", "0"):
            use_pallas = False
        else:
            use_pallas = jax.default_backend() == "tpu"
    if (
        cfg.sim.market_dtype == "bfloat16"
        and not use_pallas
        # Raw field, not resolve_market_impl (which calls back here):
        # "auto" never resolves to factored when use_pallas is False, so
        # only an EXPLICIT factored choice makes bf16 effective off-TPU.
        and cfg.sim.market_impl != "factored"
    ):
        # Since round 5 the factored path honors market_dtype on ANY
        # backend (the fused min pass computes in bf16 with f32
        # accumulation), so the inert-setting warning only applies to the
        # jnp MATRIX path, which stores f32 matrices regardless.
        import warnings

        warnings.warn(
            "market_dtype='bfloat16' has no effect: the jnp (non-Pallas) "
            "MATRIX market path stores float32 matrices. It applies when "
            "use_pallas resolves True (TPU backend, or use_pallas=True) "
            "or with market_impl='factored'.",
            stacklevel=2,
        )
    return use_pallas


def resolve_use_fused(cfg: ExperimentConfig) -> bool:
    """Resolve ``SimConfig.fused_slot``'s None auto-default.

    The fused slot megakernel (ops/pallas_slot.py) runs the whole per-slot
    env — obs build, tabular/DQN policy act, market clearing, battery +
    thermal integration — as one Pallas kernel with VMEM-resident carries.
    Auto (None) resolves to False: the unfused chain is the committed-seed
    reference everywhere, and the megakernel's TPU capture is still
    measurement debt (ROADMAP), so fusion is an explicit opt-in
    (``fused_slot=True``, or the ``fused=`` flag on ``run_episode`` /
    ``make_shared_episode_fn`` / the scenario trainers). Requesting it for
    an unsupported configuration fails loudly here rather than at trace
    time."""
    f = cfg.sim.fused_slot
    if f is None or not f:
        return False
    if cfg.train.implementation not in ("tabular", "dqn"):
        raise ValueError(
            "fused_slot=True supports tabular/dqn policies only (ddpg "
            f"advances OU state inside act), got "
            f"{cfg.train.implementation!r}"
        )
    return True


# Smallest community size at which the auto market dtype compresses to
# bfloat16: below it the [S, A, A] stream is not the traffic that matters
# and f32 keeps bit-compat with the jnp reference path.
MARKET_BF16_MIN_AGENTS = 256


def resolve_market_dtype(cfg: ExperimentConfig) -> str:
    """Resolve ``SimConfig.market_dtype``'s "auto" default.

    bfloat16 storage for the negotiation matrices is measured ~f32-accurate
    (tests/test_pallas.py: episode rewards within 2%) and halves the
    dominant HBM stream, but only exists on the fused-Pallas path — so auto
    resolves to bfloat16 exactly when the Pallas path is active AND the
    community is large enough (>= MARKET_BF16_MIN_AGENTS agents) for the
    matrix stream to dominate; float32 otherwise.
    """
    md = cfg.sim.market_dtype
    if md != "auto":
        return md
    if resolve_use_pallas(cfg) and cfg.sim.n_agents >= MARKET_BF16_MIN_AGENTS:
        return "bfloat16"
    return "float32"


def resolve_market_impl(cfg: ExperimentConfig) -> str:
    """Resolve ``SimConfig.market_impl``'s "auto" default to
    "matrix" | "factored" for the scenario-batched path.

    The factored clearing (ops/factored_market.py) removes the [S, A, A]
    negotiation matrices entirely — O(A^2) fused VPU compute over
    O(A)-memory vectors instead of O(A^2) HBM streams — but only exists
    for the one-round (or zero-round)
    negotiation whose rank-1 row structure it exploits. Auto turns it on
    exactly where the fused Pallas matrix path would otherwise run
    (trading, TPU backend) and the round count allows; explicit "factored"
    forces it on any backend (pure jnp — used by the CPU equivalence
    tests), and config validation already rejected it for rounds > 1.
    """
    mi = cfg.sim.market_impl
    if mi != "auto":
        return mi
    if cfg.sim.trading and cfg.sim.rounds <= 1 and resolve_use_pallas(cfg):
        return "factored"
    return "matrix"


def slot_dynamics_batched(
    cfg: ExperimentConfig,
    policy: Policy,
    pol_state,
    phys_s: PhysState,
    xs,
    key: jax.Array,
    ratings: AgentRatings,
    explore: bool,
    settlement_hook=None,
    act_fn=None,
    explore_state=None,
    fused: bool = False,
):
    """Scenario-batched slot dynamics: same semantics as ``slot_dynamics``
    but with an explicit leading scenario axis on all simulation state
    (leaves [S, ...]; policy parameters shared).

    ``fused=True`` routes the whole slot through the Pallas megakernel
    (ops/pallas_slot.py::slot_step_fused) — one kernel instead of the op
    chain, same-seed bit-exact on the interpret-mode CPU path for
    tabular/dqn. Incompatible with ``settlement_hook``/``act_fn`` (the
    kernel owns settlement and the policy act).

    Written for the shared-parameter trainer (parallel/scenarios.py): the
    matrix passes run once over [S, A, A] — via broadcasting jnp ops, or the
    fused Pallas kernels when ``SimConfig.use_pallas`` — instead of being
    vmapped per scenario, and only the policy's act is vmapped.

    ``settlement_hook(p_grid, p_p2p, buy, inj, trade) -> cost [S, A]``
    optionally replaces the default per-agent settlement — the extension
    point for inter-community trading (envs/multi_community.py), where the
    leading axis is communities and part of each community's grid residual
    settles peer-to-peer with other communities.

    ``act_fn(pol_state, obs [S, A, 4], prev_frac [S, A], round_key,
    explore_state) -> (hp_frac, aux, q, explore_state)`` optionally replaces
    the default vmapped ``policy.act`` — used by policies whose exploration
    carries per-scenario state that must survive across rounds/slots (the OU
    noise of shared DDPG). ``explore_state`` is threaded through every
    negotiation round and returned.

    Returns (phys', pol_state, outputs, transition, explore_state').
    """
    if fused:
        if settlement_hook is not None or act_fn is not None:
            raise ValueError(
                "fused slot dynamics cannot take settlement_hook/act_fn "
                "overrides — the megakernel owns settlement and the policy "
                "act (use fused=False for multi-community/ddpg paths)"
            )
        from p2pmicrogrid_tpu.ops.pallas_slot import slot_step_fused

        market_impl_f = resolve_market_impl(cfg) if cfg.sim.trading else "matrix"
        f_dtype = (
            jnp.bfloat16
            if cfg.sim.trading
            and market_impl_f == "factored"
            and resolve_market_dtype(cfg) == "bfloat16"
            else None
        )
        phys_f, outputs_f, tr_f = slot_step_fused(
            cfg, pol_state, phys_s, xs, key, ratings, explore,
            market_impl=market_impl_f, compute_dtype=f_dtype,
        )
        return phys_f, pol_state, outputs_f, tr_f, explore_state

    time_s, t_out_s, load_w, pv_w, next_time_s, next_load_w, next_pv_w = xs
    n_scenarios = load_w.shape[0]
    th = cfg.thermal
    use_pallas = resolve_use_pallas(cfg)
    if use_pallas:
        from p2pmicrogrid_tpu.ops.pallas_market import (
            clear_market_fused,
            divide_power_fused_with_mean,
            divide_rank1_fused,
        )

    buy, inj = grid_prices(cfg.tariff, time_s)  # [S]
    trade = p2p_price_fn(buy, inj)

    balance_w = load_w - pv_w  # [S, A]
    soc = phys_s.soc
    if cfg.battery.enabled:
        soc, balance_w = battery_rule_update(
            cfg.battery, soc, balance_w, cfg.sim.dt_seconds
        )
    norm_balance = balance_w / ratings.max_in

    if act_fn is None:

        def act_fn(pol_state, obs, prev_frac, round_key, ex):
            keys = jax.random.split(round_key, n_scenarios)

            def one(o, f, k):
                frac, aux, q, _ = policy.act(pol_state, o, f, k, explore)
                return frac, aux, q

            frac, aux, q = jax.vmap(one)(obs, prev_frac, keys)
            return frac, aux, q, ex

    def _round_obs_act(p2p_mean, hp_frac, round_key, ex):
        obs = make_observation(
            time_s[:, None],
            normalized_temperature(th, phys_s.t_in),
            norm_balance,
            p2p_mean,
        )  # [S, A, 4]
        hp_frac, aux, q, ex = act_fn(pol_state, obs, hp_frac, round_key, ex)
        out_power = balance_w + hp_frac * th.hp_max_power
        return obs, hp_frac, aux, q, ex, out_power

    market_impl = resolve_market_impl(cfg) if cfg.sim.trading else "matrix"
    if cfg.sim.trading and market_impl == "factored":
        # Matrix-free path (ops/factored_market.py): the one-round
        # negotiation's final matrix is rank-1 per sign class, so clearing
        # reduces to fused broadcast-min reductions over [S, A] vectors —
        # no [S, A, A] materialization (O(A^2) compute, O(A) memory). Key
        # chain, observations and decisions are IDENTICAL to the matrix
        # paths (same per-round keys, same closed-form round-0 mean); only
        # the clearing arithmetic differs. The fused min pass follows the
        # same resolved market dtype as the matrix paths' storage
        # (bf16 at large A, f32 accumulation — resolve_market_dtype): the
        # O(A^2) VPU pass is the slot's largest op after the round-5
        # rewrite (artifacts/SLOT_PROFILE_r05.json) and bf16 compute is
        # the shipped tolerance class already.
        from p2pmicrogrid_tpu.ops.factored_market import (
            clear_factored_rounds0,
            clear_factored_rounds1,
        )

        f_dtype = (
            jnp.bfloat16
            if resolve_market_dtype(cfg) == "bfloat16"
            else None
        )
        n_rounds = cfg.sim.rounds + 1
        keys = jax.random.split(key, n_rounds)
        A = load_w.shape[1]
        obs, hp_frac, aux, q, ex, out0 = _round_obs_act(
            jnp.zeros_like(balance_w), phys_s.hp_frac, keys[0], explore_state
        )
        hp_power_l = [hp_frac * th.hp_max_power]
        if n_rounds == 1:
            p_grid, p_p2p = clear_factored_rounds0(out0, compute_dtype=f_dtype)
        else:
            tot = jnp.sum(out0, axis=-1, keepdims=True)
            mean_raw = -(tot - out0) / (A * A)
            obs, hp_frac, aux, q, ex, out1 = _round_obs_act(
                mean_raw / ratings.max_in, hp_frac, keys[1], ex
            )
            hp_power_l.append(hp_frac * th.hp_max_power)
            p_grid, p_p2p = clear_factored_rounds1(
                out0, out1, compute_dtype=f_dtype
            )
        explore_state = ex
        hp_power_r = jnp.stack(hp_power_l)  # [rounds+1, S, A]
    elif cfg.sim.trading and use_pallas:
        # Pallas path: a Python loop over the (static) round count so the
        # first rounds specialize. Round 0 always splits against a zero
        # matrix, making its output exactly rank-1 (out_0/A per row, the
        # equal-split branch) — so no matrix is materialized for it and its
        # prep_mean has a closed form; round 1 rebuilds that rank-1 matrix
        # in VMEM from the [S, A] vector (divide_rank1_fused); later rounds
        # run the full fused kernel, which emits the next round's mean while
        # its output is still in VMEM.
        # market_dtype is validated at config construction (SimConfig);
        # "auto" resolves here (bf16 on this path at large A).
        mdt = (
            jnp.bfloat16
            if resolve_market_dtype(cfg) == "bfloat16"
            else jnp.float32
        )
        n_rounds = cfg.sim.rounds + 1
        keys = jax.random.split(key, n_rounds)
        A = load_w.shape[1]
        mean_raw = jnp.zeros_like(balance_w)
        hp_frac, ex = phys_s.hp_frac, explore_state
        prev_vec, p2p = None, None
        obs = aux = q = None
        hp_power_l = []
        for r in range(n_rounds):
            obs, hp_frac, aux, q, ex, out_power = _round_obs_act(
                mean_raw / ratings.max_in, hp_frac, keys[r], ex
            )
            hp_power_l.append(hp_frac * th.hp_max_power)
            if r == 0:
                prev_vec = out_power
                tot = jnp.sum(out_power, axis=-1, keepdims=True)
                mean_raw = -(tot - out_power) / (A * A)
            elif prev_vec is not None:
                p2p, mean_raw = divide_rank1_fused(
                    prev_vec, out_power, out_dtype=mdt
                )
                prev_vec = None
            else:
                p2p, mean_raw = divide_power_fused_with_mean(p2p, out_power)
        explore_state = ex
        if p2p is None:
            # rounds == 0: single decision pass; materialize the rank-1 final
            # matrix for clearing (rare path, not bandwidth-critical).
            p2p = jnp.broadcast_to(
                (prev_vec / A)[:, :, None], (n_scenarios, A, A)
            ).astype(mdt)
        p_grid, p_p2p = clear_market_fused(p2p)
        hp_power_r = jnp.stack(hp_power_l)  # [rounds+1, S, A]
    elif cfg.sim.trading:

        def round_body(carry, round_key):
            p2p, hp_frac, ex = carry  # p2p [S, A, A]
            p2p_zd = zero_diagonal(p2p)
            powers = -jnp.swapaxes(p2p_zd, -1, -2)
            p2p_mean = jnp.mean(powers, axis=-1) / ratings.max_in
            obs, hp_frac, aux, q, ex, out_power = _round_obs_act(
                p2p_mean, hp_frac, round_key, ex
            )
            p_out = divide_power(out_power, powers)
            return (p_out, hp_frac, ex), (
                obs, aux, q, hp_frac * th.hp_max_power,
            )

        keys = jax.random.split(key, cfg.sim.rounds + 1)
        init = (
            jnp.zeros((n_scenarios, load_w.shape[1], load_w.shape[1])),
            phys_s.hp_frac,
            explore_state,
        )
        (p2p, hp_frac, explore_state), (obs_r, aux_r, q_r, hp_power_r) = jax.lax.scan(
            round_body,
            init,
            keys,
            unroll=cfg.sim.rounds + 1,
        )
        obs, aux, q = obs_r[-1], aux_r[-1], q_r[-1]
        p_grid, p_p2p = clear_market(p2p)
    else:
        # No-com community: one decision pass, zero p2p signal, grid-only
        # settlement (mirrors the trading=False branch of slot_dynamics).
        obs = make_observation(
            time_s[:, None],
            normalized_temperature(th, phys_s.t_in),
            norm_balance,
            jnp.zeros_like(norm_balance),
        )
        hp_frac, aux, q, explore_state = act_fn(
            pol_state, obs, phys_s.hp_frac, key, explore_state
        )
        p_grid = balance_w + hp_frac * th.hp_max_power
        p_p2p = jnp.zeros_like(p_grid)
        hp_power_r = (hp_frac * th.hp_max_power)[None]
    if settlement_hook is not None:
        cost = settlement_hook(p_grid, p_p2p, buy, inj, trade)
    else:
        cost = compute_costs(
            p_grid, p_p2p, buy[:, None], inj[:, None], trade[:, None], cfg.sim.slot_hours
        )

    penalty = comfort_penalty(th, phys_s.t_in)
    reward = -(cost + 10.0 * penalty)

    hp_power = hp_frac * th.hp_max_power
    t_in_pre = phys_s.t_in
    t_in_new, t_bm_new = thermal_step(
        th, cfg.sim.dt_seconds, t_out_s[:, None], phys_s.t_in, phys_s.t_bm, hp_power
    )

    next_temp = phys_s.t_in if cfg.sim.stale_next_temp else t_in_new
    next_balance = (next_load_w - next_pv_w) / ratings.max_in
    next_obs = make_observation(
        next_time_s[:, None],
        normalized_temperature(th, next_temp),
        next_balance,
        jnp.zeros_like(next_balance),
    )

    phys_s = PhysState(t_in=t_in_new, t_bm=t_bm_new, soc=soc, hp_frac=hp_frac)
    outputs = SlotOutputs(
        cost=cost,
        reward=reward,
        loss=jnp.zeros_like(reward),
        p_grid=p_grid,
        p_p2p=p_p2p,
        buy_price=buy,
        injection_price=inj,
        trade_price=trade,
        t_in=t_in_pre,
        hp_power_w=hp_power,
        decisions=jnp.swapaxes(hp_power_r, 0, 1),  # [S, rounds+1, A]
        q=q,
    )
    transition = SlotTransition(obs=obs, aux=aux, reward=reward, next_obs=next_obs)
    return phys_s, pol_state, outputs, transition, explore_state


def community_slot(
    cfg: ExperimentConfig,
    policy: Policy,
    carry,
    xs,
    training: bool,
    ratings: AgentRatings,
    fused: bool = False,
):
    """One 15-minute slot: negotiate -> clear -> settle -> learn -> step assets
    (community.py:149-170). ``fused=True`` replaces the slot-dynamics op
    chain with the Pallas megakernel (ops/pallas_slot.py) — learning stays
    outside either way."""
    phys, pol_state, key = carry
    key, k_round, k_learn = jax.random.split(key, 3)

    if fused:
        from p2pmicrogrid_tpu.ops.pallas_slot import slot_step_fused_single

        phys, outputs, tr = slot_step_fused_single(
            cfg, pol_state, phys, xs, k_round, ratings, explore=training
        )
    else:
        phys, pol_state, outputs, tr = slot_dynamics(
            cfg, policy, pol_state, phys, xs, k_round, ratings, explore=training
        )

    if training:
        pol_state, loss = policy.learn(
            pol_state, tr.obs, tr.aux, tr.reward, tr.next_obs, k_learn
        )
        outputs = outputs._replace(loss=loss)

    return (phys, pol_state, key), outputs


def run_episode(
    cfg: ExperimentConfig,
    policy: Policy,
    pol_state,
    phys: PhysState,
    arrays: EpisodeArrays,
    ratings: AgentRatings,
    key: jax.Array,
    training: bool = True,
    collect_device_metrics: bool = False,
    fused: "bool | None" = None,
) -> Tuple[PhysState, object, SlotOutputs]:
    """One full episode as a single ``lax.scan`` (community.py:149-182 for
    training, :95-123 for greedy evaluation).

    Returns (final physical state, final policy state, per-slot outputs with a
    leading time axis). With ``collect_device_metrics`` a
    ``telemetry.DeviceCounters`` total rides the scan carry — per-slot NaN/
    comfort/market counters accumulated in-program and reduced once per
    device call — and a 4th element is returned (the episode-total counters).

    ``fused`` selects the Pallas slot megakernel (ops/pallas_slot.py) for
    every slot of the scan; ``None`` resolves ``SimConfig.fused_slot``
    (``resolve_use_fused`` — off by default, tabular/dqn only).
    """
    use_fused = resolve_use_fused(cfg) if fused is None else bool(fused)
    if use_fused and cfg.train.implementation not in ("tabular", "dqn"):
        raise ValueError(
            "run_episode(fused=True) supports tabular/dqn policies only"
        )
    xs = (
        arrays.time,
        arrays.t_out,
        arrays.load_w,
        arrays.pv_w,
        arrays.next_time,
        arrays.next_load_w,
        arrays.next_pv_w,
    )
    ratings = AgentRatings(*(jnp.asarray(a) for a in ratings))

    if collect_device_metrics:
        from p2pmicrogrid_tpu.telemetry.device_metrics import (
            dc_add,
            dc_from_slot,
            dc_zero,
        )

    # One scan for both modes: the counter slot carries None (an empty
    # pytree) when disabled, so the program is unchanged.
    def step(carry, x):
        inner, dc = carry
        inner, outputs = community_slot(
            cfg, policy, inner, x, training, ratings, fused=use_fused
        )
        if collect_device_metrics:
            dc = dc_add(dc, dc_from_slot(cfg, outputs))
        return (inner, dc), outputs

    dc0 = dc_zero() if collect_device_metrics else None
    ((phys, pol_state, key), dc), outputs = jax.lax.scan(
        step, ((phys, pol_state, key), dc0), xs, unroll=cfg.sim.slot_unroll
    )
    if collect_device_metrics:
        return phys, pol_state, outputs, dc
    return phys, pol_state, outputs


def _thermostat_episode(
    cfg: ExperimentConfig,
    phys: PhysState,
    arrays: EpisodeArrays,
    hp_rule,
) -> Tuple[PhysState, SlotOutputs]:
    """Shared scaffold for the rule-based baselines: grid-only settlement,
    no learning, no RNG; ``hp_rule(phys, buy_price) -> hp_frac [A]`` supplies
    the heat-pump policy."""
    th = cfg.thermal

    def step(carry, x):
        phys = carry
        time_norm, t_out, load_w, pv_w = x
        buy, inj = grid_prices(cfg.tariff, time_norm)
        trade = p2p_price_fn(buy, inj)

        hp_frac = hp_rule(phys, buy)
        hp_power = hp_frac * th.hp_max_power

        balance_w = load_w - pv_w
        soc = phys.soc
        if cfg.battery.enabled:
            soc, balance_w = battery_rule_update(
                cfg.battery, soc, balance_w, cfg.sim.dt_seconds
            )
        p_grid = balance_w + hp_power
        p_p2p = jnp.zeros_like(p_grid)

        cost = compute_costs(p_grid, p_p2p, buy, inj, trade, cfg.sim.slot_hours)
        penalty = comfort_penalty(th, phys.t_in)
        reward = -(cost + 10.0 * penalty)

        t_in_new, t_bm_new = thermal_step(
            th, cfg.sim.dt_seconds, t_out, phys.t_in, phys.t_bm, hp_power
        )
        new_phys = PhysState(t_in=t_in_new, t_bm=t_bm_new, soc=soc, hp_frac=hp_frac)
        out = SlotOutputs(
            cost=cost,
            reward=reward,
            loss=jnp.zeros_like(reward),
            p_grid=p_grid,
            p_p2p=p_p2p,
            buy_price=buy,
            injection_price=inj,
            trade_price=trade,
            t_in=phys.t_in,
            hp_power_w=hp_power,
            decisions=hp_power[None, :],
            q=jnp.zeros_like(reward),
        )
        return new_phys, out

    xs = (arrays.time, arrays.t_out, arrays.load_w, arrays.pv_w)
    phys, outputs = jax.lax.scan(step, phys, xs)
    return phys, outputs


def _bang_bang(cfg: ExperimentConfig, phys: PhysState) -> jnp.ndarray:
    """Bang-bang thermostat (agent.py:130-136): full power below the comfort
    band, off above it, hold the previous command inside it."""
    th = cfg.thermal
    return jnp.where(
        phys.t_in <= th.lower_bound,
        1.0,
        jnp.where(phys.t_in >= th.upper_bound, 0.0, phys.hp_frac),
    )


def rule_baseline_episode(
    cfg: ExperimentConfig,
    phys: PhysState,
    arrays: EpisodeArrays,
) -> Tuple[PhysState, SlotOutputs]:
    """Thermostat bang-bang baseline, grid-only settlement — the reference's
    ``RuleAgent`` (agent.py:106-136)."""
    return _thermostat_episode(
        cfg, phys, arrays, lambda phys, buy: _bang_bang(cfg, phys)
    )


def semi_intelligent_baseline_episode(
    cfg: ExperimentConfig,
    phys: PhysState,
    arrays: EpisodeArrays,
) -> Tuple[PhysState, SlotOutputs]:
    """Price-aware thermostat baseline, grid-only settlement.

    The reference's thesis results include a 'semi-intelligent' baseline
    (data_analysis.py:327,865,1308-1319) whose generating code was never
    shipped. Reconstruction of the obvious mid-point between the bang-bang
    thermostat and the RL agents: identical comfort logic, plus pre-heating
    (at half power, up to the comfort band's upper bound) whenever the
    time-of-use buy price is below its daily average (= tariff ``cost_avg``,
    the mean of the sinusoid, agent.py:60-64) — buying heat in cheap slots to
    coast through expensive ones.
    """
    th = cfg.thermal
    avg_price = cfg.tariff.cost_avg / 100.0

    def rule(phys, buy):
        hp_frac = _bang_bang(cfg, phys)
        cheap = buy < avg_price
        return jnp.where(
            cheap & (phys.t_in < th.upper_bound), jnp.maximum(hp_frac, 0.5), hp_frac
        )

    return _thermostat_episode(cfg, phys, arrays, rule)
