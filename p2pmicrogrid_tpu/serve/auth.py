"""Trust termination for the serve fleet: per-household bearer tokens + TLS.

Untrusted households on real networks reach the gateway/router; two
stdlib-only primitives terminate trust there:

* **HMAC-signed bearer tokens.** ``p2p1.<b64url(claims)>.<b64url(sig)>``
  where ``claims`` is JSON ``{"household": id, "iat": unix, "exp": unix
  or null}`` and ``sig`` is HMAC-SHA256 over the claims bytes with the
  fleet secret. No asymmetric crypto, no external deps — one shared
  secret file (``serve-token --new-secret``) distributed to every
  gateway/router process. The household claim ``"*"`` is the operator
  wildcard: it authorizes ANY household plus the admin surface
  (``/stats``, ``/admin/*``) — the router holds one to probe and swap.
* **Failure taxonomy.** A missing/malformed/forged/expired token is 401
  ("you are nobody"); a VALID token presented for another household's
  request is 403 ("you are somebody, but not them"). Both are terminal
  client errors on the wire: router and loadgen never retry them and
  they never consume the retry budget — an attacker hammering /v1/act
  with garbage tokens must not eat the budget honest retries depend on.
* **Rotation without a synchronized restart.** ``rotate_secret`` writes a
  fresh secret in place and parks the previous one next to it
  (``<path>.prev``, JSON with an expiry ``grace_s`` seconds out);
  ``load_secret_chain`` returns the primary plus the still-graced old
  secret, and a ``TokenAuthenticator`` built on a chain verifies against
  BOTH until the grace expires (checked at verification time, so a
  long-lived gateway honors the expiry without reloading). Tokens are
  always MINTED with the primary — the old secret only verifies. Fleets
  rotate by running ``serve-token --rotate`` and restarting/reloading
  processes at leisure inside the grace window; requests signed with
  either secret pass mid-rotation, and post-grace old-secret tokens 401.
* **TLS.** ``server_ssl_context``/``client_ssl_context`` wrap stdlib
  ``ssl``; ``ensure_test_certs`` shells out to the system ``openssl`` to
  mint a short-lived self-signed cert (SAN ``IP:127.0.0.1,DNS:localhost``)
  under ``artifacts/tls/`` — a scratch location ``.gitignore``d and
  exempted by ``tools/check_artifacts_schema.py``'s committed-private-key
  refusal, so test keys can exist locally but never land in the repo.

Timing discipline: signature comparison is ``hmac.compare_digest``
(constant-time); everything else here is cold-path per-request work
measured in microseconds against a millisecond wire.
"""

from __future__ import annotations

import base64
import hmac
import hashlib
import json
import os
import secrets
import shutil
import subprocess
import time
from typing import Optional, Tuple

TOKEN_PREFIX = "p2p1"
WILDCARD_HOUSEHOLD = "*"


class AuthError(Exception):
    """A rejected credential. ``status`` is the HTTP mapping: 401 for
    missing/malformed/forged/expired tokens, 403 for a valid token that
    does not authorize the requested household/surface."""

    def __init__(self, message: str, status: int = 401):
        super().__init__(message)
        self.status = status


def _b64e(raw: bytes) -> str:
    return base64.urlsafe_b64encode(raw).decode().rstrip("=")


def _b64d(text: str) -> bytes:
    pad = "=" * (-len(text) % 4)
    return base64.urlsafe_b64decode(text + pad)


def generate_secret(path: Optional[str] = None) -> str:
    """A fresh 32-byte hex fleet secret; written 0600 when ``path``."""
    secret = secrets.token_hex(32)
    if path is not None:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(secret + "\n")
    return secret


def load_secret(path: str) -> str:
    with open(path) as f:
        secret = f.read().strip()
    if not secret:
        raise ValueError(f"secret file {path} is empty")
    return secret


def _prev_secret_path(path: str) -> str:
    return path + ".prev"


def rotate_secret(
    path: str, grace_s: float = 3600.0, now: Optional[float] = None
) -> str:
    """Rotate the fleet secret at ``path`` in place.

    Writes a fresh secret to ``path`` (0600) and parks the PREVIOUS one in
    ``<path>.prev`` as JSON ``{"secret": ..., "expires": unix}`` with the
    expiry ``grace_s`` seconds from ``now``. Verifiers built from
    ``load_secret_chain`` honor both until the grace passes, so the fleet
    needs no synchronized restart; minting always uses the new primary.
    Returns the new secret.
    """
    now = time.time() if now is None else now
    old = load_secret(path)
    fd = os.open(
        _prev_secret_path(path), os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600
    )
    with os.fdopen(fd, "w") as f:
        json.dump({"secret": old, "expires": now + max(grace_s, 0.0)}, f)
    return generate_secret(path)


def load_secret_chain(
    path: str, now: Optional[float] = None
) -> list:
    """``[(secret, expires_or_None), ...]`` — the primary secret first
    (never expiring), then the rotated-out previous secret while its grace
    window holds. An expired/missing/corrupt ``.prev`` contributes
    nothing: the chain degrades to exactly ``load_secret``'s behavior."""
    now = time.time() if now is None else now
    chain = [(load_secret(path), None)]
    try:
        with open(_prev_secret_path(path)) as f:
            prev = json.load(f)
        secret = prev.get("secret")
        expires = float(prev.get("expires", 0.0))
        if secret and expires > now:
            chain.append((secret, expires))
    except (OSError, ValueError, TypeError):
        pass
    return chain


def _sign(secret: str, claims_raw: bytes) -> bytes:
    return hmac.new(secret.encode(), claims_raw, hashlib.sha256).digest()


def mint_token(
    secret: str,
    household: str,
    ttl_s: Optional[float] = None,
    now: Optional[float] = None,
) -> str:
    """A signed bearer for ``household`` (``"*"`` = operator wildcard),
    expiring ``ttl_s`` seconds from ``now`` (None = never)."""
    if not household:
        raise ValueError("household must be non-empty")
    now = time.time() if now is None else now
    claims = {
        "household": household,
        "iat": int(now),
        "exp": int(now + ttl_s) if ttl_s is not None else None,
    }
    raw = json.dumps(claims, sort_keys=True, separators=(",", ":")).encode()
    return f"{TOKEN_PREFIX}.{_b64e(raw)}.{_b64e(_sign(secret, raw))}"


def verify_token(secret: str, token: str, now: Optional[float] = None) -> dict:
    """The verified claims dict, or ``AuthError`` (always 401 here: a
    token that fails verification authenticates nobody)."""
    if not isinstance(token, str) or not token:
        raise AuthError("missing bearer token", status=401)
    parts = token.split(".")
    if len(parts) != 3 or parts[0] != TOKEN_PREFIX:
        raise AuthError("malformed bearer token", status=401)
    try:
        raw = _b64d(parts[1])
        sig = _b64d(parts[2])
    except (ValueError, TypeError):
        raise AuthError("malformed bearer token", status=401) from None
    if not hmac.compare_digest(sig, _sign(secret, raw)):
        raise AuthError("bad token signature", status=401)
    try:
        claims = json.loads(raw.decode())
    except (UnicodeDecodeError, json.JSONDecodeError):
        raise AuthError("malformed token claims", status=401) from None
    if not isinstance(claims, dict) or not claims.get("household"):
        raise AuthError("token carries no household claim", status=401)
    exp = claims.get("exp")
    if exp is not None:
        now = time.time() if now is None else now
        if now >= exp:
            raise AuthError("token expired", status=401)
    return claims


class TokenAuthenticator:
    """The gateway/router-side verifier bound to one fleet secret — or,
    across a rotation, to a dual-secret chain: ``secret`` may be a plain
    string or a ``load_secret_chain`` list of ``(secret, expires)`` pairs.
    Minting always signs with the PRIMARY (first) secret; verification
    accepts any chain member whose expiry has not passed — expiry is
    checked per verification, so the grace window closes on schedule in a
    long-lived process without reloading the chain."""

    def __init__(self, secret):
        if isinstance(secret, str):
            chain = [(secret, None)]
        else:
            chain = [(s, e) for s, e in secret]
        if not chain or not all(s for s, _ in chain):
            raise ValueError("secret must be non-empty")
        self.chain = chain
        self.secret = chain[0][0]  # the minting (primary) secret

    @classmethod
    def from_secret_file(cls, path: str) -> "TokenAuthenticator":
        """Build from a secret file, honoring a rotation's ``.prev``
        grace window (``load_secret_chain``)."""
        return cls(load_secret_chain(path))

    def mint(self, household: str, ttl_s: Optional[float] = None) -> str:
        return mint_token(self.secret, household, ttl_s=ttl_s)

    def verify(self, token: Optional[str]) -> dict:
        """Verify against every live chain member; the PRIMARY's failure
        is what surfaces (the old secret is a compatibility window, not
        an identity of its own)."""
        now = time.time()
        primary_error: Optional[AuthError] = None
        for i, (secret, expires) in enumerate(self.chain):
            if expires is not None and now >= expires:
                continue
            try:
                return verify_token(secret, token, now=now)
            except AuthError as err:
                if i == 0:
                    primary_error = err
        raise primary_error or AuthError("missing bearer token", status=401)

    def check(self, token: Optional[str], household: Optional[str]) -> dict:
        """Authorize an act request for ``household``. 401 on a token
        that authenticates nobody; 403 on a real token for the wrong
        household (wildcard tokens pass any)."""
        claims = self.verify(token)
        claimed = claims["household"]
        if claimed == WILDCARD_HOUSEHOLD:
            return claims
        if household is not None and household != claimed:
            raise AuthError(
                f"token authorizes household {claimed!r}, "
                f"not {household!r}", status=403,
            )
        return claims

    def check_admin(self, token: Optional[str]) -> dict:
        """Authorize the admin surface (stats/swap/drain): wildcard only."""
        claims = self.verify(token)
        if claims["household"] != WILDCARD_HOUSEHOLD:
            raise AuthError(
                "admin surface requires the operator wildcard token",
                status=403,
            )
        return claims


# -- TLS ----------------------------------------------------------------------


def server_ssl_context(cert_path: str, key_path: str):
    """TLS-terminating server context over a cert/key pair on disk."""
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_path, key_path)
    return ctx


def client_ssl_context(cafile: str):
    """Client context trusting EXACTLY ``cafile`` (the fleet's self-signed
    test cert doubles as its own CA); hostname/IP-SAN checking stays ON."""
    import ssl

    return ssl.create_default_context(cafile=cafile)


# artifacts/tls under the REPO ROOT is the designated local scratch for
# generated test certs: .gitignore'd, and exempted from
# check_artifacts_schema's private-key refusal — keys may exist there,
# never anywhere committed. Anchored to this file (not the CWD) so a CLI
# run from a subdirectory cannot scatter key material into unignored,
# checker-visible locations.
_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
TEST_CERT_DIR = os.path.join(_REPO_ROOT, "artifacts", "tls")


def ensure_test_certs(
    cert_dir: str = TEST_CERT_DIR,
    days: int = 2,
    refresh_after_s: float = 12 * 3600.0,
) -> Tuple[str, str]:
    """(cert_path, key_path) of a loopback self-signed pair under
    ``cert_dir``, minted via the system ``openssl`` (no Python crypto
    deps). Reuses a pair younger than ``refresh_after_s`` — well inside
    the ``days`` validity, so a reused cert never expires mid-run."""
    cert = os.path.join(cert_dir, "test-cert.pem")
    key = os.path.join(cert_dir, "test-key.pem")
    if os.path.exists(cert) and os.path.exists(key):
        age = time.time() - os.path.getmtime(cert)
        if age < refresh_after_s:
            return cert, key
    if shutil.which("openssl") is None:
        raise RuntimeError(
            "openssl binary not found: cannot generate test TLS certs "
            "(provide --tls-cert/--tls-key explicitly)"
        )
    os.makedirs(cert_dir, exist_ok=True)
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048",
            "-keyout", key, "-out", cert,
            "-days", str(days), "-nodes",
            "-subj", "/CN=p2p-test-fleet",
            "-addext", "subjectAltName=IP:127.0.0.1,DNS:localhost",
        ],
        check=True,
        capture_output=True,
    )
    os.chmod(key, 0o600)
    return cert, key
