"""Batched greedy inference engine over a policy bundle.

The serving hot path of the paper's decision loop: every 15-minute slot,
every household (grouped per community) needs a greedy heat-pump action from
the trained policy given its observation. Requests arrive as community
observation rows ``[A, 4]``; the engine coalesces them into batches
``[B, A, 4]`` and answers with heat-pump fractions ``[B, A]``.

Design points:

* **Padding buckets.** ``jax.jit`` compiles one program per input shape, so
  arbitrary request-batch sizes would compile unboundedly many programs and
  stall tail requests behind compiles. The engine rounds every batch up to
  the next power of two (capped at ``max_batch``), so ALL traffic hits a
  small fixed set of pre-compiled programs; ``warmup()`` compiles them ahead
  of the first request. The pad rows are wasted compute — the engine counts
  them (``padding_waste``) and serve-bench reports the fraction.

* **Bit-exact greedy.** The per-implementation forward passes below are the
  SAME computations as the training-side greedy paths (``tabular_act`` /
  ``dqn_act`` with ``explore=False``; the actor half of ``ddpg_shared_act``),
  so a bundle serves byte-identical actions to the checkpoint it came from —
  enforced by tests/test_serve.py. One honest caveat: XLA fuses and tiles
  the MLP math differently per program and per shape, so raw network
  outputs can move by ~1 ulp vs the training-side call. The DISCRETE
  policies (tabular, DQN) serve BIT-IDENTICAL actions regardless — a table
  gather is exact and an argmax only flips on an exact tie; the continuous
  DDPG actor is deterministic per bucket and matches the training greedy
  act to ~1e-7 relative. Both guarantees assume the default float32 export:
  a ``dtype="float16"`` bundle quantizes the parameters themselves (see
  serve/export.py).

* **Sessions.** ``init_sessions``/``step`` carry per-household cross-slot
  state (previous served action — the env's round-0 ``hp_frac`` carry — and
  a served-slot counter) through a donated-buffer jitted step, so a
  controller loop holds one live array instead of re-shipping state.
  Recurrent bundles (manifest ``hidden_state``, models/ddpg_recurrent.py)
  extend the carry with their per-agent flat LSTM hidden state:
  ``act(obs, hidden)`` threads it explicitly, ``Sessions.hidden`` rides the
  donated step, and a recurrent bundle REFUSES to act without a carry — a
  hidden-state policy served statelessly is a different policy.

* **Microbatching.** ``MicroBatchQueue`` fronts the engine for concurrent
  callers: single-community requests coalesce until ``max_batch`` or
  ``max_wait_s``, then execute as one padded batch. It refuses recurrent
  bundles (sessions are disabled on the full-batch path); the slot-level
  continuous batcher (serve/continuous.py) is the session-carrying front
  — and the lower-p99 one under bursty load.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import NamedTuple, Optional

import numpy as np


class Sessions(NamedTuple):
    """Per-community serving sessions (leaves [N, ...]).

    ``hidden`` is ``None`` for the feedforward policies; a recurrent bundle
    (manifest ``hidden_state``) carries its per-household flat LSTM carry
    ``[N, A, H]`` here — state the POLICY reads, not just bookkeeping, so it
    must ride the same donated device step as the rest of the session."""

    hp_frac: object  # [N, A] last served action fraction
    slots: object    # [N] int32 slots served
    hidden: object = None  # [N, A, H] recurrent carry (None: feedforward)


# Process-wide AOT executable cache for the padding-bucket act programs,
# keyed by (implementation, n_agents, model-architecture signature, device)
# -> {bucket: jax Compiled}. The greedy program depends only on the
# architecture and the bucket shape — NOT on the parameter values or the
# bundle's on-disk dtype (serving always computes f32) — so one compile
# serves every same-arch bundle in the process: export-time AOT
# (serve/export.py::aot_compile_bundle, the ``jit(...).lower().compile()``
# path) pre-populates it, and a gateway hot-swap to a retrained same-arch
# candidate warms up without compiling anything. Donating programs (the
# session step) are deliberately NOT cached. Bounded LRU over arch keys so a
# long-lived gateway whose candidates drift architecture (community growth,
# hidden-width change) does not retain dead executables for the process
# lifetime; steady same-arch operation never evicts.
_AOT_PROGRAM_CACHE: dict = {}
_AOT_CACHE_MAX_ARCHES = 8


def _aot_cache_for(key: tuple) -> dict:
    """The per-architecture bucket dict, LRU-touched; evicts the stalest
    architecture's executables past ``_AOT_CACHE_MAX_ARCHES`` entries."""
    cache = _AOT_PROGRAM_CACHE.pop(key, None)
    if cache is None:
        cache = {}
        while len(_AOT_PROGRAM_CACHE) >= _AOT_CACHE_MAX_ARCHES:
            # dicts iterate in insertion order; the pop/re-insert below
            # keeps that order LRU, so the first key is the stalest.
            _AOT_PROGRAM_CACHE.pop(next(iter(_AOT_PROGRAM_CACHE)))
    _AOT_PROGRAM_CACHE[key] = cache
    return cache


def clear_aot_program_cache() -> None:
    """Drop every cached bucket executable (tests, cold-start measurement)."""
    _AOT_PROGRAM_CACHE.clear()


def _arch_signature(manifest: dict) -> tuple:
    """Hashable architecture identity of a bundle's greedy program."""
    impl = manifest.get("implementation")
    model = manifest.get("model") or {}
    if impl == "tabular":
        q = model.get("qlearning") or {}
        return ("tabular",) + tuple(sorted((k, v) for k, v in q.items()))
    if impl == "dqn":
        return ("dqn", model.get("hidden"))
    if impl == "ddpg_recurrent":
        return (
            "ddpg_recurrent", model.get("hidden_pre"),
            model.get("lstm_features"), model.get("hidden_post"),
        )
    return (
        "ddpg", model.get("actor_hidden"), bool(model.get("share_across_agents"))
    )


def _bucket_sizes(max_batch: int) -> list:
    sizes, b = [], 1
    while b < max_batch:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch)
    return sizes


class PolicyEngine:
    """Loads a policy bundle and serves batched greedy actions.

    ``act(obs)`` with obs ``[B, A, 4]`` returns hp fractions ``[B, A]``;
    batches larger than ``max_batch`` are split, smaller ones padded up to
    the next power-of-two bucket. ``telemetry`` (a ``telemetry.Telemetry``)
    receives ``serve.*`` counters and per-batch latency histograms.
    """

    def __init__(
        self,
        bundle_dir: Optional[str] = None,
        manifest: Optional[dict] = None,
        params: Optional[dict] = None,
        max_batch: int = 256,
        telemetry=None,
        device: str = "auto",
    ):
        import jax
        import jax.numpy as jnp

        if bundle_dir is not None:
            from p2pmicrogrid_tpu.serve.export import load_policy_bundle

            manifest, params = load_policy_bundle(bundle_dir)
        if manifest is None or params is None:
            raise ValueError("pass bundle_dir, or both manifest and params")
        if max_batch < 1 or max_batch & (max_batch - 1):
            raise ValueError(f"max_batch must be a power of two, got {max_batch}")
        if device not in ("auto", "default", "cpu"):
            raise ValueError(
                f"device must be 'auto', 'default' or 'cpu', got {device!r}"
            )
        self.manifest = manifest
        self.max_batch = max_batch
        self.telemetry = telemetry
        self.n_agents = int(manifest["n_agents"])
        self._impl = manifest["implementation"]
        # Recurrent bundles (manifest ``hidden_state``) thread a per-agent
        # flat carry through every act: the serving contract sizes the
        # session ring from the manifest block, never the arch fields.
        hidden_spec = manifest.get("hidden_state")
        self.is_recurrent = hidden_spec is not None
        self.hidden_dim = (
            int(hidden_spec["shape"][-1]) if self.is_recurrent else 0
        )
        # Crossover-driven placement (train/placement.py): tiny communities'
        # greedy passes are dispatch-bound and measured faster on host
        # XLA-CPU — 'auto' serves them from there the way training places
        # itself; 'default' pins the default backend, 'cpu' forces host CPU.
        self.device = None
        self.placement_reason = "default backend"
        if device == "cpu":
            try:
                self.device = jax.devices("cpu")[0]
                self.placement_reason = "pinned by device='cpu'"
            except RuntimeError:
                self.placement_reason = "host XLA-CPU backend unavailable"
        elif device == "auto":
            from p2pmicrogrid_tpu.train.placement import pick_serve_device

            # Batch-width-aware: the serve-specific crossover table decides
            # when one exists; wide-batch configs without a serve
            # measurement stay on the default backend (the B=1 training
            # table only governs max_batch=1 serving).
            self.device, self.placement_reason = pick_serve_device(
                self._impl, self.n_agents, max_batch=self.max_batch
            )
        # Serving computes in float32 regardless of the on-disk dtype: a
        # float16 bundle halves storage/transfer, not arithmetic precision.
        self.params = jax.tree_util.tree_map(
            lambda x: jnp.asarray(
                x, jnp.float32 if np.issubdtype(x.dtype, np.floating) else None
            ),
            params,
        )
        if self.device is not None:
            # Committed params pin every bucket program to the chosen
            # device (uncommitted obs inputs follow the committed operand).
            self.params = jax.device_put(self.params, self.device)
            if self.telemetry is not None:
                self.telemetry.event(
                    "serve_placement",
                    device=str(self.device),
                    reason=self.placement_reason,
                )
        self._act_raw = self._build_act_fn()
        # One jitted callable; XLA caches one executable per bucket shape.
        self._act_jit = jax.jit(self._act_raw)
        # Profiled/AOT warmups stash the executable per bucket here; the act
        # path prefers it (the AOT and jit-call caches are separate, so this
        # is what keeps compile-profiling from compiling every bucket twice).
        self._compiled: dict = {}
        # Process-wide AOT reuse across engines of the SAME architecture
        # (export-time precompiles, hot-swapped same-arch candidates).
        self._aot_key = (_arch_signature(manifest), self.n_agents,
                         str(self.device))
        self._step_jit = jax.jit(self._step_fn, donate_argnums=(1,))
        self.stats = {
            "batches": 0, "rows": 0, "padded_rows": 0,
            "aot_hits": 0, "aot_compiles": 0,
        }

    # --- greedy forward passes (mirror the training greedy paths) -----------

    def _build_act_fn(self):
        import jax
        import jax.numpy as jnp

        impl = self._impl
        model = self.manifest["model"]
        if impl == "tabular":
            from p2pmicrogrid_tpu.config import QLearningConfig
            from p2pmicrogrid_tpu.models.dqn import ACTION_VALUES
            from p2pmicrogrid_tpu.models.tabular import TabularState, tabular_act

            qcfg = QLearningConfig(**model["qlearning"])
            key0 = jax.random.PRNGKey(0)  # unused on the explore=False path

            def act(params, obs):  # [B, A, 4] -> [B, A]
                state = TabularState(
                    q_table=params["q_table"], epsilon=jnp.zeros(())
                )

                def one(o):
                    action, _ = tabular_act(qcfg, state, o, key0, explore=False)
                    return ACTION_VALUES[action]

                return jax.vmap(one)(obs)

            return act

        if impl == "dqn":
            from p2pmicrogrid_tpu.config import DQNConfig
            from p2pmicrogrid_tpu.models.dqn import ACTION_VALUES, _q_all_actions

            dcfg = DQNConfig(hidden=model["hidden"])

            def act(params, obs):
                def one(o):
                    q = _q_all_actions(dcfg, params, o)
                    return ACTION_VALUES[jnp.argmax(q, axis=-1).astype(jnp.int32)]

                return jax.vmap(one)(obs)

            return act

        if impl == "ddpg_recurrent":
            from p2pmicrogrid_tpu.models.ddpg_recurrent import (
                recurrent_actor_step,
            )

            lstm_features = model["lstm_features"]

            # One shared actor across agents AND batch rows: flatten [B, A]
            # into the leading axis, step the LSTM cell once, restore.
            def act(params, obs, hidden):  # [B,A,4], [B,A,H] -> ([B,A], ')
                B, A, F = obs.shape
                a, h = recurrent_actor_step(
                    params,
                    obs.reshape(B * A, F),
                    hidden.reshape(B * A, hidden.shape[-1]),
                    lstm_features=lstm_features,
                )
                return a.reshape(B, A), h.reshape(B, A, h.shape[-1])

            return act

        if impl == "ddpg":
            from p2pmicrogrid_tpu.models.networks import Actor

            actor = Actor(hidden=model["actor_hidden"])
            if model["share_across_agents"]:

                def act(params, obs):
                    B, A, F = obs.shape
                    flat = obs.reshape(B * A, F)
                    return actor.apply({"params": params}, flat)[:, 0].reshape(B, A)

            else:

                def act(params, obs):
                    def one_agent(pa, o):  # o [B, 4]
                        return actor.apply({"params": pa}, o)[:, 0]

                    return jax.vmap(one_agent, in_axes=(0, 1), out_axes=1)(
                        params, obs
                    )

            return act

        raise ValueError(f"bundle has unknown implementation {self._impl!r}")

    # --- batched act --------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        """Smallest power-of-two bucket >= n (capped at max_batch)."""
        if n < 1:
            raise ValueError(f"batch size must be >= 1, got {n}")
        b = 1
        while b < n:
            b *= 2
        return min(b, self.max_batch)

    @property
    def buckets(self) -> list:
        return _bucket_sizes(self.max_batch)

    def warmup(self, buckets=None, include_step: bool = True) -> list:
        """Pre-compile the bucket programs; returns the bucket sizes
        warmed. Without this, the first request of each size pays its
        compile inside its latency. Buckets whose same-architecture program
        is already in the process-wide AOT cache (export-time
        ``aot_compile_bundle``, an earlier engine) are adopted WITHOUT
        compiling — the hot-swap warmup savings the ``serve_quantized``
        bench row measures; ``stats['aot_hits']``/``['aot_compiles']``
        count both paths. ``include_step`` also compiles the
        session-step executable per bucket (a separate XLA program) — a
        controller loop's first ``step()`` must not compile in-slot;
        act-only callers (serve-bench) pass False and skip that cost.

        With a ``telemetry`` attached (and ``P2P_PROFILE`` not 0), each
        bucket's program is also compile-profiled: HLO flops/bytes and the
        executable's buffer sizes land as ``profile.serve_bucket_<b>.*``
        gauges plus a ``compile_profile`` event — the per-bucket cost model
        next to the measured ``serve.batch_ms`` latencies."""
        import jax

        profile = False
        if self.telemetry is not None:
            from p2pmicrogrid_tpu.telemetry.profiling import (
                profile_and_compile,
                profiling_enabled,
            )

            profile = profiling_enabled()
        warmed = []
        cache = _aot_cache_for(self._aot_key)
        for b in buckets if buckets is not None else self.buckets:
            obs = np.zeros((b, self.n_agents, 4), dtype=np.float32)
            # Recurrent programs take the hidden carry as a third operand;
            # the AOT cache key's arch signature already separates them
            # from same-shape feedforward programs.
            operands = (
                (obs, np.zeros((b, self.n_agents, self.hidden_dim),
                               np.float32))
                if self.is_recurrent else (obs,)
            )
            cached = cache.get(b)
            if cached is not None and not profile:
                # AOT hit: a same-architecture bucket program was already
                # compiled in this process (export-time aot_compile_bundle,
                # or an earlier engine) — this warmup/hot-swap pays no cold
                # compile. The program depends only on arch + bucket shape,
                # never on parameter values.
                self._compiled[b] = cached
                self.stats["aot_hits"] += 1
                if self.telemetry is not None:
                    self.telemetry.counter("serve.aot_hit")
            elif profile:
                # One AOT compile serves both the profile and the bucket's
                # executable (stashed for the act path) — the AOT and
                # jit-call caches are separate, so profiling via the jit
                # wrapper would compile each bucket twice.
                compiled, _ = profile_and_compile(
                    self._act_jit, self.params, *operands,
                    label=f"serve_bucket_{b}", telemetry=self.telemetry,
                    extra={"bucket": b, "n_agents": self.n_agents},
                )
                if compiled is not self._act_jit:
                    self._compiled[b] = compiled
                    cache[b] = compiled
                self.stats["aot_compiles"] += 1
                # host-sync: warmup compile boundary (pre-traffic).
                jax.block_until_ready(compiled(self.params, *operands))
            else:
                # AOT-compile the bucket program explicitly
                # (jit(...).lower().compile()) so later same-arch engines
                # hit the cache instead of recompiling.
                compiled = self._act_jit.lower(
                    self.params, *operands
                ).compile()
                self._compiled[b] = compiled
                cache[b] = compiled
                self.stats["aot_compiles"] += 1
                if self.telemetry is not None:
                    self.telemetry.counter("serve.aot_compile")
                # host-sync: warmup compile boundary (pre-traffic).
                jax.block_until_ready(compiled(self.params, *operands))
            if include_step:
                # host-sync: warmup compile boundary (pre-traffic).
                jax.block_until_ready(
                    self._step_jit(self.params, self.init_sessions(b), obs)[1]
                )
            warmed.append(b)
        return warmed

    def _check_obs(self, obs: np.ndarray) -> np.ndarray:
        # host-sync: caller-supplied host observations, not device values.
        obs = np.asarray(obs, dtype=np.float32)
        if obs.ndim != 3 or obs.shape[1:] != (self.n_agents, 4):
            raise ValueError(
                f"obs must be [B, {self.n_agents}, 4] for this bundle "
                f"(setting {self.manifest.get('setting')!r}), got {obs.shape}"
            )
        return obs

    def act(self, obs, hidden=None):
        """Greedy actions for a batch of community observations.

        obs [B, A, 4] -> hp fraction [B, A]. B may exceed ``max_batch``
        (the batch is split); sub-bucket batches are zero-padded and the pad
        rows discarded.

        Recurrent bundles THREAD the carry: pass ``hidden`` [B, A, H]
        (``init_hidden`` for fresh sessions) and get ``(actions [B, A],
        hidden' [B, A, H])`` back. Calling a recurrent bundle without
        ``hidden`` is refused loudly — a hidden-state policy served
        statelessly is a different (wrong) policy, not a degraded one.
        Feedforward bundles refuse a ``hidden`` argument symmetrically.
        """
        obs = self._check_obs(obs)
        if self.is_recurrent and hidden is None:
            raise ValueError(
                "recurrent bundle: act() needs the hidden carry "
                "([B, A, H]; init_hidden() for fresh sessions) — serve it "
                "through session-carrying paths (ContinuousBatcher with "
                "sessions on), not the stateless act/microbatch path"
            )
        if not self.is_recurrent and hidden is not None:
            raise ValueError(
                f"{self._impl!r} bundle is feedforward — it takes no "
                "hidden carry"
            )
        if hidden is not None:
            hidden = self._check_hidden(hidden, obs.shape[0])
        if obs.shape[0] == 0:
            empty = np.zeros((0, self.n_agents), dtype=np.float32)
            if self.is_recurrent:
                return empty, np.zeros(
                    (0, self.n_agents, self.hidden_dim), np.float32
                )
            return empty
        outs, hiddens = [], []
        for i in range(0, obs.shape[0], self.max_batch):
            out = self._act_one_batch(
                obs[i : i + self.max_batch],
                hidden[i : i + self.max_batch] if hidden is not None else None,
            )
            if self.is_recurrent:
                outs.append(out[0])
                hiddens.append(out[1])
            else:
                outs.append(out)
        if self.is_recurrent:
            return (
                np.concatenate(outs, axis=0),
                np.concatenate(hiddens, axis=0),
            )
        return np.concatenate(outs, axis=0)

    def _check_hidden(self, hidden, n_rows: int) -> np.ndarray:
        # host-sync: caller-supplied host carry, not device values.
        hidden = np.asarray(hidden, dtype=np.float32)
        want = (n_rows, self.n_agents, self.hidden_dim)
        if hidden.shape != want:
            raise ValueError(
                f"hidden carry must be {list(want)} for this bundle, "
                f"got {list(hidden.shape)}"
            )
        return hidden

    def _act_one_batch(self, obs: np.ndarray, hidden=None):
        import jax

        b = obs.shape[0]
        bucket = self.bucket_for(b)
        if bucket > b:
            pad = np.zeros((bucket - b,) + obs.shape[1:], dtype=obs.dtype)
            obs = np.concatenate([obs, pad], axis=0)
            if hidden is not None:
                hidden = np.concatenate(
                    [hidden,
                     np.zeros((bucket - b,) + hidden.shape[1:], hidden.dtype)],
                    axis=0,
                )
        t0 = time.perf_counter()
        # Prefer the bucket's AOT executable from a profiled warmup (same
        # program; avoids a cold jit-cache compile next to it).
        act = self._compiled.get(bucket, self._act_jit)
        operands = (obs,) if hidden is None else (obs, hidden)
        out = act(self.params, *operands)
        # host-sync: the per-batch serving latency boundary — requests
        # need their answers NOW; serve latency IS this sync.
        jax.block_until_ready(out)
        secs = time.perf_counter() - t0
        self.stats["rows"] += b
        self.stats["batches"] += 1
        self.stats["padded_rows"] += bucket - b
        if self.telemetry is not None:
            self.telemetry.counter("serve.requests", b)
            self.telemetry.counter("serve.batches")
            self.telemetry.counter("serve.padded_rows", bucket - b)
            self.telemetry.histogram("serve.batch_ms", secs * 1e3)
        if self.is_recurrent:
            actions, new_hidden = out
            # host-sync: result delivery
            return np.asarray(actions[:b]), np.asarray(new_hidden[:b])
        return np.asarray(out[:b])  # host-sync: result delivery

    @property
    def padding_waste(self) -> float:
        """Fraction of computed rows that were padding, lifetime."""
        total = self.stats["rows"] + self.stats["padded_rows"]
        return self.stats["padded_rows"] / total if total else 0.0

    # --- stateful per-community sessions ------------------------------------

    def _step_fn(self, params, sessions: Sessions, obs):
        import jax.numpy as jnp

        if self.is_recurrent:
            hp, hidden = self._act_raw(params, obs, sessions.hidden)
            return Sessions(
                hp_frac=hp, slots=sessions.slots + jnp.int32(1), hidden=hidden
            ), hp
        hp = self._act_raw(params, obs)
        return Sessions(
            hp_frac=hp, slots=sessions.slots + jnp.int32(1),
            hidden=sessions.hidden,
        ), hp

    def init_hidden(self, n: int):
        """Deterministic fresh-session hidden carry [n, A, H] (zeros) —
        what a session re-init after eviction resets to."""
        import jax.numpy as jnp

        if not self.is_recurrent:
            raise ValueError(
                f"{self._impl!r} bundle is feedforward — no hidden carry"
            )
        return jnp.zeros((n, self.n_agents, self.hidden_dim), jnp.float32)

    def init_sessions(self, n: int) -> Sessions:
        import jax
        import jax.numpy as jnp

        sessions = Sessions(
            hp_frac=jnp.zeros((n, self.n_agents), jnp.float32),
            slots=jnp.zeros((n,), jnp.int32),
            hidden=self.init_hidden(n) if self.is_recurrent else None,
        )
        if self.device is not None:
            # Sessions ride the donated step next to the committed params —
            # they must live on the same (placement-chosen) device.
            sessions = jax.device_put(sessions, self.device)
        return sessions

    def step(self, sessions: Sessions, obs):
        """Advance ``n`` sessions one slot: act on obs [n, A, 4], record the
        served action as each session's new ``hp_frac``, bump slot counters.

        The jitted step donates the (padded) session buffers — the previous
        slot's state is consumed in place, not copied. Returns
        (sessions', hp_frac [n, A]).
        """
        import jax.numpy as jnp

        obs = self._check_obs(obs)
        n = obs.shape[0]
        if int(sessions.slots.shape[0]) != n:
            raise ValueError(
                f"{n} obs rows for {int(sessions.slots.shape[0])} sessions"
            )
        bucket = self.bucket_for(n)
        if n > self.max_batch:
            raise ValueError(
                f"sessions batch {n} exceeds max_batch {self.max_batch}"
            )
        if bucket > n:
            pad = bucket - n
            obs = np.concatenate(
                [obs, np.zeros((pad,) + obs.shape[1:], obs.dtype)], axis=0
            )
            sessions = Sessions(
                hp_frac=jnp.concatenate(
                    [sessions.hp_frac,
                     jnp.zeros((pad, self.n_agents), jnp.float32)], axis=0
                ),
                slots=jnp.concatenate(
                    [sessions.slots, jnp.zeros((pad,), jnp.int32)], axis=0
                ),
                hidden=(
                    jnp.concatenate(
                        [sessions.hidden, self.init_hidden(pad)], axis=0
                    ) if sessions.hidden is not None else None
                ),
            )
        new, hp = self._step_jit(self.params, sessions, obs)
        new = Sessions(
            hp_frac=new.hp_frac[:n], slots=new.slots[:n],
            hidden=new.hidden[:n] if new.hidden is not None else None,
        )
        return new, np.asarray(hp[:n])  # host-sync: result delivery


class MicroBatchQueue:
    """Coalescing front for concurrent single-community callers.

    ``submit(obs_row [A, 4])`` returns a ``Future`` resolving to the
    household actions ``[A]``. Waiting requests are dispatched as ONE
    padded engine batch when either ``max_batch`` have queued or the oldest
    has waited ``max_wait_s`` (the same knobs serve-bench's open-loop
    planner models on a virtual clock).
    """

    def __init__(self, engine: PolicyEngine, max_batch=None, max_wait_s=0.002):
        if getattr(engine, "is_recurrent", False):
            # A hidden-state policy served through the stateless full-batch
            # queue would silently act from a zero carry every slot — a
            # DIFFERENT policy. Refuse at construction, loudly, with the
            # fix: the session-carrying continuous batcher.
            raise ValueError(
                "recurrent bundle cannot serve through MicroBatchQueue "
                "(sessions are disabled on the stateless full-batch path) "
                "— serve it through serve.continuous.ContinuousBatcher "
                "with sessions enabled (gateway: batching='continuous')"
            )
        self.engine = engine
        self.max_batch = min(max_batch or engine.max_batch, engine.max_batch)
        self.max_wait_s = max_wait_s
        self._pending: list = []  # (obs_row, Future)
        self._cv = threading.Condition()
        self._closed = False
        # Bounded window of recent enqueue->dispatch waits, as
        # (monotonic dispatch instant, wait ms) — the admission-control
        # signal the serve gateway sheds on. Timestamped so readers can
        # age samples out: only dispatches refresh this window, and a
        # gateway shedding on a stale p95 would otherwise never admit the
        # traffic that could refresh it (permanent shed). A deque, not the
        # telemetry histogram: histograms grow unbounded over a
        # long-running server and may not be attached at all.
        self.recent_wait_ms: deque = deque(maxlen=512)
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    @property
    def depth(self) -> int:
        """Requests queued but not yet dispatched (admission signal)."""
        with self._cv:
            return len(self._pending)

    def submit(self, obs_row, household=None, trace=None, request_id=None) -> Future:
        # ``household`` is accepted (and ignored) so the gateway submits
        # through one interface: the continuous batcher uses it for slot
        # affinity; the stateless microbatch path has no sessions to pin.
        # ``trace`` (a TraceContext or None) and ``request_id`` ride the
        # pending tuple so _trace can stitch queue-wait/execute spans and
        # id-joinable serve_request events without a side lookup.
        del household
        # host-sync: caller-supplied host observation row.
        obs_row = np.asarray(obs_row, dtype=np.float32)
        fut: Future = Future()
        with self._cv:
            if self._closed:
                raise RuntimeError("queue is closed")
            self._pending.append(
                (obs_row, fut, time.monotonic(), trace, request_id, time.time())
            )
            self._cv.notify()
        return fut

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if not self._pending and self._closed:
                    return
                # Window anchored at the OLDEST request's enqueue time, not
                # this wake: a backlog that piled up while the engine was
                # busy has already out-waited the window and dispatches
                # immediately — the dispatch model plan_open_loop replays.
                deadline = self._pending[0][2] + self.max_wait_s
                while len(self._pending) < self.max_batch and not self._closed:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(timeout=remaining)
                batch = self._pending[: self.max_batch]
                del self._pending[: self.max_batch]
            try:
                dispatch_t = time.monotonic()
                dispatch_epoch = time.time()
                for entry in batch:
                    self.recent_wait_ms.append(
                        (dispatch_t, (dispatch_t - entry[2]) * 1e3)
                    )
                out = self.engine.act(np.stack([entry[0] for entry in batch]))
                service_s = time.monotonic() - dispatch_t
                for i, (_, fut, *_rest) in enumerate(batch):
                    # A caller may have given up mid-batch (the gateway's
                    # request timeout cancels through wrap_future);
                    # delivering to a cancelled future raises and must not
                    # abort delivery to the batch's OTHER waiters.
                    if fut.cancelled():
                        continue
                    try:
                        # host-sync: result delivery to the waiting future.
                        fut.set_result(np.asarray(out[i]))
                    except InvalidStateError:
                        pass  # cancelled between the check and delivery
            except Exception as err:  # noqa: BLE001 — fail the waiters, not the loop
                for _, fut, *_rest in batch:
                    if not fut.done():
                        try:
                            fut.set_exception(err)
                        except InvalidStateError:
                            pass  # lost a cancellation race
                continue
            try:
                # AFTER result delivery, and fenced off: a sink hiccup (a
                # locked warehouse DB, full disk) must not fail waiters whose
                # inference succeeded, nor stall the next dispatch's results.
                self._trace(batch, dispatch_t, service_s, dispatch_epoch)
            except Exception:  # noqa: BLE001 — telemetry is best-effort
                pass

    def _trace(
        self, batch, dispatch_t: float, service_s: float,
        dispatch_epoch: float = 0.0,
    ) -> None:
        """Per-request trace records through the engine's telemetry: the
        enqueue->dispatch coalescing wait, the bucket the batch padded to,
        and the shared batch-service span — the queueing story serve-bench
        models on a virtual clock, measured live here.

        Traced requests additionally get real spans: a per-request
        ``queue.wait`` and ``engine.execute`` pair, plus ONE ``engine.step``
        span under the first traced request's context that fans in the whole
        coalesced dispatch (``linked`` = how many traced requests shared it)
        and a synthetic ``engine.pad`` span attributing the padded-lane share
        of the batch's service time."""
        from p2pmicrogrid_tpu.telemetry.tracing import record_span

        tel = self.engine.telemetry
        if tel is None:
            return
        n = len(batch)
        bucket = self.engine.bucket_for(n)
        padded = bucket - n
        traced = [e for e in batch if len(e) >= 6 and e[3] is not None]
        for row_i, entry in enumerate(batch):
            t_enq = entry[2]
            request_id = entry[4] if len(entry) >= 6 else None
            wait_ms = (dispatch_t - t_enq) * 1e3
            tel.histogram("serve.queue_wait_ms", wait_ms)
            tel.event(
                "serve_request",
                source="queue",
                row=row_i,
                batch_size=n,
                bucket=bucket,
                padded_rows=padded,
                wait_ms=round(wait_ms, 3),
                service_ms=round(service_s * 1e3, 3),
                latency_ms=round(wait_ms + service_s * 1e3, 3),
                request_id=request_id,
            )
        if not traced:
            return
        for entry in traced:
            ctx, t_enq_epoch = entry[3], entry[5]
            wait_s = max(0.0, dispatch_epoch - t_enq_epoch)
            record_span(
                tel, ctx.child("queue.wait"), "queue.wait",
                t_enq_epoch, wait_s, batch_size=n,
            )
            record_span(
                tel, ctx.child("engine.execute"), "engine.execute",
                dispatch_epoch, service_s,
                bucket=bucket, batch_size=n, padded_rows=padded,
            )
        first_ctx = traced[0][3]
        record_span(
            tel, first_ctx.child("engine.step"), "engine.step",
            dispatch_epoch, service_s,
            bucket=bucket, batch_size=n, linked=len(traced),
        )
        if padded > 0:
            record_span(
                tel, first_ctx.child("engine.pad"), "engine.pad",
                dispatch_epoch, service_s * padded / bucket,
                bucket=bucket, padded_rows=padded, estimated=True,
            )

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "MicroBatchQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
