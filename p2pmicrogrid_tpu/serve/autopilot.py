"""Autopilot: crash-safe unattended continual-deployment cycles.

The operator-less half of ROADMAP item 5. PR 10 built the flywheel's
machinery — trace export (data/trace_export.py), continual fine-tuning
(train/continual.py), the gated promotion + canary rails
(serve/promotion.py) — but an operator still typed ``continual`` then
``promote``, against one in-process gateway. This module is the
supervisor that runs the WHOLE cycle on a cadence against a live
multi-replica fleet, and survives its own death:

* **One cycle** = export → retrain → gate → canary → promote/abort,
  driven over a real ``FleetRouter`` front: candidates reach already-
  running replicas through ``POST /admin/register`` (``router.
  register_fleet``), canary splits push fleet-wide, and the 100% stage
  is ``router.swap_fleet`` — the two-phase zero-drop swap.

* **Crash-safe cycle state.** Every phase transition lands in a journal
  file first (``write_journal``: write-temp → fsync → digest → atomic
  rename — the same integrity contract as ``train/checkpoint.py``),
  recording the phase (exporting / retraining / gating / canarying /
  promoted / aborted), the incumbent and candidate config hashes and the
  cumulative safety counters. A SIGKILL at ANY instant leaves a journal
  a relaunched autopilot recovers from (``Autopilot.recover``): phases
  before traffic exposure (exporting/retraining/gating) re-run the same
  cycle from the top — they are idempotent — while a kill mid-CANARY
  aborts back to the incumbent: split cleared fleet-wide, pins cleared,
  the candidate unregistered, and the fleet default verified to be the
  incumbent before the next cycle starts. The fleet is never left
  half-ramped and an orphaned candidate never keeps serving traffic.

* **Export/retention handshake.** Each cycle takes an export LEASE in
  the warehouse (``data/results.acquire_export_lease``) naming its
  window start (the previous cycle's released watermark);
  ``compact_serve_telemetry`` caps its cutoff at active leases, so the
  retention pass and the export coordinate by schedule instead of racing
  by convention. The ``TracesCompactedError`` contract stays as the loud
  backstop for a FORCED race (expired lease, operator override).

* **Metered-reward settlement.** Before exporting, the cycle bills the
  window's decisions (``data/trace_export.bill_decisions`` — the meter
  stand-in a production deployment replaces) and attributes training
  reward from the billed rows via ``settlement_reward_fn`` — with its
  loud fallback to the env tariff model when rows are missing.

* **Lineage.** Every promotion appends an (incumbent → candidate) link
  to the journal and the warehouse (``promotion`` events), so
  ``telemetry-query --promotions`` renders the ancestry chain a week of
  unattended cycles produced: incumbent → candidate → candidate².

``autopilot_bench`` is the committed-capture harness
(``AUTOPILOT_*.jsonl``): N unattended cycles over a real 3-replica
``ProcessFleet`` with a replica SIGKILL mid-run (chaos), injected bad
candidates (cost-regressed, NaN-poisoned) that must never promote, at
least one honest promotion, availability 1.0 throughout, and a mid-cycle
SIGKILL of the autopilot process itself that recovers cleanly.

Host-sync note: this module is on the serving hot-path list
(tools/check_host_sync.py); it runs on the host by construction — every
array it touches is wire/warehouse JSON.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import json
import os
import sqlite3
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

CYCLE_PHASES = (
    "idle", "exporting", "retraining", "gating", "canarying",
    "promoted", "aborted",
)
# Phases with NO candidate traffic exposure: a crash here re-runs the
# cycle (idempotent); a crash in 'canarying' must abort to the incumbent.
_RERUNNABLE_PHASES = ("exporting", "retraining", "gating")

JOURNAL_NAME = "cycle_journal.json"
JOURNAL_KIND = "autopilot_journal"
JOURNAL_FORMAT_VERSION = 1


class JournalCorrupt(RuntimeError):
    """The cycle journal failed its digest/shape verification."""


# -- crash-safe journal --------------------------------------------------------


@dataclass
class AutopilotState:
    """The durable cycle state (one journal file, rewritten atomically)."""

    cycle: int = 0
    phase: str = "idle"
    incumbent_dir: Optional[str] = None
    incumbent_hash: Optional[str] = None
    candidate_dir: Optional[str] = None
    candidate_hash: Optional[str] = None
    inject_kind: Optional[str] = None
    window_start_ts: float = 0.0
    lease_id: Optional[str] = None
    # Cumulative safety ledger (survives restarts — the headline numbers).
    promotions: int = 0
    blocked: int = 0
    rollbacks: int = 0
    crash_aborts: int = 0
    bad_promotions: int = 0
    n_requests: int = 0
    n_ok: int = 0
    n_shed: int = 0
    lineage: List[dict] = field(default_factory=list)
    last_error: Optional[str] = None
    updated_ts: float = 0.0

    @property
    def availability(self) -> float:
        admitted = self.n_requests - self.n_shed
        return self.n_ok / admitted if admitted else 1.0

    def to_doc(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_doc(cls, doc: dict) -> "AutopilotState":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in doc.items() if k in fields})


def _state_digest(doc: dict) -> str:
    payload = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return f"sha256:{hashlib.sha256(payload.encode()).hexdigest()}"


def journal_path(state_dir: str) -> str:
    return os.path.join(state_dir, JOURNAL_NAME)


def write_journal(state_dir: str, state: AutopilotState) -> str:
    """Persist the cycle state with the checkpoint integrity contract:
    write to a same-directory temp file, fsync, verify the digest reads
    back, atomically rename over the journal, fsync the directory. A
    SIGKILL before the rename leaves the previous journal intact; after
    it, the new one — never a torn file."""
    from p2pmicrogrid_tpu.train.checkpoint import _fsync_dir, _fsync_file

    os.makedirs(state_dir, exist_ok=True)
    state.updated_ts = time.time()
    doc = state.to_doc()
    record = {
        "kind": JOURNAL_KIND,
        "format_version": JOURNAL_FORMAT_VERSION,
        "digest": _state_digest(doc),
        "state": doc,
    }
    path = journal_path(state_dir)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    # Digest read-back before the rename: a torn/bit-flipped temp must
    # never replace a good journal.
    with open(tmp) as f:
        back = json.load(f)
    if back.get("digest") != _state_digest(back.get("state", {})):
        os.unlink(tmp)
        raise JournalCorrupt(f"{tmp}: digest mismatch on read-back")
    os.replace(tmp, path)
    _fsync_file(path)
    _fsync_dir(state_dir)
    return path


def read_journal(state_dir: str) -> Optional[AutopilotState]:
    """The verified journal state, or None when none exists. Raises
    ``JournalCorrupt`` on a journal that exists but fails verification —
    loud, because silently starting a fresh cycle over a fleet whose
    real state is unknown is exactly the failure the journal prevents."""
    path = journal_path(state_dir)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError) as err:
        raise JournalCorrupt(f"{path}: unreadable ({err})") from None
    if record.get("kind") != JOURNAL_KIND:
        raise JournalCorrupt(f"{path}: not an autopilot journal")
    doc = record.get("state")
    if not isinstance(doc, dict):
        raise JournalCorrupt(f"{path}: missing state")
    if record.get("digest") != _state_digest(doc):
        raise JournalCorrupt(f"{path}: digest mismatch")
    state = AutopilotState.from_doc(doc)
    if state.phase not in CYCLE_PHASES:
        raise JournalCorrupt(f"{path}: unknown phase {state.phase!r}")
    return state


# -- the supervisor ------------------------------------------------------------


class _FleetRoutingView:
    """Duck-types the two registry attributes ``CanaryController``
    consults when every routing mutation is delegated to fleet-wide
    hooks: the locally-tracked default hash and split."""

    def __init__(self, default_hash: Optional[str]):
        self.default_hash = default_hash
        self.split = None


class Autopilot:
    """The unattended retrain→gate→canary supervisor over a live fleet.

    ``router`` is a ``FleetRouter`` over the serving replicas (the
    autopilot holds the operator token when the fleet enforces auth).
    ``traffic_fn(cycle, n_requests, seed) -> FleetLoadgenResult``
    overrides the baseline traffic source (default: the open-loop
    Poisson loadgen through the router — a production deployment has
    ambient traffic instead). ``hold_s`` maps phase -> seconds slept
    right after that phase's journal write: the deterministic kill
    window the crash tests (and nothing else) use.
    """

    def __init__(
        self,
        cfg,
        router,
        incumbent_dir: str,
        state_dir: str,
        results_db: str,
        telemetry=None,
        gate_budgets=None,
        canary_budgets=None,
        stages: Sequence[float] = (25.0, 100.0),
        requests_per_cycle: int = 128,
        canary_requests: int = 64,
        n_households: int = 16,
        rate_hz: float = 64.0,
        seed: int = 0,
        trace_steps: int = 50,
        sim_episodes: int = 0,
        settlement: bool = True,
        min_transitions: int = 8,
        lease_ttl_s: float = 600.0,
        max_batch: int = 16,
        s_eval: int = 4,
        emit: Optional[Callable[[dict], None]] = None,
        traffic_fn: Optional[Callable] = None,
        hold_s: Optional[Dict[str, float]] = None,
        verify_serving: bool = True,
        serve_device: str = "cpu",
    ):
        from p2pmicrogrid_tpu.serve.promotion import (
            CanaryBudgets,
            GateBudgets,
        )

        self.cfg = cfg
        self.router = router
        self.state_dir = state_dir
        self.results_db = results_db
        self.telemetry = telemetry
        self.gate_budgets = gate_budgets or GateBudgets()
        self.canary_budgets = canary_budgets or CanaryBudgets()
        self.stages = tuple(stages)
        self.requests_per_cycle = requests_per_cycle
        self.canary_requests = canary_requests
        self.n_households = n_households
        self.rate_hz = rate_hz
        self.seed = seed
        self.trace_steps = trace_steps
        self.sim_episodes = sim_episodes
        self.settlement = settlement
        self.min_transitions = min_transitions
        self.lease_ttl_s = lease_ttl_s
        self.max_batch = max_batch
        self.s_eval = s_eval
        self.emit = emit
        self.traffic_fn = traffic_fn
        self.hold_s = dict(hold_s or {})
        self.verify_serving = verify_serving
        # The gate/verify reference engines must run on the SAME backend
        # the fleet serves from, or the bit-exact serving check fails on
        # honest float differences ("cpu" matches the committed CPU
        # captures; --no-verify-serving is the mixed-backend escape).
        self.serve_device = serve_device
        self._incumbent_eval_cache: Dict[str, tuple] = {}

        state = read_journal(state_dir)
        if state is None:
            from p2pmicrogrid_tpu.serve.export import load_policy_bundle

            manifest, _ = load_policy_bundle(incumbent_dir)
            state = AutopilotState(
                incumbent_dir=os.path.abspath(incumbent_dir),
                incumbent_hash=manifest.get("config_hash"),
            )
            write_journal(state_dir, state)
        self.state = state
        # A relaunched autopilot starts with a FRESH router whose
        # known_bundles map is empty — seed it from the journal so the
        # prober can still re-register the (possibly runtime-promoted)
        # incumbent on a replica that relaunches later. Without this, a
        # post-restart replica crash would resurrect its launch-time
        # bundle forever (_push_swap's 404 path has nothing to register).
        if state.incumbent_hash and state.incumbent_dir and hasattr(
            router, "known_bundles"
        ):
            router.known_bundles.setdefault(
                state.incumbent_hash, state.incumbent_dir
            )

    # -- plumbing ------------------------------------------------------------

    def _journal(self, phase: str, **updates) -> None:
        st = self.state
        st.phase = phase
        for k, v in updates.items():
            setattr(st, k, v)
        write_journal(self.state_dir, st)
        if self.telemetry is not None:
            self.telemetry.event(
                "autopilot_phase",
                cycle=st.cycle,
                phase=phase,
                incumbent=st.incumbent_hash,
                candidate=st.candidate_hash,
            )
            # The audit trail must survive the autopilot's own SIGKILL:
            # buffered warehouse rows (gate verdicts, PROMOTED lineage
            # events) die with the process unless flushed at every
            # journaled transition — and a cycle the journal says
            # happened must be visible to `telemetry-query --promotions`.
            try:
                self.telemetry.flush()
            except Exception:  # noqa: BLE001 — telemetry is best-effort
                pass
        hold = self.hold_s.get(phase, 0.0)
        if hold > 0:
            time.sleep(hold)

    def _log(self, msg: str) -> None:
        print(f"autopilot: {msg}", file=sys.stderr, flush=True)

    def _run_async(self, coro):
        return asyncio.run(coro)

    def _record_traffic(self, result) -> None:
        st = self.state
        st.n_requests += result.n_requests
        st.n_ok += result.n_ok
        st.n_shed += result.n_shed

    def _drive_traffic(self, cycle: int, n_requests: int, seed: int):
        """Open-loop traffic through the router (baseline decisions for
        the next export + the canary stage driver's engine)."""
        from p2pmicrogrid_tpu.serve.loadgen import (
            poisson_arrivals,
            synthetic_obs,
        )
        from p2pmicrogrid_tpu.serve.router import run_fleet_loadgen

        if self.traffic_fn is not None:
            return self.traffic_fn(cycle, n_requests, seed)
        obs = synthetic_obs(n_requests, self.cfg.sim.n_agents, seed=seed)
        arrivals = poisson_arrivals(self.rate_hz, n_requests, seed=seed)
        households = [f"house-{i:04d}" for i in range(self.n_households)]
        return run_fleet_loadgen(self.router, obs, arrivals, households)

    def _con(self) -> sqlite3.Connection:
        con = sqlite3.connect(self.results_db)
        return con

    # -- recovery ------------------------------------------------------------

    def recover(self) -> Optional[str]:
        """Reconcile a relaunched autopilot with the journal (module
        docstring). Returns a human-readable description of what recovery
        did, or None when the journal was already at rest."""
        st = self.state
        if st.phase in ("idle", "promoted", "aborted"):
            if st.phase in ("promoted", "aborted"):
                st.cycle += 1
                self._journal("idle")
            return None
        if st.phase in _RERUNNABLE_PHASES:
            # No candidate traffic was exposed; the cycle re-runs from the
            # top. Defensive routing reset anyway — register/split pushes
            # may have partially landed right at the kill instant.
            action = (
                f"crash during {st.phase} (cycle {st.cycle}): re-running "
                "the cycle"
            )
            self._reset_fleet_routing(unregister_candidate=True)
            self._journal("idle", last_error=action)
            return action
        # canarying: the candidate may be taking live traffic RIGHT NOW.
        action = (
            f"crash during canary (cycle {st.cycle}): aborting back to "
            f"incumbent {st.incumbent_hash}"
        )
        self._reset_fleet_routing(unregister_candidate=True)
        st.crash_aborts += 1
        st.cycle += 1
        self._journal(
            "idle",
            candidate_dir=None,
            candidate_hash=None,
            last_error=action,
        )
        return action

    def _reset_fleet_routing(self, unregister_candidate: bool) -> None:
        """Clear any split + pins fleet-wide, verify the incumbent is the
        serving default (two-phase swap back when it is not), and drop an
        orphaned candidate registration."""
        st = self.state
        self._run_async(self.router.clear_split_fleet())
        if st.incumbent_hash:
            try:
                self._run_async(
                    self.router.swap_fleet(st.incumbent_hash)
                )
            except Exception as err:  # noqa: BLE001 — recovery is best-
                # effort per step; the serving check below is the verdict
                self._log(f"recovery swap_fleet: {err}")
        if unregister_candidate and st.candidate_hash and (
            st.candidate_hash != st.incumbent_hash
        ):
            self._run_async(
                self.router.unregister_fleet(st.candidate_hash)
            )

    # -- one cycle -----------------------------------------------------------

    def run_cycle(self, inject_kind: Optional[str] = None) -> dict:
        """One full unattended cycle; returns the ``autopilot_cycle`` row."""
        st = self.state
        cycle = st.cycle
        cycle_dir = os.path.join(self.state_dir, f"cycle-{cycle:04d}")
        os.makedirs(cycle_dir, exist_ok=True)
        t0 = time.time()
        row: dict = {
            "metric": "autopilot_cycle",
            "value": float(cycle),
            "unit": "cycle",
            "cycle": cycle,
            "inject": inject_kind,
            "incumbent": st.incumbent_hash,
        }

        # Phase 1: export (leased window, settlement-billed rewards).
        self._journal(
            "exporting", inject_kind=inject_kind,
            candidate_dir=None, candidate_hash=None,
        )
        traffic = self._drive_traffic(
            cycle, self.requests_per_cycle, seed=self.seed + 977 * cycle
        )
        self._record_traffic(traffic)
        self._run_async(self.router.flush_fleet())
        dataset = self._export_window(cycle, row)

        # Phase 2: retrain (or inject a crafted candidate).
        self._journal("retraining")
        cand_dir, cand_hash = self._make_candidate(
            cycle, cycle_dir, dataset, inject_kind
        )
        row["candidate"] = cand_hash

        # Phase 3: offline gate.
        self._journal(
            "gating", candidate_dir=cand_dir, candidate_hash=cand_hash
        )
        verdict = self._gate(cand_dir)
        row["gate_verdict"] = verdict.verdict
        row["gate"] = verdict.to_fields()
        if not verdict.passed:
            st.blocked += 1
            self._finish_cycle(
                row, promoted=False, blocked=True, rolled_back=False,
                seconds=time.time() - t0,
            )
            return row

        # Phase 4: live canary over the fleet.
        self._journal("canarying")
        result = self._canary(cycle, cand_dir, cand_hash)
        promoted = result.promoted and not result.rolled_back
        row["canary_stages"] = [s.to_fields() for s in result.stages]
        row["aborted_stage"] = result.aborted_stage
        row["abort_reasons"] = result.reasons
        if promoted:
            st.promotions += 1
            if inject_kind in ("cost_regressed", "nan_poisoned"):
                st.bad_promotions += 1
            st.lineage.append({
                "cycle": cycle,
                "incumbent": st.incumbent_hash,
                "candidate": cand_hash,
                "ts": round(time.time(), 3),
            })
            old_incumbent = st.incumbent_hash
            st.incumbent_dir, st.incumbent_hash = cand_dir, cand_hash
        else:
            old_incumbent = None
            st.rollbacks += 1 if result.rolled_back else 0
            self._run_async(self.router.unregister_fleet(cand_hash))
        self._finish_cycle(
            row, promoted=promoted, blocked=False,
            rolled_back=result.rolled_back, seconds=time.time() - t0,
        )
        if promoted and old_incumbent and old_incumbent != cand_hash:
            # The retired incumbent must not stay registered forever on
            # every replica (a week of cycles would accrete bundles) —
            # but it IS the rollback target until the promotion is
            # JOURNALED: unregistering first would strand a SIGKILL in
            # that window with a journal still naming an incumbent no
            # replica knows (recovery's swap-back would 404 everywhere).
            # After the journal records the new incumbent, dropping the
            # old one is pure cleanup; a crash here merely leaks one
            # stale registration until the replica's next relaunch.
            self._run_async(self.router.unregister_fleet(old_incumbent))
        return row

    def _export_window(self, cycle: int, row: dict):
        from p2pmicrogrid_tpu.data.results import (
            ExportLeaseScope,
            last_export_watermark,
        )
        from p2pmicrogrid_tpu.data.trace_export import (
            bill_decisions,
            export_serve_traces,
            settlement_reward_fn,
        )

        st = self.state
        con = self._con()
        try:
            watermark = last_export_watermark(con, st.incumbent_hash)
        finally:
            con.close()
        if watermark is None:
            # A freshly-promoted incumbent has no export history: its
            # window starts at the PROMOTION instant (the lineage
            # link), not at 0 — which keeps since_ts set, so aggregates
            # from the previous incumbent's era read as scheduled
            # history rather than condemning the export.
            watermark = next(
                (
                    link["ts"] for link in reversed(st.lineage)
                    if link["candidate"] == st.incumbent_hash
                ),
                None,
            )
        window_start = watermark if watermark is not None else 0.0
        # A failed export CANCELS the lease on scope exit (retention is
        # not gated for the TTL); a SIGKILL leaves it to expire.
        with ExportLeaseScope(
            self.results_db,
            holder=f"autopilot-cycle-{cycle}",
            window_start_ts=window_start,
            ttl_s=self.lease_ttl_s,
            config_hash=st.incumbent_hash,
        ) as scope:
            st.window_start_ts = window_start
            st.lease_id = scope.lease_id
            write_journal(self.state_dir, st)
            billed = 0
            reward_fn = None
            if self.settlement:
                billed = bill_decisions(
                    self.results_db, self.cfg,
                    config_hash=st.incumbent_hash,
                    since_ts=window_start or None,
                )
                reward_fn = settlement_reward_fn(
                    self.results_db, self.cfg, telemetry=self.telemetry
                )
            dataset = export_serve_traces(
                self.results_db,
                config_hash=st.incumbent_hash,
                cfg=self.cfg,
                reward_fn=reward_fn,
                min_transitions=self.min_transitions,
                since_ts=window_start or None,
            )
            exported_through = dataset.window_end_ts or time.time()
            scope.release(exported_through)
        st.lease_id = None
        row["trace_transitions"] = dataset.n_transitions
        row["settlement_billed"] = billed
        row["window_start_ts"] = round(window_start, 3)
        row["window_end_ts"] = round(exported_through, 3)
        self._log(
            f"cycle {cycle}: exported {dataset.n_transitions} transitions "
            f"({billed} billed) from window >= {window_start:.3f}"
        )
        return dataset

    def _make_candidate(self, cycle, cycle_dir, dataset, inject_kind):
        from p2pmicrogrid_tpu.serve.promotion import make_crafted_bundle
        from p2pmicrogrid_tpu.telemetry import config_hash as cfg_hash
        from p2pmicrogrid_tpu.train.continual import train_continual

        out_dir = os.path.join(cycle_dir, "candidate")
        if inject_kind:
            # Injected candidate (the harness's regression source): a
            # crafted closed-form bundle under a cycle-distinct hash.
            cand_cfg = self.cfg.replace(
                train=dataclasses.replace(
                    self.cfg.train,
                    starting_episodes=(
                        self.cfg.train.starting_episodes + 1000 + cycle
                    ),
                )
            )
            make_crafted_bundle(cand_cfg, inject_kind, out_dir)
            return out_dir, cfg_hash(cand_cfg)
        result = train_continual(
            self.cfg,
            self.state.incumbent_dir,
            dataset,
            out_dir,
            os.path.join(cycle_dir, "ckpt"),
            n_episodes=self.sim_episodes,
            trace_steps=self.trace_steps,
            telemetry=self.telemetry,
            s_eval=self.s_eval,
        )
        return result.candidate_dir, result.candidate_hash

    def _gate(self, cand_dir: str):
        from p2pmicrogrid_tpu.serve.promotion import (
            evaluate_bundle_cost,
            run_promotion_gate,
        )

        st = self.state
        cached = self._incumbent_eval_cache.get(st.incumbent_hash)
        if cached is None:
            cached = evaluate_bundle_cost(
                self.cfg, st.incumbent_dir, s_eval=self.s_eval
            )
            self._incumbent_eval_cache[st.incumbent_hash] = cached
        return run_promotion_gate(
            self.cfg,
            cand_dir,
            st.incumbent_dir,
            budgets=self.gate_budgets,
            telemetry=self.telemetry,
            s_eval=self.s_eval,
            bench_requests=64,
            bench_seed=self.seed,
            max_batch=self.max_batch,
            device=self.serve_device,
            incumbent_eval=cached,
        )

    def _canary(self, cycle: int, cand_dir: str, cand_hash: str):
        from p2pmicrogrid_tpu.serve.promotion import (
            CanaryController,
            StageTraffic,
        )

        st = self.state
        router = self.router
        self._run_async(router.register_fleet(cand_dir))
        view = _FleetRoutingView(st.incumbent_hash)

        def swap_fn(config_hash: str) -> None:
            self._run_async(router.swap_fleet(config_hash))
            view.default_hash = config_hash

        def split_fn(config_hash: str, percent: float) -> None:
            self._run_async(router.split_fleet(config_hash, percent))
            view.split = (config_hash, percent)

        def clear_split_fn() -> None:
            self._run_async(router.clear_split_fleet())
            view.split = None

        def clear_pins_fn() -> None:
            self._run_async(router.clear_pins_fleet())

        def flush_fn() -> None:
            self._run_async(router.flush_fleet())

        def drive_stage(plan) -> StageTraffic:
            result = self._drive_traffic(
                cycle,
                self.canary_requests,
                seed=self.seed + 7919 * cycle + 31 * (plan.index + 1),
            )
            self._record_traffic(result)
            households = [
                f"house-{i:04d}" for i in range(self.n_households)
            ]
            return StageTraffic(
                statuses=result.statuses,
                latencies_ms=result.latencies_s * 1e3,
                config_hashes=result.config_hashes,
                actions=result.actions,
                households=[
                    households[i % len(households)]
                    for i in range(result.n_requests)
                ],
                n_shed=result.n_shed,
            )

        controller = CanaryController(
            view,
            candidate_hash=cand_hash,
            incumbent_hash=st.incumbent_hash,
            cfg=self.cfg,
            stages=self.stages,
            budgets=self.canary_budgets,
            telemetry=self.telemetry,
            results_db=self.results_db,
            flush_fn=flush_fn,
            swap_fn=swap_fn,
            split_fn=split_fn,
            clear_split_fn=clear_split_fn,
            clear_pins_fn=clear_pins_fn,
        )
        return controller.run(drive_stage)

    def _finish_cycle(
        self, row: dict, promoted: bool, blocked: bool, rolled_back: bool,
        seconds: float,
    ) -> None:
        st = self.state
        row.update(
            promoted=promoted,
            blocked_at_gate=blocked,
            rolled_back=rolled_back,
            availability=round(st.availability, 6),
            n_requests=st.n_requests,
            incumbent_after=st.incumbent_hash,
            lineage=[link["candidate"] for link in st.lineage],
            seconds=round(seconds, 3),
        )
        # Safe outcome per injection contract: crafted regressions must
        # never promote; everything else is the gate/canary's call.
        inject = st.inject_kind
        row["outcome_ok"] = not (
            inject in ("cost_regressed", "nan_poisoned") and promoted
        )
        row["vs_baseline"] = 1.0 if row["outcome_ok"] else 0.0
        if self.verify_serving:
            row["serving_verified"] = self._verify_incumbent_serving()
        self._journal("promoted" if promoted else "aborted")
        if self.telemetry is not None:
            self.telemetry.event(
                "autopilot_cycle",
                **{
                    k: v for k, v in row.items()
                    if k not in ("metric", "value", "unit", "gate")
                },
            )
        if self.emit is not None:
            self.emit(row)
        self._log(
            f"cycle {st.cycle}: "
            + ("PROMOTED" if promoted else
               "blocked at gate" if blocked else
               "rolled back" if rolled_back else "aborted")
            + f" (candidate {st.candidate_hash}, availability "
            f"{st.availability:.4f})"
        )

    def _verify_incumbent_serving(self) -> bool:
        """Bit-exact check: the fleet's default answers MUST match a
        direct engine on the journal's incumbent bundle — the post-cycle
        invariant every cycle (and every recovery) re-establishes."""
        from p2pmicrogrid_tpu.serve.engine import PolicyEngine
        from p2pmicrogrid_tpu.serve.loadgen import synthetic_obs
        from p2pmicrogrid_tpu.serve.router import run_fleet_loadgen

        st = self.state
        obs = synthetic_obs(4, self.cfg.sim.n_agents, seed=self.seed + 555)
        arrivals = np.zeros(4)
        result = run_fleet_loadgen(
            self.router, obs, arrivals, ["verify-house"]
        )
        self._record_traffic(result)
        if not (result.statuses == 200).all():
            return False
        if any(h != st.incumbent_hash for h in result.config_hashes):
            return False
        engine = PolicyEngine(
            bundle_dir=st.incumbent_dir, max_batch=self.max_batch,
            device=self.serve_device,
        )
        want = engine.act(obs)
        # host-sync: wire JSON payloads, host data.
        got = np.asarray(result.actions, dtype=np.float32)
        return bool((got == want).all())

    # -- the cadence loop ----------------------------------------------------

    def run(
        self,
        n_cycles: int,
        cadence_s: float = 0.0,
        inject_plan: Optional[Dict[int, str]] = None,
    ) -> AutopilotState:
        """Recover, then run cycles until ``n_cycles`` TOTAL cycles have
        completed (journal-counted — a relaunched autopilot continues
        where the journal left off, which is what makes the SIGKILL
        harness's 'same command line again' recovery work)."""
        inject_plan = inject_plan or {}
        recovery = self.recover()
        if recovery:
            self._log(f"recovered: {recovery}")
            if self.telemetry is not None:
                self.telemetry.event(
                    "autopilot_recovery", detail=recovery,
                    cycle=self.state.cycle,
                )
        while self.state.cycle < n_cycles:
            self.run_cycle(inject_plan.get(self.state.cycle))
            self.state.cycle += 1
            self._journal("idle")
            if cadence_s > 0 and self.state.cycle < n_cycles:
                time.sleep(cadence_s)
        return self.state

    def summary_row(self) -> dict:
        st = self.state
        all_safe = st.bad_promotions == 0
        return {
            # Same metric name as the bench headline: a daemon capture
            # saved under the documented AUTOPILOT_*.jsonl name must pass
            # check_artifacts_schema, which requires an autopilot_bench
            # headline as the last row.
            "metric": "autopilot_bench",
            "value": float(st.cycle),
            "unit": "cycles",
            "vs_baseline": 1.0 if all_safe else 0.0,
            "cycles": st.cycle,
            "promotions": st.promotions,
            "blocked": st.blocked,
            "rollbacks": st.rollbacks,
            "crash_aborts": st.crash_aborts,
            "bad_promotions": st.bad_promotions,
            "availability": round(st.availability, 6),
            "n_requests": st.n_requests,
            "all_safe": all_safe,
            "incumbent": st.incumbent_hash,
            "lineage": [link["candidate"] for link in st.lineage],
        }


def parse_inject_plan(spec: Optional[str]) -> Dict[int, str]:
    """``"1:cost_regressed,2:nan_poisoned"`` -> {1: ..., 2: ...} (the
    ``autopilot --inject`` syntax; ``good`` injects the crafted honest
    improvement, empty/None injects nothing — every cycle retrains)."""
    plan: Dict[int, str] = {}
    if not spec:
        return plan
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        cycle_s, _, kind = part.partition(":")
        kind = kind.strip()
        if kind not in ("good", "cost_regressed", "nan_poisoned", "continual"):
            raise ValueError(
                f"unknown inject kind {kind!r} (good | cost_regressed | "
                "nan_poisoned | continual)"
            )
        plan[int(cycle_s)] = None if kind == "continual" else kind
    return plan


# -- the committed-capture harness ---------------------------------------------


def autopilot_bench(
    cfg,
    work_dir: str,
    n_replicas: int = 3,
    n_cycles: int = 3,
    inject: str = "0:good,1:cost_regressed,2:nan_poisoned",
    seed: int = 0,
    chaos: bool = True,
    chaos_kill_after_s: float = 6.0,
    sigkill_phase: Optional[str] = "retraining",
    sigkill_cycle: int = 1,
    requests_per_cycle: int = 96,
    canary_requests: int = 64,
    n_households: int = 16,
    stages: str = "25,100",
    emit: Optional[Callable[[dict], None]] = None,
    startup_timeout_s: float = 300.0,
    cycle_timeout_s: float = 1200.0,
    extra_cfg_args: Optional[List[str]] = None,
) -> List[dict]:
    """The AUTOPILOT_*.jsonl capture (module docstring): a crafted
    incumbent serves from a real ``ProcessFleet``; the autopilot runs as
    its OWN subprocess (``cli autopilot``) against the fleet; a replica
    is SIGKILLed mid-run (the supervisor relaunches it); the autopilot
    itself is SIGKILLed in ``sigkill_phase`` of ``sigkill_cycle`` (the
    journal poll gives the deterministic window) and relaunched with the
    SAME command line — recovery must finish the remaining cycles.
    Emits the per-cycle rows the autopilot wrote plus the
    ``autopilot_bench`` headline (LAST)."""
    import shutil
    import signal
    import subprocess
    import threading

    from p2pmicrogrid_tpu.serve.procfleet import ProcessFleet
    from p2pmicrogrid_tpu.serve.promotion import make_crafted_bundle

    os.makedirs(work_dir, exist_ok=True)
    results_db = os.path.join(work_dir, "warehouse.db")
    state_dir = os.path.join(work_dir, "autopilot")
    out_path = os.path.join(work_dir, "cycles.jsonl")
    for stale in (results_db, out_path):
        if os.path.exists(stale):
            os.unlink(stale)
    if os.path.isdir(state_dir):
        shutil.rmtree(state_dir)
    incumbent_dir = make_crafted_bundle(
        cfg, "incumbent", os.path.join(work_dir, "incumbent")
    )

    fleet = ProcessFleet(
        [incumbent_dir],
        n_replicas=n_replicas,
        max_batch=16,
        results_db=results_db,
        serve_device="cpu",
        supervise=True,
        startup_timeout_s=startup_timeout_s,
    )
    rows: List[dict] = []
    sigkills = 0
    chaos_kill: List[str] = []
    replicas = fleet.start()
    try:
        argv = [
            sys.executable, "-m", "p2pmicrogrid_tpu.cli", "autopilot",
            "--incumbent", incumbent_dir,
            "--state-dir", state_dir,
            "--results-db", results_db,
            "--cycles", str(n_cycles),
            "--inject", inject,
            "--out", out_path,
            "--requests-per-cycle", str(requests_per_cycle),
            "--canary-requests", str(canary_requests),
            "--households", str(n_households),
            "--stages", stages,
            "--seed", str(seed),
        ] + list(extra_cfg_args or [])
        for rep in replicas:
            spec = f"{rep.host}:{rep.port}"
            if rep.mux_port:
                spec += f"/{rep.mux_port}"
            argv += ["--replica", spec]

        env = dict(os.environ)
        if sigkill_phase:
            # The kill window: the autopilot sleeps right after
            # journaling sigkill_phase, so the poll below always lands.
            env["P2P_AUTOPILOT_HOLD"] = json.dumps({sigkill_phase: 8.0})

        def spawn() -> subprocess.Popen:
            return subprocess.Popen(
                argv, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )

        def pump(proc, sink: List[str]) -> threading.Thread:
            def run():
                for line in proc.stdout:
                    sink.append(line.rstrip("\n"))
            t = threading.Thread(target=run, daemon=True)
            t.start()
            return t

        if chaos:
            victim = replicas[-1].replica_id

            def chaos_run():
                time.sleep(chaos_kill_after_s)
                fleet.kill(victim)
                chaos_kill.append(victim)

            threading.Thread(target=chaos_run, daemon=True).start()

        proc = spawn()
        log: List[str] = []
        pump(proc, log)
        recovered = True
        if sigkill_phase:
            # Poll the journal for the kill window.
            end = time.monotonic() + cycle_timeout_s
            killed = False
            while time.monotonic() < end and proc.poll() is None:
                try:
                    st = read_journal(state_dir)
                except JournalCorrupt:
                    st = None
                if (
                    st is not None
                    and st.cycle == sigkill_cycle
                    and st.phase == sigkill_phase
                ):
                    proc.send_signal(signal.SIGKILL)
                    proc.wait(timeout=30)
                    sigkills += 1
                    killed = True
                    break
                time.sleep(0.2)
            if killed:
                # Same command line again: the journal drives recovery.
                proc = spawn()
                pump(proc, log)
            else:
                recovered = False  # window never opened — report it
        rc = proc.wait(timeout=cycle_timeout_s)
        if rc != 0:
            tail = "\n".join(log[-30:])
            raise RuntimeError(
                f"autopilot exited rc={rc}; log tail:\n{tail}"
            )

        child_rows: List[dict] = []
        with open(out_path) as f:
            for line in f:
                line = line.strip()
                if line:
                    child_rows.append(json.loads(line))
        # Re-emit only the per-cycle rows: the child's own summary
        # headline would duplicate (and misplace) the bench headline
        # appended below.
        cycles = [
            r for r in child_rows if r.get("metric") == "autopilot_cycle"
        ]
        rows.extend(cycles)
        final = read_journal(state_dir)
        promotions = final.promotions
        all_safe = final.bad_promotions == 0 and all(
            r.get("outcome_ok", False) for r in cycles
        )
        serving_ok = all(
            r.get("serving_verified") in (True, None) for r in cycles
        )
        rows.append({
            "metric": "autopilot_bench",
            "value": float(final.cycle),
            "unit": "cycles",
            "vs_baseline": 1.0 if (all_safe and promotions >= 1) else 0.0,
            "cycles": final.cycle,
            "promotions": promotions,
            "blocked": final.blocked,
            "rollbacks": final.rollbacks,
            "crash_aborts": final.crash_aborts,
            "bad_promotions": final.bad_promotions,
            "availability": round(final.availability, 6),
            "n_requests": final.n_requests,
            "all_safe": bool(all_safe),
            "serving_verified": bool(serving_ok),
            "autopilot_sigkills": sigkills,
            "autopilot_recovered": bool(recovered and sigkills > 0),
            "lineage": [link["candidate"] for link in final.lineage],
            "incumbent_after": final.incumbent_hash,
            "n_replicas": n_replicas,
            "process_mode": True,
            "chaos": {
                "enabled": chaos,
                "kills": list(fleet.kills),
                "restarts": list(fleet.restarts),
            },
            "inject": inject,
            "seed": seed,
            "journal": os.path.abspath(journal_path(state_dir)),
        })
    finally:
        fleet.stop_all()
    if emit is not None:
        for row in rows:
            emit(row)
    return rows
