"""Deterministic fault injection for the serve fleet.

Chaos testing is only trustworthy when a failing run can be REPLAYED: a
flaky injected fault that appears in one run and not the next turns every
fleet regression into an unreproducible heisenbug. This module makes the
whole fault surface a pure function of a seed:

* ``FaultPlan`` — a seed plus an ordered tuple of ``FaultEvent``s. Two
  kinds of event:

  - **lifecycle** (``kill`` / ``restart``): replica-process faults applied
    at a scheduled instant by a ``FaultSchedule`` driving a fleet's
    ``kill``/``restart`` hooks (serve/router.py ``LocalFleet``).
  - **request** (``stall`` / ``error`` / ``drop`` / ``corrupt``): per-
    request faults decided by a ``FaultInjector`` hooked into
    ``ServeGateway`` — stall the response ``stall_s`` seconds, answer an
    injected 500, drop the connection without answering, or corrupt the
    response payload (always DETECTABLY: the corruption breaks JSON
    parsing, so a client can never mistake a corrupted answer for a real
    one — silent wrong-answer faults would poison the fleet bench's
    bit-exactness acceptance check).

* **Determinism.** Every request-fault coin is
  ``sha256(seed : replica : event-index : request-index)`` mapped to
  [0, 1) and compared against the event's ``rate`` — no RNG state, no
  wall-clock in the coin. The request index counts per SCOPE (act /
  health / other), so the router's timing-driven health probes can never
  shift the coins of act-scope faults: given the same plan and the same
  per-replica order of requests *within a scope*, the injected fault
  sequence for that scope is bit-identical across runs.
  ``FaultInjector.history`` records it for replay assertions
  (tests/test_fleet.py).

* **Windows.** Request events apply while ``at_s <= t < until_s`` on the
  injector's clock (anchored by ``activate(t0)`` — the fleet bench
  activates every replica's injector at the loadgen start instant, so a
  plan's windows line up across the fleet). Events with the default
  window (0, inf) are always active, which keeps the determinism tests
  independent of timing.

JSON round-trip (``FaultPlan.to_json``/``from_json``) so chaos runs are
shareable as committed artifacts and CLI inputs (``serve-bench --fleet
--chaos-plan plan.json``).
"""

from __future__ import annotations

import hashlib
import json
import math
import threading
import time
from dataclasses import asdict, dataclass
from typing import Callable, Dict, List, Optional, Tuple

LIFECYCLE_KINDS = ("kill", "restart")
REQUEST_KINDS = ("stall", "error", "drop", "corrupt")
SCOPES = ("act", "health", "all")


@dataclass(frozen=True)
class FaultEvent:
    """One fault in a plan.

    ``replica=None`` targets every replica. Lifecycle kinds use ``at_s``
    as the scheduled instant; request kinds use [``at_s``, ``until_s``)
    as the active window (``until_s=None`` = open-ended) and flip a
    deterministic coin against ``rate`` per request. ``scope`` picks the
    endpoints a request fault applies to: ``act`` (``POST /v1/act``),
    ``health`` (``/healthz`` + ``/readyz`` — lets a plan fail probes
    without failing traffic, the health-ejection test fixture), or
    ``all``.
    """

    kind: str
    replica: Optional[str] = None
    at_s: float = 0.0
    until_s: Optional[float] = None
    rate: float = 1.0
    stall_s: float = 0.0
    scope: str = "act"

    def __post_init__(self):
        if self.kind not in LIFECYCLE_KINDS + REQUEST_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.scope not in SCOPES:
            raise ValueError(f"unknown fault scope {self.scope!r}")
        if not 0.0 <= self.rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {self.rate}")
        if self.kind in LIFECYCLE_KINDS and self.replica is None:
            raise ValueError(f"{self.kind} events must name a replica")
        if self.until_s is not None and self.until_s <= self.at_s:
            raise ValueError(
                f"until_s {self.until_s} must exceed at_s {self.at_s}"
            )
        if self.kind == "stall" and self.stall_s <= 0.0:
            raise ValueError("stall events need stall_s > 0")

    def active_at(self, t: float) -> bool:
        until = math.inf if self.until_s is None else self.until_s
        return self.at_s <= t < until


@dataclass(frozen=True)
class FaultPlan:
    """A seed plus an ordered tuple of events — the whole chaos run."""

    seed: int
    events: Tuple[FaultEvent, ...] = ()

    def __post_init__(self):
        # Accept lists for ergonomic literals; store a tuple (hashable,
        # immutable — a plan is an identity, not a mutable builder).
        if not isinstance(self.events, tuple):
            object.__setattr__(self, "events", tuple(self.events))

    # -- views ---------------------------------------------------------------

    def lifecycle_events(self) -> List[FaultEvent]:
        """kill/restart events in schedule order."""
        return sorted(
            (e for e in self.events if e.kind in LIFECYCLE_KINDS),
            key=lambda e: e.at_s,
        )

    def request_events(self) -> List[Tuple[int, FaultEvent]]:
        """(plan index, event) for request-kind events, plan order. The
        plan index — not the position in this filtered list — feeds the
        coin, so editing lifecycle events never shifts request coins."""
        return [
            (i, e)
            for i, e in enumerate(self.events)
            if e.kind in REQUEST_KINDS
        ]

    # -- serialization -------------------------------------------------------

    def to_json(self) -> str:
        return json.dumps(
            {
                "kind": "fault_plan",
                "seed": self.seed,
                "events": [asdict(e) for e in self.events],
            },
            indent=2,
        )

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        doc = json.loads(text)
        if not isinstance(doc, dict) or doc.get("kind") != "fault_plan":
            raise ValueError("not a fault_plan document")
        events = tuple(
            FaultEvent(**{str(k): v for k, v in e.items()})
            for e in doc.get("events", [])
        )
        return cls(seed=int(doc["seed"]), events=events)


def kill_restart_plan(
    replica: str,
    kill_at_s: float,
    restart_at_s: float,
    seed: int = 0,
    extra_events: Tuple[FaultEvent, ...] = (),
) -> FaultPlan:
    """The canonical chaos plan: kill one replica mid-run, restart it
    later (the ``serve-bench --fleet --chaos`` default)."""
    if restart_at_s <= kill_at_s:
        raise ValueError(
            f"restart_at_s {restart_at_s} must exceed kill_at_s {kill_at_s}"
        )
    return FaultPlan(
        seed=seed,
        events=(
            FaultEvent(kind="kill", replica=replica, at_s=kill_at_s),
            FaultEvent(kind="restart", replica=replica, at_s=restart_at_s),
        )
        + tuple(extra_events),
    )


@dataclass(frozen=True)
class FaultDecision:
    """What the injector chose for one request (``None`` = no fault)."""

    kind: str                # one of REQUEST_KINDS
    event_index: int         # plan index of the deciding event
    request_index: int       # per-replica request counter value
    stall_s: float = 0.0


def _coin(seed: int, replica_id: str, event_index: int, n: int) -> float:
    """Deterministic uniform [0, 1) for one (event, request) pair."""
    digest = hashlib.sha256(
        f"{seed}:{replica_id}:{event_index}:{n}".encode()
    ).digest()
    return int.from_bytes(digest[:8], "big") / float(1 << 64)


class FaultInjector:
    """Per-replica request-fault decider (hooked into ``ServeGateway``).

    ``decide(scope)`` is called once per incoming request; the coin is a
    pure function of (plan seed, replica id, event index, request index),
    so the fault sequence replays exactly for a given request order. The
    first matching event in plan order wins — plans encode precedence by
    ordering. Thread-safe: the request counter is the only mutable state.
    """

    def __init__(
        self,
        plan: FaultPlan,
        replica_id: str,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.plan = plan
        self.replica_id = replica_id
        self._clock = clock
        self._t0: Optional[float] = None
        # Per-SCOPE request counters: health probes arrive on their own
        # nondeterministic timer, and a shared counter would let them
        # shift the coin indices of act-scope faults between otherwise
        # identical runs — breaking the replay guarantee for exactly the
        # traffic chaos runs care about.
        self._n: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.history: List[Optional[FaultDecision]] = []
        self.injected: Dict[str, int] = {k: 0 for k in REQUEST_KINDS}

    def activate(self, t0: Optional[float] = None) -> None:
        """Anchor the fault windows' clock (idempotent; the first
        ``decide`` self-activates if never called)."""
        with self._lock:
            if self._t0 is None:
                self._t0 = self._clock() if t0 is None else t0

    def decide(self, scope: str = "act") -> Optional[FaultDecision]:
        with self._lock:
            if self._t0 is None:
                self._t0 = self._clock()
            n = self._n.get(scope, 0)
            self._n[scope] = n + 1
            t = self._clock() - self._t0
            decision = None
            for i, event in self.plan.request_events():
                if event.replica is not None and event.replica != self.replica_id:
                    continue
                if event.scope != "all" and event.scope != scope:
                    continue
                if not event.active_at(t):
                    continue
                if _coin(self.plan.seed, self.replica_id, i, n) < event.rate:
                    decision = FaultDecision(
                        kind=event.kind,
                        event_index=i,
                        request_index=n,
                        stall_s=event.stall_s,
                    )
                    self.injected[event.kind] += 1
                    break
            self.history.append(decision)
            return decision

    def stats(self) -> dict:
        with self._lock:
            return {
                "replica_id": self.replica_id,
                "requests_seen": sum(self._n.values()),
                "requests_by_scope": dict(self._n),
                "injected": dict(self.injected),
            }


class FaultSchedule:
    """Drives a plan's lifecycle (kill/restart) events against a fleet.

    ``kill_fn``/``restart_fn`` take the replica id; the schedule thread
    waits out each event's ``at_s`` relative to ``start()`` and applies
    it. ``stop()`` cancels outstanding events (bounded join — a restart
    scheduled past the end of a bench run must not pin the process).
    """

    def __init__(
        self,
        plan: FaultPlan,
        kill_fn: Callable[[str], None],
        restart_fn: Callable[[str], None],
    ):
        self.plan = plan
        self._kill_fn = kill_fn
        self._restart_fn = restart_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.applied: List[Tuple[float, str, str]] = []  # (t, kind, replica)
        self.errors: List[str] = []

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("schedule already started")
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._t0 = time.monotonic()
        self._thread.start()

    def _run(self) -> None:
        for event in self.plan.lifecycle_events():
            delay = event.at_s - (time.monotonic() - self._t0)
            if delay > 0 and self._stop.wait(delay):
                return  # cancelled
            if self._stop.is_set():
                return
            fn = self._kill_fn if event.kind == "kill" else self._restart_fn
            try:
                fn(event.replica)
                self.applied.append(
                    (round(time.monotonic() - self._t0, 3), event.kind,
                     event.replica)
                )
            except Exception as err:  # noqa: BLE001 — a failed restart must
                # surface in the bench report, not kill the schedule thread
                # (later events may still apply).
                self.errors.append(f"{event.kind} {event.replica}: {err}")

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def join(self, timeout_s: float) -> None:
        """Wait for every scheduled event to apply (bench teardown)."""
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
