"""Slot-level continuous batching: join/leave serving with a hidden-state ring.

``MicroBatchQueue`` (serve/engine.py) dispatches a microbatch as ONE unit:
the coalescing window holds early arrivals back up to ``max_wait_s``, and a
request that misses a dispatch waits out the whole in-flight batch before
its own batch even forms. Under bursty open-loop load those batch-boundary
waits — not compute — set the p99 (ROADMAP open item 1). Orca-style
iteration-level scheduling removes exactly that wait class: the engine
steps continuously, and requests JOIN the padded in-flight batch between
steps while completed rows RETIRE between steps, so nobody ever waits on a
coalescing window or on somebody else's full batch.

``ContinuousBatcher`` is that front, duck-typing ``MicroBatchQueue``
(``submit``/``depth``/``recent_wait_ms``/``close``) so the gateway,
registry stats and admission control work unchanged:

* **Step loop.** A worker thread runs engine steps back-to-back whenever
  work is pending. Each step takes up to ``max_batch`` queued requests
  (FIFO), pads to the engine's power-of-two bucket, executes, and delivers
  — then immediately composes the next step from whatever arrived in the
  meantime. No window, no barrier: the worst join wait is the remaining
  service of the CURRENT step.
* **Row slots + household affinity.** With ``sessions`` on, each household
  owns a row slot carrying its cross-slot session (served-action /
  slot-count metadata; for recurrent bundles the policy's hidden state).
  The household-affinity routing from the gateway/fleet tiers keeps a
  household on one replica, so its slot — and therefore its hidden state —
  is engine-side stable across its request stream.
* **Generation counters.** Every slot carries a generation, bumped on
  every retire/evict/reassign. A request's slot resolution is tagged
  ``(slot, gen)`` when it joins a step, and state is only read/written
  under a matching generation — a late joiner can never read a RETIRED
  row's state: eviction re-allocates under a fresh generation with a
  deterministic re-init (zero carry), never a stale buffer.
* **Donated hidden-state ring.** For recurrent bundles the per-household
  flat LSTM carry lives in ONE device array ``[S + 1, A, H]`` (row ``S``
  is the scratch row pad rows gather from and scatter to). Each padding
  bucket gets its own compiled step program — gather rows, zero the
  fresh-session rows, step the actor, scatter the new carries back — with
  the ring DONATED, so the carry updates in place instead of copying
  ``S * A * H`` floats per step.
* **Stateless bit-exactness.** Feedforward bundles execute through the
  SAME per-bucket engine executables the microbatch path uses
  (``engine.act``), so continuous serving is bit-exact vs the microbatch
  queue for every stateless policy — only the queueing schedule moves,
  never the math (asserted end-to-end through the gateway in
  tests/test_continuous.py and by the committed ``SERVE_CB_*`` capture's
  ``bit_exact_stateless`` verdict).
* **Observability.** Every step emits ``serve.batch_occupancy`` (live
  rows / padded bucket) and per-request ``serve.slot_wait_ms`` histograms
  plus the same ``serve_request`` trace events the microbatch queue
  streams (``source="continuous"``), so the continuous-vs-microbatch win
  is attributable in the SQLite warehouse (``telemetry-query
  --continuous``), not just in a capture file.

Anonymous requests (no household id) and ``sessions=False`` serving run
each request from a fresh deterministic zero carry on the scratch row —
recurrent bundles stay servable for smoke traffic, but only a household id
buys continuity. A recurrent bundle with ``sessions=False`` is REFUSED at
construction, and under slot exhaustion a recurrent household's request is
DEFERRED (FIFO position kept; it joins once a resident household goes
idle) rather than silently served from a zero carry — serving a
hidden-state policy without its state would be a different policy.
Stateless households do overflow to the scratch row (their actions depend
only on the observation; only session metadata is lost).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np


@dataclass
class _SlotMeta:
    """Host-side bookkeeping for one row slot."""

    household: Optional[str] = None
    gen: int = 0
    last_used: int = -1       # step counter, for deterministic LRU
    fresh: bool = True        # next read must re-init (zero carry)
    served: int = 0           # session slot counter (Sessions.slots mirror)
    hp_frac: Optional[np.ndarray] = None  # [A] last served action


@dataclass
class _Request:
    obs: np.ndarray
    future: Future
    t_enq: float
    household: Optional[str]
    slot: int = -1
    gen: int = -1
    fresh: bool = True
    trace: object = None        # TraceContext when the caller is traced
    request_id: Optional[str] = None
    t_enq_epoch: float = 0.0


class ContinuousBatcher:
    """Slot-level continuous batching front over a ``PolicyEngine``.

    Duck-types ``MicroBatchQueue`` for the gateway/registry. ``max_slots``
    bounds resident sessions (LRU eviction past it, deterministic re-init
    on return); ``sessions=False`` disables per-household state entirely
    (stateless bundles only). ``max_wait_s`` is accepted for interface
    compatibility and ignored — continuous batching has no coalescing
    window, which is the point.
    """

    SCRATCH = -1  # sentinel: request runs from the scratch row, no session

    def __init__(
        self,
        engine,
        max_batch: Optional[int] = None,
        max_wait_s: float = 0.0,
        max_slots: int = 256,
        sessions: bool = True,
        autostart: bool = True,
        slot_wait_timeout_s: float = 5.0,
    ):
        del max_wait_s  # no coalescing window by design
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if getattr(engine, "is_recurrent", False) and not sessions:
            raise ValueError(
                "recurrent bundle needs sessions: serving a hidden-state "
                "policy with sessions disabled would silently act from a "
                "zero carry every slot — a different policy. Enable "
                "sessions (the default) or export a feedforward bundle."
            )
        self.engine = engine
        self.max_batch = min(max_batch or engine.max_batch, engine.max_batch)
        self.sessions_enabled = sessions
        self.max_slots = max_slots
        # How long a recurrent household's request may wait for a session
        # slot under exhaustion before it FAILS LOUDLY naming the fix
        # (raise max_slots) — unbounded deferral would starve un-slotted
        # households invisibly once resident households saturate the ring.
        self.slot_wait_timeout_s = slot_wait_timeout_s
        self._pending: List[_Request] = []
        self._cv = threading.Condition()
        self._closed = False
        # Admission signal window, same shape as MicroBatchQueue's:
        # (monotonic dispatch instant, enqueue->dispatch wait ms).
        self.recent_wait_ms: deque = deque(maxlen=512)
        # Host-side slot table. Device state (the recurrent hidden ring)
        # lives separately in _ring; the table is the source of truth for
        # WHO owns a row and under which generation.
        self._slots: List[_SlotMeta] = [_SlotMeta() for _ in range(max_slots)]
        self._by_household: Dict[str, int] = {}
        self._free: deque = deque(range(max_slots))
        self._step_counter = 0
        self.stats = {
            "steps": 0, "joins": 0, "evictions": 0, "retired": 0,
            "scratch_rows": 0, "stale_generation_drops": 0,
            "slot_deferrals": 0, "slot_wait_expired": 0,
            "cancelled_drops": 0, "spill_rejoins": 0,
        }
        # Spill-policy meter (ROADMAP item 4): households seen returning
        # after an LRU eviction. A high rejoin share means max_slots is
        # below the live working set and the ring is thrashing re-inits —
        # the signal the scale bench's spill row quantifies. Bounded at
        # 4x max_slots so a million-household churn cannot grow it; only
        # recency (not completeness) matters for the thrash signal.
        self._recently_evicted: OrderedDict = OrderedDict()
        self._recently_evicted_cap = 4 * max_slots
        self._ring = None
        self._ring_step = None
        if engine.is_recurrent:
            self._ring = self._init_ring()
            self._ring_step = self._make_ring_step()
        # ``autostart=False`` is the manual-stepping mode: no worker
        # thread; the owner drives ``step_once()`` itself — an external
        # control loop embedding the batcher, and the deterministic unit
        # tests (step composition becomes timing-independent).
        self._thread: Optional[threading.Thread] = None
        if autostart:
            self._thread = threading.Thread(target=self._worker, daemon=True)
            self._thread.start()

    # -- device ring ---------------------------------------------------------

    def _init_ring(self):
        import jax
        import jax.numpy as jnp

        ring = jnp.zeros(
            (self.max_slots + 1, self.engine.n_agents, self.engine.hidden_dim),
            jnp.float32,
        )
        if self.engine.device is not None:
            ring = jax.device_put(ring, self.engine.device)
        return ring

    def _make_ring_step(self):
        """The per-bucket compiled step program: gather the stepping rows'
        carries out of the ring, zero the fresh-session rows, run the
        recurrent actor one slot, scatter the new carries back. The ring is
        DONATED — the previous step's buffer is consumed in place. One
        jitted callable; XLA caches one executable per bucket shape."""
        import jax

        act_raw = self.engine._act_raw

        def step(params, ring, obs, rows, fresh):
            h = ring[rows]                                   # [b, A, H]
            h = h * (1.0 - fresh)[:, None, None]             # re-init rows
            actions, h2 = act_raw(params, obs, h)
            ring = ring.at[rows].set(h2)                     # pads -> scratch
            return ring, actions

        return jax.jit(step, donate_argnums=(1,))

    def warmup(self, buckets=None) -> List[int]:
        """Pre-compile the step program per padding bucket (recurrent) or
        the engine's act buckets (stateless) so the first request of each
        size never pays an XLA compile in-slot."""
        import jax

        if not self.engine.is_recurrent:
            return self.engine.warmup(buckets, include_step=False)
        warmed = []
        for b in buckets if buckets is not None else self.engine.buckets:
            if b > self.max_batch:
                continue
            obs = np.zeros((b, self.engine.n_agents, 4), np.float32)
            rows = np.full((b,), self.max_slots, np.int32)  # scratch only
            fresh = np.ones((b,), np.float32)
            self._ring, _ = self._ring_step(
                self.engine.params, self._ring, obs, rows, fresh
            )
            # host-sync: warmup compile boundary (pre-traffic).
            jax.block_until_ready(self._ring)
            warmed.append(b)
        return warmed

    # -- public queue interface ----------------------------------------------

    @property
    def depth(self) -> int:
        """Requests queued but not yet joined to a step (admission
        signal)."""
        with self._cv:
            return len(self._pending)

    def submit(
        self, obs_row, household: Optional[str] = None,
        trace=None, request_id: Optional[str] = None,
    ) -> Future:
        """Queue one community observation row; resolves to actions [A].

        ``household`` pins the request to its session slot (hidden-state
        continuity for recurrent bundles); ``None`` serves from a fresh
        deterministic zero carry on the scratch row. ``trace`` (a
        TraceContext) and ``request_id`` flow through to the step's trace
        records so queue-wait/execute spans stitch into the caller's tree."""
        # host-sync: caller-supplied host observation row.
        obs_row = np.asarray(obs_row, dtype=np.float32)
        fut: Future = Future()
        with self._cv:
            if self._closed:
                raise RuntimeError("queue is closed")
            self._pending.append(
                _Request(
                    obs=obs_row, future=fut, t_enq=time.monotonic(),
                    household=household if self.sessions_enabled else None,
                    trace=trace, request_id=request_id,
                    t_enq_epoch=time.time(),
                )
            )
            self._cv.notify()
        return fut

    def step_once(self) -> int:
        """Compose and execute ONE engine step synchronously; returns the
        number of rows stepped (0 = nothing pending). Manual-stepping
        companion to ``autostart=False`` — never call it with the worker
        thread running."""
        with self._cv:
            batch = self._compose_locked()
        if batch:
            try:
                self._execute(batch)
            except Exception as err:  # noqa: BLE001 — fail waiters too
                for req in batch:
                    if not req.future.done():
                        try:
                            req.future.set_exception(err)
                        except InvalidStateError:
                            pass
                raise
        return len(batch)

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def __enter__(self) -> "ContinuousBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- session lifecycle ----------------------------------------------------

    def session_info(self, household: str) -> Optional[dict]:
        """Test/observability hook: the household's live slot state, or
        None when it holds no slot."""
        with self._cv:
            slot = self._by_household.get(household)
            if slot is None:
                return None
            m = self._slots[slot]
            return {
                "slot": slot,
                "gen": m.gen,
                "served": m.served,
                "hp_frac": None if m.hp_frac is None else m.hp_frac.copy(),
            }

    def end_session(self, household: str) -> bool:
        """Retire a household's slot NOW (gen bump + free-list return).
        Its next request re-initializes deterministically. Returns whether
        a session existed."""
        with self._cv:
            slot = self._by_household.pop(household, None)
            if slot is None:
                return False
            self._retire_locked(slot)
            self.stats["retired"] += 1
            return True

    def _retire_locked(self, slot: int) -> None:
        m = self._slots[slot]
        m.household = None
        m.gen += 1
        m.fresh = True
        m.served = 0
        m.hp_frac = None
        self._free.append(slot)

    @property
    def occupancy(self) -> int:
        """Resident sessions (slots owned by a household)."""
        with self._cv:
            return self.max_slots - len(self._free)

    # -- slot resolution (lock held) ------------------------------------------

    def _resolve_slot_locked(self, household: str, pending_households) -> int:
        """The household's slot, allocating (and LRU-evicting an idle slot
        of a household with no queued work) when needed. Returns
        ``SCRATCH`` when every slot is unavailable this step."""
        slot = self._by_household.get(household)
        if slot is not None:
            return slot
        if self._free:
            slot = self._free.popleft()
        else:
            # Deterministic LRU eviction among slots whose household has
            # nothing queued: same arrival schedule, same victim. Slots of
            # households WITH queued requests are kept — evicting one
            # would break a continuity the very next step re-pays.
            candidates = [
                (m.last_used, i) for i, m in enumerate(self._slots)
                if m.household is not None
                and m.household not in pending_households
            ]
            if not candidates:
                return self.SCRATCH
            _, slot = min(candidates)
            victim = self._slots[slot].household
            self._by_household.pop(victim, None)
            self._retire_locked(slot)
            self._free.remove(slot)
            self.stats["evictions"] += 1
            self._recently_evicted[victim] = self._step_counter
            self._recently_evicted.move_to_end(victim)
            while len(self._recently_evicted) > self._recently_evicted_cap:
                self._recently_evicted.popitem(last=False)
        m = self._slots[slot]
        if self._recently_evicted.pop(household, None) is not None:
            # This household was LRU-evicted recently and is now paying a
            # deterministic re-init: the spill cost the eviction deferred.
            self.stats["spill_rejoins"] += 1
        m.household = household
        m.fresh = True
        m.served = 0
        m.hp_frac = None
        self._by_household[household] = slot
        self.stats["joins"] += 1
        return slot

    # -- the step loop --------------------------------------------------------

    def _compose_locked(self):
        """Pop the next step's requests off the FIFO queue, resolving each
        to a (slot, gen) under the current generations. For RECURRENT
        engines, at most one request per slot per step — a household's
        back-to-back requests serialize through consecutive steps (each
        must read the carry the previous one writes); later households may
        overtake an earlier one's SECOND request, never its first
        (per-household order is preserved). Stateless engines skip the
        serialization: their rows are order-independent, so a household's
        burst rides one step. Cancelled requests are dropped; recurrent
        requests that out-waited ``slot_wait_timeout_s`` for a slot fail
        loudly naming the ``max_slots`` fix."""
        batch: List[_Request] = []
        expired: List[_Request] = []
        taken: set = set()
        deferred: set = set()
        recurrent = self.engine.is_recurrent
        now = time.monotonic()
        pending_households = {
            r.household for r in self._pending if r.household is not None
        }
        remaining: List[_Request] = []
        for req in self._pending:
            if req.future.cancelled():
                # The caller gave up (gateway request timeout): dropping
                # the corpse here keeps the admission depth honest, and —
                # for recurrent sessions — never advances a household's
                # carry for a request nobody is waiting on.
                self.stats["cancelled_drops"] += 1
                continue
            if len(batch) >= self.max_batch:
                remaining.append(req)
                continue
            if req.household is None:
                req.slot, req.gen, req.fresh = self.SCRATCH, -1, True
                batch.append(req)
                continue
            if req.household in deferred:
                remaining.append(req)
                continue
            slot = self._by_household.get(req.household)
            if recurrent and slot is not None and slot in taken:
                # This household already steps this round: its next
                # request rides the NEXT step, reading the carry this
                # step is about to write. Recurrent-only — a stateless
                # household's rows are order-independent (actions depend
                # on the obs alone), so serializing them would pay K step
                # latencies for bookkeeping metadata.
                deferred.add(req.household)
                remaining.append(req)
                continue
            if slot is None:
                slot = self._resolve_slot_locked(
                    req.household, pending_households
                )
            if slot == self.SCRATCH:
                if recurrent:
                    # Slot exhaustion: a hidden-state household must NEVER
                    # silently serve from the scratch row's zero carry —
                    # that is the different-policy class the micro-queue
                    # and sessions=False refusals exist for. Defer: the
                    # request keeps its FIFO position and joins as soon as
                    # a resident household goes idle (its slot becomes the
                    # LRU eviction candidate). Bounded: past
                    # slot_wait_timeout_s the request FAILS loudly naming
                    # the fix instead of starving invisibly. Stateless
                    # households DO fall through to scratch — their
                    # actions depend on the observation only; all that is
                    # lost is session metadata, and latency beats a stall.
                    if now - req.t_enq > self.slot_wait_timeout_s:
                        expired.append(req)
                        continue
                    self.stats["slot_deferrals"] += 1
                    deferred.add(req.household)
                    remaining.append(req)
                    continue
                req.slot, req.gen, req.fresh = self.SCRATCH, -1, True
                batch.append(req)
                continue
            m = self._slots[slot]
            req.slot, req.gen, req.fresh = slot, m.gen, m.fresh
            taken.add(slot)
            batch.append(req)
        self._pending = remaining
        for req in expired:
            self.stats["slot_wait_expired"] += 1
            if not req.future.done():
                try:
                    req.future.set_exception(
                        RuntimeError(
                            "no session slot freed within "
                            f"{self.slot_wait_timeout_s:g}s: max_slots="
                            f"{self.max_slots} is below this replica's "
                            "concurrent recurrent household count — raise "
                            "--max-sessions (or spread households over "
                            "more replicas)"
                        )
                    )
                except InvalidStateError:
                    pass
        return batch

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if not self._pending and self._closed:
                    return
                batch = self._compose_locked()
            if not batch:
                # Defensive only — compose always joins >= 1 request when
                # work is pending today (resident households' requests are
                # joinable, and a fully-idle-occupied slot table is
                # evictable). Kept so a future composition rule that CAN
                # defer everything parks on the condition briefly instead
                # of hot-spinning this lock.
                with self._cv:
                    self._cv.wait(timeout=0.001)
                continue
            try:
                self._execute(batch)
            except Exception as err:  # noqa: BLE001 — fail waiters, not loop
                for req in batch:
                    if not req.future.done():
                        try:
                            req.future.set_exception(err)
                        except InvalidStateError:
                            pass

    def _execute(self, batch: List[_Request]) -> None:
        import jax

        b = len(batch)
        bucket = self.engine.bucket_for(b)
        obs = np.stack([r.obs for r in batch])
        dispatch_t = time.monotonic()
        dispatch_epoch = time.time()
        for req in batch:
            self.recent_wait_ms.append(
                (dispatch_t, (dispatch_t - req.t_enq) * 1e3)
            )
        if self.engine.is_recurrent:
            if bucket > b:
                obs = np.concatenate(
                    [obs, np.zeros((bucket - b,) + obs.shape[1:], obs.dtype)]
                )
            rows = np.full((bucket,), self.max_slots, np.int32)
            fresh = np.ones((bucket,), np.float32)
            for i, req in enumerate(batch):
                if req.slot != self.SCRATCH:
                    rows[i] = req.slot
                    fresh[i] = 1.0 if req.fresh else 0.0
            self._ring, actions = self._ring_step(
                self.engine.params, self._ring, obs, rows, fresh
            )
            # host-sync: the per-step serving latency boundary — the
            # batch's waiters need their actions NOW.
            actions = np.asarray(jax.block_until_ready(actions))[:b]
            self.engine.stats["rows"] += b
            self.engine.stats["batches"] += 1
            self.engine.stats["padded_rows"] += bucket - b
            tel = self.engine.telemetry
            if tel is not None:
                tel.counter("serve.requests", b)
                tel.counter("serve.batches")
                tel.counter("serve.padded_rows", bucket - b)
        else:
            # The SAME per-bucket executables the microbatch path runs —
            # continuous serving is bit-exact vs MicroBatchQueue for every
            # stateless policy by construction.
            actions = self.engine.act(obs)
        service_s = time.monotonic() - dispatch_t

        with self._cv:
            self._step_counter += 1
            self.stats["steps"] += 1
            for i, req in enumerate(batch):
                if req.slot == self.SCRATCH:
                    self.stats["scratch_rows"] += 1
                    continue
                m = self._slots[req.slot]
                if m.gen != req.gen or m.household != req.household:
                    # The slot was retired/reassigned between composition
                    # and delivery (end_session racing the step): the
                    # answer is still correct — it was computed under the
                    # request's own generation — but the RETIRED slot's
                    # state must not be touched under a stale generation.
                    self.stats["stale_generation_drops"] += 1
                    continue
                m.fresh = False
                m.served += 1
                m.last_used = self._step_counter
                m.hp_frac = actions[i].copy()
        for i, req in enumerate(batch):
            if req.future.cancelled():
                continue
            try:
                # host-sync: result delivery to the waiting future.
                req.future.set_result(np.asarray(actions[i]))
            except InvalidStateError:
                pass  # cancelled between the check and delivery
        try:
            self._trace(batch, b, bucket, dispatch_t, service_s, dispatch_epoch)
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            pass

    def _trace(
        self, batch, b: int, bucket: int, dispatch_t: float,
        service_s: float, dispatch_epoch: float = 0.0,
    ) -> None:
        """Per-step occupancy + per-request slot-wait records through the
        engine's telemetry: the queueing story the warehouse attributes the
        continuous-vs-microbatch win with. Traced requests additionally get
        real ``queue.wait``/``engine.execute`` spans, one fan-in
        ``engine.step`` span, and a synthetic ``engine.pad`` span — the same
        shapes the microbatch queue emits."""
        from p2pmicrogrid_tpu.telemetry.tracing import record_span

        tel = self.engine.telemetry
        if tel is None:
            return
        tel.counter("serve.steps")
        tel.histogram("serve.batch_occupancy", b / bucket)
        padded = bucket - b
        for row_i, req in enumerate(batch):
            wait_ms = (dispatch_t - req.t_enq) * 1e3
            tel.histogram("serve.slot_wait_ms", wait_ms)
            tel.event(
                "serve_request",
                source="continuous",
                row=row_i,
                batch_size=b,
                bucket=bucket,
                padded_rows=padded,
                slot=None if req.slot == self.SCRATCH else req.slot,
                wait_ms=round(wait_ms, 3),
                service_ms=round(service_s * 1e3, 3),
                latency_ms=round(wait_ms + service_s * 1e3, 3),
                request_id=req.request_id,
            )
        traced = [req for req in batch if req.trace is not None]
        if not traced:
            return
        for req in traced:
            wait_s = max(0.0, dispatch_epoch - req.t_enq_epoch)
            record_span(
                tel, req.trace.child("queue.wait"), "queue.wait",
                req.t_enq_epoch, wait_s, batch_size=b,
            )
            record_span(
                tel, req.trace.child("engine.execute"), "engine.execute",
                dispatch_epoch, service_s,
                bucket=bucket, batch_size=b, padded_rows=padded,
            )
        first_ctx = traced[0].trace
        record_span(
            tel, first_ctx.child("engine.step"), "engine.step",
            dispatch_epoch, service_s,
            bucket=bucket, batch_size=b, linked=len(traced),
        )
        if padded > 0:
            record_span(
                tel, first_ctx.child("engine.pad"), "engine.pad",
                dispatch_epoch, service_s * padded / bucket,
                bucket=bucket, padded_rows=padded, estimated=True,
            )


# -- the acceptance measurement -----------------------------------------------
#
# serve-bench --continuous-compare / benchmarks.py bench_serve_continuous:
# the SAME bursty open-loop schedule fired over the persistent mux wire
# through a microbatch gateway and a continuous-batching gateway in ONE
# process, same bundle, same observations — per-arm wire percentiles, a
# bit-exactness verdict across the arms AND against a direct engine, and
# the continuous arm's occupancy/slot-wait distributions. The committed
# ``artifacts/SERVE_CB_*.jsonl`` captures come from here and
# ``tools/check_artifacts_schema.py`` validates their contract.


def serve_bench_continuous_compare(
    bundle_dir: str,
    rate_hz: float = 256.0,
    n_requests: int = 1024,
    n_households: int = 32,
    seed: int = 0,
    slo_ms: float = 100.0,
    burst_factor: float = 8.0,
    burst_dwell_s: float = 0.25,
    max_batch: int = 64,
    max_wait_s: float = 0.002,
    max_slots: int = 256,
    device: str = "auto",
    results_db: Optional[str] = None,
    timeout_s: float = 30.0,
    emit=None,
) -> List[dict]:
    """Continuous vs microbatch at the mux wire, one process, one bundle.

    Emits (and returns) metric rows; the LAST row is the ``serve_continuous``
    headline carrying both arms' percentiles, ``vs_microbatch`` (microbatch
    p99 / continuous p99 — > 1 means continuous wins), the
    ``bit_exact_stateless`` verdict, the continuous arm's
    occupancy/slot-wait stats and the generating ``burst_config``.
    Stateless bundles only: the microbatch arm cannot serve a recurrent
    bundle at all, so there is nothing to compare (refused loudly)."""
    from p2pmicrogrid_tpu.serve.engine import PolicyEngine
    from p2pmicrogrid_tpu.serve.gateway import (
        AdmissionConfig,
        GatewayServer,
        build_gateway,
    )
    from p2pmicrogrid_tpu.serve.loadgen import (
        make_arrivals,
        run_network_loadgen,
        synthetic_obs,
    )

    reference = PolicyEngine(
        bundle_dir=bundle_dir, max_batch=max_batch, device=device
    )
    if reference.is_recurrent:
        raise ValueError(
            "--continuous-compare needs a stateless bundle: the microbatch "
            "arm refuses recurrent bundles, so there is no baseline to "
            "beat — bench a recurrent bundle through serve-bench --fleet "
            "--batching continuous instead"
        )
    arrivals, burst_config = make_arrivals(
        rate_hz, n_requests, seed=seed,
        burst_factor=burst_factor, burst_dwell_s=burst_dwell_s,
    )
    obs = synthetic_obs(n_requests, reference.n_agents, seed=seed)
    households = [f"house-{i:04d}" for i in range(n_households)]
    # Admission wide open: the comparison measures queueing discipline, not
    # shedding — a shed request would vanish from exactly the tail this
    # capture exists to show.
    admission = AdmissionConfig(max_queue_depth=1 << 16, wait_budget_ms=1e9)

    results, arm_tel = {}, {}
    for batching in ("micro", "continuous"):
        gateway = build_gateway(
            [bundle_dir],
            max_batch=max_batch,
            max_wait_s=max_wait_s,
            results_db=results_db,
            device=device,
            admission=admission,
            run_name=f"serve-cb-{batching}",
            mux_port=0,
            batching=batching,
            max_slots=max_slots,
        )
        server = GatewayServer(gateway)
        try:
            host, _port = server.start()
            results[batching] = run_network_loadgen(
                host, gateway.mux_port, obs, arrivals, households,
                timeout_s=timeout_s, transport="mux",
                record_actions=True,
            )
            default = gateway.registry.get(gateway.registry.default_hash)
            arm_tel[batching] = (
                default.telemetry.summary() if default.telemetry else {}
            )
        finally:
            server.stop()

    micro, cont = results["micro"], results["continuous"]
    # Bit-exactness across the arms AND against the direct engine, on every
    # request both arms answered.
    ok = [
        i for i in range(n_requests)
        if micro.statuses[i] == 200 and cont.statuses[i] == 200
        and micro.actions[i] is not None and cont.actions[i] is not None
    ]
    if not ok:
        # A verdict over zero compared requests would be indistinguishable
        # from a real bit-exactness failure in the schema-checked capture —
        # refuse to produce a meaningless acceptance row.
        raise RuntimeError(
            "continuous compare: no request succeeded on BOTH arms "
            f"(micro ok={micro.n_ok}, continuous ok={cont.n_ok} of "
            f"{n_requests}) — nothing compared; raise timeout_s or loosen "
            "the schedule before trusting any capture from this host"
        )
    got_m = np.asarray(  # host-sync: wire responses, host data
        [micro.actions[i] for i in ok], np.float32
    )
    got_c = np.asarray(  # host-sync: wire responses, host data
        [cont.actions[i] for i in ok], np.float32
    )
    want = reference.act(obs[ok])
    mismatches = int(
        ((got_m != want) | (got_c != want)).any(axis=-1).sum()
    )
    bit_exact = mismatches == 0

    p50_c, p95_c, p99_c = (cont.latency_ms(q) for q in (50, 95, 99))
    p50_m, p95_m, p99_m = (micro.latency_ms(q) for q in (50, 95, 99))
    vs_microbatch = round(p99_m / p99_c, 3) if p99_c > 0 else 0.0
    hists = arm_tel.get("continuous", {}).get("histograms", {})
    occupancy = hists.get("serve.batch_occupancy", {})
    slot_wait = hists.get("serve.slot_wait_ms", {})

    rows = [
        {
            "metric": f"serve_continuous_latency_ms_p{q}",
            "value": round(v, 3),
            "unit": "ms",
            "vs_baseline": round(slo_ms / v, 2) if v > 0 else 0.0,
        }
        for q, v in (("50", p50_c), ("95", p95_c), ("99", p99_c))
    ]
    rows.append(
        {
            "metric": "serve_microbatch_latency_ms_p99",
            "value": round(p99_m, 3),
            "unit": "ms",
            "vs_baseline": round(slo_ms / p99_m, 2) if p99_m > 0 else 0.0,
        }
    )
    rows.append(
        {
            "metric": "serve_continuous",
            "value": vs_microbatch,
            "unit": "x_p99_speedup",
            # >= 1.0 means slot-level continuous batching beats the
            # full-batch microbatch queue on p99 under this schedule —
            # the acceptance bar for the committed bursty captures.
            "vs_baseline": vs_microbatch,
            "p50_ms": round(p50_c, 3),
            "p95_ms": round(p95_c, 3),
            "p99_ms": round(p99_c, 3),
            "micro_p50_ms": round(p50_m, 3),
            "micro_p95_ms": round(p95_m, 3),
            "micro_p99_ms": round(p99_m, 3),
            "vs_microbatch": vs_microbatch,
            "bit_exact_stateless": bit_exact,
            "bit_exact_mismatches": mismatches,
            "n_compared": len(ok),
            "occupancy_mean": round(float(occupancy.get("mean", 0.0)), 4),
            "occupancy_p50": round(float(occupancy.get("p50", 0.0)), 4),
            "occupancy_p95": round(float(occupancy.get("p95", 0.0)), 4),
            "slot_wait_p50_ms": round(float(slot_wait.get("p50", 0.0)), 3),
            "slot_wait_p95_ms": round(float(slot_wait.get("p95", 0.0)), 3),
            "engine_steps": int(
                arm_tel.get("continuous", {}).get("counters", {}).get(
                    "serve.steps", 0
                )
            ),
            "throughput_rps": round(cont.throughput_rps, 1),
            "micro_throughput_rps": round(micro.throughput_rps, 1),
            "n_requests": n_requests,
            "n_ok": cont.n_ok,
            "micro_n_ok": micro.n_ok,
            "n_households": n_households,
            "offered_rate_rps": rate_hz,
            "slo_ms": slo_ms,
            "transport": "mux",
            "max_batch": max_batch,
            "max_wait_ms": round(max_wait_s * 1e3, 3),
            "max_sessions": max_slots,
            "burst_config": burst_config,
            "implementation": reference.manifest.get("implementation"),
            "n_agents": reference.n_agents,
            "config_hash": reference.manifest.get("config_hash"),
        }
    )
    if emit is not None:
        for row in rows:
            emit(row)
    return rows
