"""Persistent multiplexed wire: length-prefixed JSON frames over keep-alive
connections.

The r06/r07 wire captures showed the dominant per-request cost was not the
engine batch — it was the fresh HTTP/1.1 connection every request paid
(TCP handshake + slow-start + teardown, and with TLS a full handshake on
top). This module is the replacement transport: ONE connection per
(client, replica) pair stays up for the whole session and carries many
requests concurrently, matched by request id, so responses may complete
out of order (a stalled request never head-of-line-blocks its neighbours
the way a serial keep-alive HTTP/1.1 connection would).

Framing (the spec README documents):

* A frame is a 4-byte big-endian unsigned length ``N`` followed by ``N``
  bytes of UTF-8 JSON (one object). ``N`` is bounded by
  ``max_frame_bytes`` (default 1 MiB) — an oversized or negative length
  is a protocol error and kills the connection (the stream position past
  a bogus prefix is unknowable).
* Request object:  ``{"id": int, "method": "POST", "path": "/v1/act",
  "body": {...}, "token": "p2p1...", "trace": "<trace_id>-<span_id>-<hop>"}``
  — ``token`` optional, carries the per-household bearer (serve/auth.py)
  when the gateway terminates trust; ``trace`` optional, carries the
  encoded distributed-trace context (telemetry/tracing.py — the mux
  counterpart of the ``x-p2p-trace`` HTTP header). ``MuxPool`` replays
  stamp the replayed frame with hop+1, so server spans distinguish the
  original delivery from the post-reconnect one.
* Response object: ``{"id": int, "status": int, "body": {...}}`` plus
  ``"retry_after_s"`` when the server sheds. ``id`` echoes the request.
* A response whose ``body`` is not an object is a DETECTABLY corrupt
  payload (the fault injector's ``corrupt`` kind garbles exactly this
  way): clients report it as ``doc=None`` just like a corrupt HTTP body,
  so the retry machinery treats both transports identically.

Client machinery:

* ``MuxConnection`` — one live framed connection: a reader task resolves
  pending request futures by id; EOF/reset fails EVERY pending future
  with ``ConnectionResetError`` (the half-open case: a SIGKILLed peer
  that never FINs is caught by the per-request timeout, after which the
  caller discards the connection).
* ``MuxPool`` — the per-replica connection pool the router and loadgen
  share: picks a live connection round-robin, reconnects on demand, and
  (``replay=True``) replays a transport-failed request on a fresh
  connection inside the caller's deadline. Replay is safe because
  ``/v1/act`` is idempotent — a greedy action is a pure function of the
  observation; the engine holds no per-request state. ``reconnects`` is
  counted for the fleet stats headline.

Server side: ``serve_mux_connection`` is the shared accept-loop body —
the gateway (serve/gateway.py) and the standalone router proxy
(serve/proxy.py) both hand it a ``route`` coroutine and get identical
framing, fault-injection hooks and concurrent per-frame dispatch.
"""

from __future__ import annotations

import asyncio
import inspect
import json
import time
from typing import Callable, Dict, List, Optional

MAX_FRAME_BYTES = 1 << 20
_LEN_BYTES = 4

# The corrupt-fault body marker: deliberately NOT a JSON object, so every
# client detects the corruption (doc -> None) instead of acting on it.
CORRUPT_BODY = "�" * 8


class WireProtocolError(Exception):
    """The framed stream is unrecoverable (bad length prefix, non-JSON
    frame, non-object frame): the connection must close."""


class FrameTooLarge(WireProtocolError):
    """An inbound frame exceeded the cap but was fully DRAINED — the
    stream is still at a frame boundary, so a server may answer 413 and
    keep the connection (the HTTP wire's behavior for the same input).
    Raised only with ``drain_oversize=True``."""

    def __init__(self, length: int, cap: int):
        super().__init__(
            f"frame of {length} bytes exceeds the {cap}-byte cap"
        )
        self.length = length


# A bogus length prefix can claim gigabytes; drain-and-413 only up to this
# multiple of the cap — past it, closing is cheaper than reading garbage.
_DRAIN_CAP_MULTIPLE = 8


def encode_frame(doc: dict) -> bytes:
    payload = json.dumps(doc).encode()
    return len(payload).to_bytes(_LEN_BYTES, "big") + payload


async def read_frame(
    reader: asyncio.StreamReader,
    max_frame_bytes: int = MAX_FRAME_BYTES,
    drain_oversize: bool = False,
) -> Optional[dict]:
    """One frame, or ``None`` on clean EOF at a frame boundary. Raises
    ``WireProtocolError`` on oversized/garbage frames and
    ``asyncio.IncompleteReadError`` on mid-frame EOF.

    ``drain_oversize=True`` (servers): a frame over the cap — but under
    a bounded drain ceiling — is read and DISCARDED in chunks, then
    raised as ``FrameTooLarge`` with the stream intact, so one client's
    oversized request can answer 413 without severing every other
    request multiplexed on the connection."""
    try:
        prefix = await reader.readexactly(_LEN_BYTES)
    except asyncio.IncompleteReadError as err:
        if not err.partial:
            return None  # clean close between frames
        raise
    length = int.from_bytes(prefix, "big")
    if length > max_frame_bytes:
        if drain_oversize and length <= max_frame_bytes * _DRAIN_CAP_MULTIPLE:
            remaining = length
            while remaining > 0:
                chunk = await reader.read(min(remaining, 1 << 16))
                if not chunk:
                    raise asyncio.IncompleteReadError(b"", remaining)
                remaining -= len(chunk)
            raise FrameTooLarge(length, max_frame_bytes)
        raise WireProtocolError(
            f"frame of {length} bytes exceeds the {max_frame_bytes}-byte cap"
        )
    raw = await reader.readexactly(length) if length else b""
    try:
        doc = json.loads(raw.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as err:
        raise WireProtocolError(f"frame is not valid JSON: {err}") from None
    if not isinstance(doc, dict):
        raise WireProtocolError("frame must be a JSON object")
    return doc


# -- client: one multiplexed connection ---------------------------------------


class MuxConnection:
    """One live framed connection with id-matched in-flight requests."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        max_frame_bytes: int = MAX_FRAME_BYTES,
    ):
        self._reader = reader
        self._writer = writer
        self.max_frame_bytes = max_frame_bytes
        self._pending: Dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._write_lock = asyncio.Lock()
        self.closed = False
        self._reader_task = asyncio.ensure_future(self._read_loop())

    @classmethod
    async def open(
        cls,
        host: str,
        port: int,
        ssl=None,
        connect_timeout_s: float = 5.0,
        max_frame_bytes: int = MAX_FRAME_BYTES,
    ) -> "MuxConnection":
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port, ssl=ssl), connect_timeout_s
        )
        return cls(reader, writer, max_frame_bytes=max_frame_bytes)

    async def _read_loop(self) -> None:
        error: Exception = ConnectionResetError("mux connection lost")
        try:
            while True:
                doc = await read_frame(self._reader, self.max_frame_bytes)
                if doc is None:
                    break
                fut = self._pending.pop(doc.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result(doc)
        except (
            WireProtocolError, ConnectionError, OSError,
            asyncio.IncompleteReadError,
        ) as err:
            error = ConnectionResetError(f"mux connection lost: {err}")
        finally:
            self.closed = True
            # Half-open/broken stream: every in-flight request on this
            # connection fails NOW, with a transport error the pool can
            # retry on a fresh connection — not a silent hang.
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(error)
            self._pending.clear()

    async def request(
        self,
        path: str,
        body: Optional[dict],
        timeout_s: float,
        method: str = "POST",
        token: Optional[str] = None,
        trace: Optional[str] = None,
    ):
        """(status, body doc | None-if-corrupt, headers-ish dict)."""
        if self.closed:
            raise ConnectionResetError("mux connection is closed")
        loop = asyncio.get_running_loop()
        rid = self._next_id
        self._next_id += 1
        frame: dict = {"id": rid, "method": method, "path": path}
        if body is not None:
            frame["body"] = body
        if token is not None:
            frame["token"] = token
        if trace is not None:
            frame["trace"] = trace
        encoded = encode_frame(frame)
        if len(encoded) > self.max_frame_bytes + _LEN_BYTES:
            # Refuse locally: an over-cap request would only earn a
            # server-side drain+413 with no id to route back — fail it
            # HERE, immediately and terminally, without touching the
            # shared connection.
            raise FrameTooLarge(len(encoded) - _LEN_BYTES,
                                self.max_frame_bytes)
        fut: asyncio.Future = loop.create_future()
        self._pending[rid] = fut
        try:
            async with self._write_lock:
                self._writer.write(encoded)
                await self._writer.drain()
            doc = await asyncio.wait_for(fut, timeout_s)
        finally:
            self._pending.pop(rid, None)
        status = doc.get("status")
        if not isinstance(status, int):
            raise WireProtocolError("response frame carries no status")
        resp_body = doc.get("body")
        if resp_body is not None and not isinstance(resp_body, dict):
            resp_body = None  # detectably corrupt payload
        headers = {}
        if doc.get("retry_after_s") is not None:
            headers["retry-after"] = str(doc["retry_after_s"])
        return status, resp_body, headers

    @property
    def inflight(self) -> int:
        return len(self._pending)

    async def close(self) -> None:
        self.closed = True
        self._reader_task.cancel()
        try:
            await self._reader_task
        except (asyncio.CancelledError, Exception):  # noqa: BLE001
            pass
        self._writer.close()
        try:
            await self._writer.wait_closed()
        except (ConnectionError, OSError):
            pass


# -- client: per-address pool --------------------------------------------------

_TRANSPORT_ERRORS = (
    ConnectionError, OSError, EOFError,
    asyncio.IncompleteReadError, WireProtocolError,
)


class MuxPool:
    """Persistent multiplexed connections to ONE (host, port).

    ``request`` picks a live connection round-robin (``size`` bounds the
    pool; one mux connection already carries many concurrent requests —
    more than a few only helps by spreading kernel socket buffers),
    reconnecting on demand. A transport failure discards the connection
    and — because act requests are idempotent — replays the request on a
    fresh one, bounded by the per-request deadline. Timeouts do NOT
    discard the connection (a fault-stalled server answers late on a
    healthy stream) and are never replayed (the deadline already passed).
    """

    def __init__(
        self,
        host: str,
        port: int,
        size: int = 2,
        ssl=None,
        connect_timeout_s: float = 5.0,
        max_frame_bytes: int = MAX_FRAME_BYTES,
        on_reconnect: Optional[Callable[[], None]] = None,
    ):
        if size < 1:
            raise ValueError(f"pool size must be >= 1, got {size}")
        self.host = host
        self.port = port
        self.size = size
        self.ssl = ssl
        self.connect_timeout_s = connect_timeout_s
        self.max_frame_bytes = max_frame_bytes
        self.on_reconnect = on_reconnect
        self._conns: List[Optional[MuxConnection]] = [None] * size
        self._locks = [asyncio.Lock() for _ in range(size)]
        # Whether a slot EVER held a connection: a re-open on such a slot
        # is a reconnect no matter which path discarded the old one
        # (idle-detected EOF in _conn_at, or a mid-request transport
        # failure in request()) — the headline reconnect counter must
        # count exactly the losses chaos runs exist to measure.
        self._slot_connected = [False] * size
        self._rr = 0
        self.connects = 0     # total connections ever opened
        self.reconnects = 0   # connections opened after the first per slot
        self.replays = 0      # requests replayed on a fresh connection

    async def _conn_at(self, slot: int) -> MuxConnection:
        async with self._locks[slot]:
            conn = self._conns[slot]
            if conn is None or conn.closed:
                if conn is not None:
                    await conn.close()
                conn = await MuxConnection.open(
                    self.host, self.port, ssl=self.ssl,
                    connect_timeout_s=self.connect_timeout_s,
                    max_frame_bytes=self.max_frame_bytes,
                )
                self.connects += 1
                if self._slot_connected[slot]:
                    self.reconnects += 1
                    if self.on_reconnect is not None:
                        self.on_reconnect()
                self._slot_connected[slot] = True
                self._conns[slot] = conn
            return conn

    async def request(
        self,
        path: str,
        body: Optional[dict],
        timeout_s: float,
        method: str = "POST",
        token: Optional[str] = None,
        replay: bool = True,
        trace: Optional[str] = None,
    ):
        """(status, doc, headers) — see ``MuxConnection.request``."""
        deadline = time.monotonic() + timeout_s
        slot = self._rr % self.size
        self._rr += 1
        replayed = False
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise asyncio.TimeoutError(
                    f"mux request deadline exhausted ({timeout_s:g}s)"
                )
            try:
                conn = await self._conn_at(slot)
                return await conn.request(
                    path, body, remaining, method=method, token=token,
                    trace=trace,
                )
            except FrameTooLarge:
                # The REQUEST is over the cap — terminal, and the
                # connection never saw it: no discard, no replay.
                raise
            except (asyncio.TimeoutError, TimeoutError):
                # Ordered BEFORE the transport tuple: on 3.11+ the
                # builtin TimeoutError (== asyncio.TimeoutError) is an
                # OSError subclass and would match it. A timed-out
                # request must NOT tear down the healthy shared
                # connection every other in-flight request rides on
                # (a stall-faulted server answers late on a good
                # stream), and is never replayed — its deadline passed.
                raise
            except _TRANSPORT_ERRORS:
                # Broken/half-open connection: drop it; replay the (idem-
                # potent) request ONCE on a fresh one while the deadline
                # holds. A second consecutive failure means the replica is
                # down — surface it to the failover layer above.
                conn = self._conns[slot]
                if conn is not None:
                    self._conns[slot] = None
                    await conn.close()
                if not replay or replayed:
                    raise
                replayed = True
                self.replays += 1
                if trace is not None:
                    # Same trace/span identity, one delivery later: the
                    # server spans of the replayed hop must not be
                    # mistaken for the original send's.
                    from p2pmicrogrid_tpu.telemetry.tracing import bump_hop

                    trace = bump_hop(trace)

    async def close(self) -> None:
        for i, conn in enumerate(self._conns):
            if conn is not None:
                await conn.close()
                self._conns[i] = None


# -- client: synchronous probe connection --------------------------------------


class SyncMuxProbe:
    """One persistent framed connection for SYNCHRONOUS health probing.

    The router's prober runs on a plain thread (serve/router.py), so it
    cannot ride the asyncio ``MuxPool``; before this class each ``/readyz``
    sweep opened a fresh HTTP connection per replica — with TLS, a full
    handshake per replica per sweep (ROADMAP item-1 follow-on: fine at
    N=3, ruinous at N=100). This is the sync counterpart: one blocking
    socket per (prober, replica) that stays up ACROSS sweeps and carries
    one ``GET /readyz`` frame per probe over the replica's mux listener.

    Failure semantics match what a probe must detect: connect failure,
    reset, EOF, a protocol error, or a response that never arrives within
    ``timeout_s`` (the half-open case — a SIGKILLed peer never FINs) all
    raise ``OSError``-family errors; the caller scores the probe failed
    and ``close()``s, and the next sweep reconnects. The probe path is
    sequential (one frame in flight), so ids only guard against a stale
    late answer after a timeout: mismatched ids are drained, never
    returned.
    """

    def __init__(
        self,
        host: str,
        port: int,
        ssl_context=None,
        timeout_s: float = 2.0,
        max_frame_bytes: int = MAX_FRAME_BYTES,
    ):
        import socket

        self.host = host
        self.port = port
        self.ssl_context = ssl_context
        self.timeout_s = timeout_s
        self.max_frame_bytes = max_frame_bytes
        self._sock = None
        self._next_id = 0
        self.connects = 0
        self._socket_mod = socket

    @property
    def connected(self) -> bool:
        return self._sock is not None

    def _connect(self):
        sock = self._socket_mod.create_connection(
            (self.host, self.port), timeout=self.timeout_s
        )
        if self.ssl_context is not None:
            sock = self.ssl_context.wrap_socket(
                sock, server_hostname=self.host
            )
        sock.settimeout(self.timeout_s)
        self._sock = sock
        self.connects += 1

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionResetError("mux probe connection closed")
            buf += chunk
        return buf

    def request(self, path: str, method: str = "GET", token=None):
        """(status, body doc | None) for one frame; raises OSError-family
        on any transport/protocol/timeout failure (the connection is
        closed by then — the next call reconnects)."""
        try:
            if self._sock is None:
                self._connect()
            rid = self._next_id
            self._next_id += 1
            frame: dict = {"id": rid, "method": method, "path": path}
            if token is not None:
                frame["token"] = token
            self._sock.sendall(encode_frame(frame))
            while True:
                prefix = self._recv_exact(_LEN_BYTES)
                length = int.from_bytes(prefix, "big")
                if length > self.max_frame_bytes:
                    raise WireProtocolError(
                        f"frame of {length} bytes exceeds the "
                        f"{self.max_frame_bytes}-byte cap"
                    )
                raw = self._recv_exact(length) if length else b""
                try:
                    doc = json.loads(raw.decode())
                except (UnicodeDecodeError, json.JSONDecodeError) as err:
                    raise WireProtocolError(
                        f"frame is not valid JSON: {err}"
                    ) from None
                if not isinstance(doc, dict):
                    raise WireProtocolError("frame must be a JSON object")
                if doc.get("id") != rid:
                    continue  # stale answer from a timed-out earlier probe
                status = doc.get("status")
                if not isinstance(status, int):
                    raise WireProtocolError("response frame carries no status")
                body = doc.get("body")
                return status, body if isinstance(body, dict) else None
        except (OSError, WireProtocolError):
            # One failure poisons the stream position — close so the next
            # probe reconnects instead of parsing mid-frame garbage.
            self.close()
            raise

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


# -- server: shared mux accept-loop body --------------------------------------


def _mux_fault_scope(path: str) -> str:
    if path == "/v1/act":
        return "act"
    if path in ("/healthz", "/readyz"):
        return "health"
    return "other"


async def serve_mux_connection(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    route,
    max_frame_bytes: int = MAX_FRAME_BYTES,
    fault_decide=None,
    on_fault: Optional[Callable[[object], None]] = None,
) -> None:
    """Serve one client's framed connection until EOF/protocol error.

    ``route(method, path, body_doc, token)`` is an awaitable returning
    ``(status, payload_dict, extra_headers)`` — the gateway and the router
    proxy each bind their own. A route that also declares a ``trace``
    parameter receives the frame's encoded trace context
    (``trace=<str|None>``, telemetry/tracing.py); 4-arg routes keep
    working untraced, so the wire upgrade never breaks a deployed
    handler. Every frame dispatches CONCURRENTLY (its own task),
    responses interleave by id — the multiplexing. Protocol errors
    answer one ``{"id": null, "status": 400}`` frame, then close.

    ``fault_decide(scope)`` (serve/faults.py ``FaultInjector.decide``)
    applies the chaos kinds at the wire: stall delays the response, error
    answers 500, corrupt garbles the response body detectably, drop
    aborts the whole connection (a vanished process severs every stream
    it carried — exactly what SIGKILL looks like to a mux client).
    """
    write_lock = asyncio.Lock()
    tasks: set = set()
    # Signature sniff ONCE per connection, not per frame: trace-aware
    # routes opt in by declaring the parameter; everything else (including
    # the test suite's minimal 4-arg stubs) stays untraced.
    try:
        route_takes_trace = "trace" in inspect.signature(route).parameters
    except (TypeError, ValueError):
        route_takes_trace = False

    async def send(doc: dict) -> None:
        # A client that vanished mid-exchange (disconnect, drop-fault
        # abort) has nothing to tell: swallowing the write failure here
        # keeps the handler tasks from completing exceptional and
        # logging "Task exception was never retrieved" at teardown; the
        # read loop sees the EOF and winds the connection down.
        try:
            async with write_lock:
                writer.write(encode_frame(doc))
                await writer.drain()
        except (ConnectionError, OSError):
            pass

    async def handle(
        rid: int, method: str, path: str, body, token, trace=None
    ) -> None:
        fault = fault_decide(_mux_fault_scope(path)) if fault_decide else None
        if fault is not None:
            if on_fault is not None:
                on_fault(fault)
            if fault.kind == "drop":
                transport = writer.transport
                if transport is not None:
                    transport.abort()
                return
            if fault.kind == "stall":
                await asyncio.sleep(fault.stall_s)
        if fault is not None and fault.kind == "error":
            await send({"id": rid, "status": 500,
                        "body": {"error": "injected fault"}})
            return
        if route_takes_trace:
            status, payload, extra = await route(
                method, path, body, token, trace=trace
            )
        else:
            status, payload, extra = await route(method, path, body, token)
        doc: dict = {"id": rid, "status": status, "body": payload}
        if trace is not None:
            doc["trace"] = trace  # echo: responses stay attributable
        for name, value in extra or ():
            if str(name).lower() == "retry-after":
                try:
                    doc["retry_after_s"] = float(value)
                except (TypeError, ValueError):
                    pass
        if fault is not None and fault.kind == "corrupt":
            doc["body"] = CORRUPT_BODY  # non-object: detectably corrupt
        await send(doc)

    try:
        while True:
            try:
                frame = await read_frame(
                    reader, max_frame_bytes, drain_oversize=True
                )
            except FrameTooLarge as err:
                # The oversized frame was drained — the stream is still
                # at a boundary. Answer 413 (the frame's id was inside
                # the discarded payload) and KEEP the connection: one
                # client's fat request must not sever every other
                # request multiplexed here (the HTTP wire answers the
                # identical input with a clean terminal 413 too).
                await send({"id": None, "status": 413,
                            "body": {"error": str(err)}})
                continue
            except (WireProtocolError, asyncio.IncompleteReadError) as err:
                try:
                    await send({"id": None, "status": 400,
                                "body": {"error": str(err)}})
                except (ConnectionError, OSError):
                    pass
                break
            if frame is None:
                break
            rid = frame.get("id")
            if not isinstance(rid, int) or isinstance(rid, bool):
                await send({"id": None, "status": 400,
                            "body": {"error": "frame carries no integer id"}})
                break
            method = frame.get("method", "POST")
            path = frame.get("path")
            body = frame.get("body")
            token = frame.get("token")
            # Tolerant by design: a malformed trace field downgrades the
            # request to untraced, it never fails the frame.
            trace = frame.get("trace")
            if not isinstance(trace, str):
                trace = None
            if not isinstance(path, str):
                await send({"id": rid, "status": 400,
                            "body": {"error": "frame carries no path"}})
                continue
            if body is not None and not isinstance(body, dict):
                await send({"id": rid, "status": 400,
                            "body": {"error": "body must be an object"}})
                continue
            if token is not None and not isinstance(token, str):
                await send({"id": rid, "status": 400,
                            "body": {"error": "token must be a string"}})
                continue
            task = asyncio.ensure_future(
                handle(rid, str(method).upper(), path, body, token, trace)
            )
            tasks.add(task)
            task.add_done_callback(tasks.discard)
    except (ConnectionError, OSError):
        pass
    finally:
        for task in list(tasks):
            task.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
