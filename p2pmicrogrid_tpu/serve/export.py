"""Policy bundles: frozen, versioned greedy-parameter exports for serving.

A training checkpoint (train/checkpoint.py) is the WHOLE learner state —
optimizers, replay rings, target copies, exploration schedules — because
resume needs all of it. Serving needs none of it: the greedy decision path
of every implementation reads exactly one parameter subtree (the Q-table,
the online Q-network, the deterministic actor). A *policy bundle* is that
subtree alone, frozen to disk next to a manifest that pins provenance
(config hash, git rev, implementation) and the serving contract (obs/action
spec, community size), so an engine can refuse mismatched inputs instead of
silently mis-serving.

Layout of a bundle directory::

    <dir>/manifest.json   kind="policy_bundle", format_version, provenance,
                          obs/action spec, model arch fields
    <dir>/params.npz      flat '/'-joined tree paths -> arrays

Size matters at the north star: a 1000-agent DDPG checkpoint carries actor +
critic + 2 targets + 2 Adam states + replay (~6x the actor alone before
replay); the bundle is the actor subtree, optionally dtype-cast (float16
halves it again). ``tools/check_artifacts_schema.py`` validates manifests.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional, Tuple

import numpy as np

BUNDLE_FORMAT_VERSION = 1
MANIFEST_FILE = "manifest.json"
PARAMS_FILE = "params.npz"

# The one parameter subtree each implementation's GREEDY path reads
# (tabular_act -> q_table; dqn_act -> online; ddpg / recurrent ddpg
# greedy -> actor).
GREEDY_FIELD = {
    "tabular": "q_table",
    "dqn": "online",
    "ddpg": "actor",
    "ddpg_recurrent": "actor",
}

# Implementations whose greedy decision READS cross-slot hidden state. Their
# bundles carry a ``hidden_state`` manifest block (per-agent flat shape,
# dtype, carry layout) and can only serve through session-carrying paths
# (serve/continuous.py) — the stateless microbatch queue refuses them.
RECURRENT_IMPLEMENTATIONS = ("ddpg_recurrent",)

# On-disk dtypes for floating leaves. bfloat16 is deliberately absent: numpy
# cannot persist it natively and a bit-punned encoding would make bundles
# unreadable without this codebase — float16 is the compact option; int8 is
# the quantized option (symmetric per-leaf scales + an error-bound contract,
# see the "int8 quantization" section below).
EXPORT_DTYPES = ("float32", "float16", "int8")

# --- int8 quantization -------------------------------------------------------
#
# Scheme: symmetric per-leaf int8 — each floating leaf stores
# ``round(v / scale)`` clipped to [-127, 127] with ``scale = max|v| / 127``
# (scale 1.0 for all-zero leaves), scales recorded in the manifest's
# ``quant.scales`` keyed by the flat leaf path. Serving dequantizes to f32 at
# load (``load_policy_bundle``), so arithmetic precision is unchanged — the
# quantization error lives entirely in the parameters.
#
# Error-bound CONTRACT (recorded in ``quant.error_bound``, enforced at
# export and re-checked by serve/promotion.py's gate):
#
# * discrete policies (tabular, dqn) must serve a BIT-EXACT greedy argmax vs
#   the float32 bundle. Tabular is enforced BY CONSTRUCTION: the quantized
#   table gets an exhaustive argmax-repair pass (every row's float32 winner
#   is made the strict first-occurrence int winner; repairs move entries by
#   at most a few quantization steps, and the measured post-repair
#   ``max_abs_err`` is recorded). DQN cannot be repaired row-wise (the
#   argmax is over network outputs), so the export MEASURES argmax agreement
#   on a seeded calibration capture through the real serving forward and
#   REFUSES the export on any flip.
# * continuous actors (ddpg) get a measured ulp bound: the max float32-ulp
#   distance between the f32 and dequantized actors' actions over the
#   calibration capture must stay within ``ulp_budget`` (export refuses
#   otherwise); both numbers land in the manifest for the promotion gate.

QUANT_SCHEME = "symmetric-per-leaf-int8"
# Default continuous-actor budget: int8 weight noise (~0.4% relative per
# leaf) through the shipped 64-wide actors measures ~6e4 float32 ulps on the
# [0, 1] action range (ulp distance inflates toward small outputs — 2^18
# ulps near 1.0 is ~0.03 absolute). The budget's job is to catch
# REGRESSIONS (a mis-scaled leaf, a corrupted scale table) and to give the
# promotion gate a recorded number to enforce, not to promise float
# accuracy — callers wanting tighter bounds pass ulp_budget explicitly.
DEFAULT_ULP_BUDGET = float(2 ** 18)
CALIBRATION_OBS = 64
INT8_MAX = 127

OBS_SPEC = {
    "dim": 4,
    "features": ["time_norm", "norm_temp", "norm_balance", "p2p_mean"],
}


def _path_key(entry) -> str:
    from jax.tree_util import DictKey, GetAttrKey, SequenceKey

    if isinstance(entry, DictKey):
        return str(entry.key)
    if isinstance(entry, GetAttrKey):
        return entry.name
    if isinstance(entry, SequenceKey):
        return str(entry.idx)
    return str(entry)


def _flatten_tree(tree) -> dict:
    """'/'-joined path -> np.ndarray for every leaf of a params pytree."""
    import jax

    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(_path_key(p) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _unflatten_tree(flat: dict) -> dict:
    """Inverse of ``_flatten_tree`` into plain nested dicts (what
    ``flax.linen.Module.apply`` accepts as params)."""
    root: dict = {}
    for key, arr in flat.items():
        parts = key.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root


def greedy_params(implementation: str, pol_state):
    """Extract the greedy parameter subtree from a learner state.

    Accepts both live state objects (TabularState/DQNState/DDPGState/
    DDPGParams) and the raw field-keyed dicts orbax returns from a
    structure-free checkpoint read (``train.checkpoint.restore_raw``).
    Always returns a dict-rooted tree (the bare tabular array is wrapped)
    so the npz leaf paths are never empty.
    """
    try:
        field = GREEDY_FIELD[implementation]
    except KeyError:
        raise ValueError(
            f"unknown implementation {implementation!r}; "
            f"expected one of {sorted(GREEDY_FIELD)}"
        ) from None
    if isinstance(pol_state, dict):
        node = pol_state.get(field)
    else:
        node = getattr(pol_state, field, None)
    if node is None:
        have = (
            sorted(pol_state)
            if isinstance(pol_state, dict)
            else type(pol_state).__name__
        )
        raise ValueError(
            f"state has no {field!r} subtree for implementation "
            f"{implementation!r} (got {have}); is this the right checkpoint?"
        )
    return {field: node} if implementation == "tabular" else node


def _model_spec(cfg, implementation: str, flat_params: dict) -> dict:
    """Architecture fields the engine needs to rebuild the greedy forward
    pass exactly (bin counts for the tabular discretizer, hidden widths for
    the nets, the agent-shared flag for DDPG)."""
    if implementation == "tabular":
        return {"qlearning": dataclasses.asdict(cfg.qlearning)}
    if implementation == "dqn":
        return {"hidden": cfg.dqn.hidden}
    if implementation == "ddpg_recurrent":
        # Arch read off the exported params themselves (the recurrent actor
        # is not cfg-parameterized): the shared LSTM cell's gate bias width
        # IS lstm_features, and the Dense widths pin the trunk/head.
        lstm_features = int(flat_params["OptimizedLSTMCell_0/hf/bias"].shape[0])
        return {
            "actor": "recurrent_lstm",
            "hidden_pre": int(flat_params["Dense_0/bias"].shape[0]),
            "lstm_features": lstm_features,
            "hidden_post": int(flat_params["Dense_2/bias"].shape[0]),
        }
    # ddpg: a per-agent actor stacks a leading [A] axis on every Dense
    # kernel (ndim 3); the agent-shared actor is unbatched (ndim 2). Detect
    # from the exported params, not cfg — an eval-path restore may have
    # broadcast a shared checkpoint onto per-agent stacks already.
    kernel = flat_params.get("Dense_0/kernel")
    share = kernel is not None and kernel.ndim == 2
    return {"actor_hidden": cfg.ddpg.actor_hidden, "share_across_agents": share}


def _quantize_leaf(v: np.ndarray):
    """(int8 array, float scale) — symmetric per-leaf quantization."""
    scale = float(np.max(np.abs(v))) / INT8_MAX if v.size else 0.0
    if scale == 0.0:
        scale = 1.0
    q = np.clip(np.rint(v.astype(np.float64) / scale), -INT8_MAX, INT8_MAX)
    return q.astype(np.int8), scale


def _dequantize_leaf(q: np.ndarray, scale: float) -> np.ndarray:
    return q.astype(np.float32) * np.float32(scale)


def _repair_discrete_argmax(q: np.ndarray, f32: np.ndarray):
    """Make the int table's first-occurrence argmax equal the float32
    table's on EVERY row (trailing axis = actions), by construction.

    The float winner ``w`` must strictly beat every earlier action and
    tie-or-beat every later one. The repair raises ``q[w]`` to the smallest
    satisfying value (clipped at +127) and clamps violating neighbours down
    to it — each touched entry moves by whole quantization steps, bounded
    by the recorded post-repair ``max_abs_err``. Returns
    (repaired int8 array, rows repaired)."""
    k = q.shape[-1]
    qi = q.astype(np.int32)
    w = np.argmax(f32, axis=-1)
    deq_w = np.argmax(qi, axis=-1)
    n_bad = int((deq_w != w).sum())
    idx = np.arange(k)
    before = idx < w[..., None]
    after = idx > w[..., None]
    qw = np.take_along_axis(qi, w[..., None], axis=-1)[..., 0]
    max_before = np.max(np.where(before, qi, -INT8_MAX - 1), axis=-1)
    max_after = np.max(np.where(after, qi, -INT8_MAX - 1), axis=-1)
    qw_new = np.minimum(
        np.maximum(qw, np.maximum(max_before + 1, max_after)), INT8_MAX
    )
    qi = np.where(before, np.minimum(qi, (qw_new - 1)[..., None]), qi)
    qi = np.where(after, np.minimum(qi, qw_new[..., None]), qi)
    np.put_along_axis(qi, w[..., None], qw_new[..., None], axis=-1)
    return np.clip(qi, -INT8_MAX, INT8_MAX).astype(np.int8), n_bad


def _ulp_diff(a: np.ndarray, b: np.ndarray) -> float:
    """Max float32-ulp distance between two arrays (sign-magnitude ordered
    int32 representation — the standard total-order trick)."""

    def ordered(x):
        bits = np.ascontiguousarray(x, dtype=np.float32).view(np.int32)
        return np.where(bits < 0, np.int32(-2147483648) - bits, bits).astype(
            np.int64
        )

    if a.size == 0:
        return 0.0
    return float(np.max(np.abs(ordered(a) - ordered(b))))


def calibration_obs(n: int, n_agents: int, seed: int = 0) -> np.ndarray:
    """Seeded synthetic observation capture for quantization calibration:
    time in [0, 1), the normalized temp/balance/p2p features in [-1, 1] —
    the serving contract's obs ranges."""
    rng = np.random.default_rng(seed)
    time = rng.uniform(0.0, 1.0, (n, n_agents, 1))
    rest = rng.uniform(-1.0, 1.0, (n, n_agents, 3))
    return np.concatenate([time, rest], axis=-1).astype(np.float32)


def _measure_quant_error(
    cfg,
    manifest: dict,
    flat_f32: dict,
    flat_deq: dict,
    ulp_budget: float,
    calib_seed: int,
) -> dict:
    """The error-bound block for an int8 manifest, measured through the REAL
    serving forward (two PolicyEngines — f32 vs dequantized params — on the
    calibration capture). Raises ValueError when the contract is violated:
    any greedy-argmax flip for a discrete policy, or a continuous actor
    exceeding its ulp budget."""
    from p2pmicrogrid_tpu.serve.engine import PolicyEngine

    impl = manifest["implementation"]
    n_agents = manifest["n_agents"]
    obs = calibration_obs(CALIBRATION_OBS, n_agents, seed=calib_seed)
    eng_f32 = PolicyEngine(
        manifest=manifest, params=_unflatten_tree(flat_f32),
        max_batch=CALIBRATION_OBS, device="default",
    )
    eng_deq = PolicyEngine(
        manifest=manifest, params=_unflatten_tree(flat_deq),
        max_batch=CALIBRATION_OBS, device="default",
    )
    act_f32 = eng_f32.act(obs)
    act_deq = eng_deq.act(obs)
    max_abs_err = max(
        (float(np.max(np.abs(flat_deq[k] - flat_f32[k]))) if flat_f32[k].size else 0.0)
        for k in flat_f32
    ) if flat_f32 else 0.0

    if impl in ("tabular", "dqn"):
        flips = int((act_f32 != act_deq).sum())
        bound = {
            "kind": "discrete_argmax",
            "bit_exact_argmax": flips == 0,
            "argmax_check": "exhaustive+calibration" if impl == "tabular"
            else "calibration",
            "calibration": {"n_obs": CALIBRATION_OBS, "seed": calib_seed},
            "max_abs_err": max_abs_err,
        }
        if flips:
            raise ValueError(
                f"int8 export violates the discrete greedy contract: "
                f"{flips} calibration action(s) flipped vs float32 "
                f"({impl}; the quantized bundle must serve a bit-exact "
                "argmax — use float16/float32 for this checkpoint)"
            )
        return bound
    max_ulp = _ulp_diff(act_f32, act_deq)
    bound = {
        "kind": "continuous_ulp",
        "max_ulp": max_ulp,
        "ulp_budget": float(ulp_budget),
        "max_abs_action_err": float(np.max(np.abs(act_f32 - act_deq)))
        if act_f32.size else 0.0,
        "calibration": {"n_obs": CALIBRATION_OBS, "seed": calib_seed},
        "max_abs_err": max_abs_err,
    }
    if max_ulp > ulp_budget:
        raise ValueError(
            f"int8 export exceeds the continuous-actor error budget: "
            f"measured max ulp {max_ulp:.0f} > budget {ulp_budget:.0f} "
            "(raise ulp_budget explicitly if this precision is acceptable)"
        )
    return bound


def _hidden_state_spec(model: dict) -> dict:
    """The manifest ``hidden_state`` block a recurrent bundle carries: the
    per-agent flat carry shape/dtype the engine's session ring allocates,
    and the layout documenting what lives where. Serving code sizes buffers
    from THIS block, never from the architecture fields — a future
    recurrent kind with a different carry only has to write a new block."""
    from p2pmicrogrid_tpu.models.ddpg_recurrent import (
        HIDDEN_LAYOUT,
        actor_hidden_dim,
    )

    return {
        "shape": [actor_hidden_dim(model["lstm_features"])],
        "dtype": "float32",
        "layout": list(HIDDEN_LAYOUT),
        "init": "zeros",
        "semantics": "per-agent flat LSTM carry (double shared-weight pass)",
    }


def _action_spec(implementation: str) -> dict:
    if implementation in ("tabular", "dqn"):
        return {
            "type": "discrete",
            "values": [0.0, 0.5, 1.0],  # models/dqn.py ACTION_VALUES
            "semantics": "heat-pump power fraction",
        }
    return {
        "type": "continuous",
        "low": 0.0,
        "high": 1.0,
        "semantics": "heat-pump power fraction",
    }


def export_policy_bundle(
    cfg,
    pol_state,
    out_dir: str,
    source: Optional[dict] = None,
    dtype: str = "float32",
    ulp_budget: float = DEFAULT_ULP_BUDGET,
    calibration_seed: int = 0,
    aot_buckets: Optional[list] = None,
) -> str:
    """Freeze ``pol_state``'s greedy parameters into a bundle at ``out_dir``.

    ``source`` (e.g. ``{"checkpoint": dir, "episode": n}``) is recorded
    verbatim in the manifest for provenance. ``dtype`` casts floating leaves
    on disk (``float16`` halves the bundle; ``int8`` quarters it with
    symmetric per-leaf scales and the error-bound contract documented at the
    top of this module — discrete policies stay bit-exact on the greedy
    argmax, continuous actors get a measured ulp bound within
    ``ulp_budget``; integer leaves are untouched). Note that a float16
    export QUANTIZES the parameters silently — the engine's
    bit-identical-to-checkpoint guarantee for discrete policies holds for
    float32 and (by the enforced contract) int8 bundles; a float16 Q-table
    can collapse near-tied action values and flip an argmax. The int8
    discrete certification has two strengths, recorded as
    ``quant.error_bound.argmax_check``: tabular argmax-exactness is
    EXHAUSTIVE (every Q-table row repaired so the int winner is the f32
    winner, first occurrence), while DQN is verified on a seeded
    ``calibration.n_obs``-point capture through the real engine — the export
    refuses on any flip there, but an observation outside the calibration
    set with a sufficiently near-tied Q-gap could still flip (near-tie
    refusal narrows, not closes, that window).

    ``aot_buckets`` additionally AOT-compiles those padding-bucket serving
    programs (``jit(...).lower().compile()``) into the in-process program
    cache (serve/engine.py) so a ``PolicyEngine.warmup`` or gateway hot-swap
    of this architecture later IN THE SAME PROCESS skips the cold compile;
    executables are not serialized — only the bucket list and compile
    timings land in the manifest. Returns ``out_dir``.
    """
    from p2pmicrogrid_tpu.telemetry import config_hash
    from p2pmicrogrid_tpu.telemetry.registry import git_rev

    if dtype not in EXPORT_DTYPES:
        raise ValueError(f"dtype must be one of {EXPORT_DTYPES}, got {dtype!r}")
    impl = cfg.train.implementation
    if dtype == "int8" and impl in RECURRENT_IMPLEMENTATIONS:
        raise ValueError(
            "int8 export is not defined for recurrent actors: the ulp "
            "error-bound contract is measured on a stateless calibration "
            "capture, and quantization error COMPOUNDS through the hidden "
            "carry across a session — use float32 or float16"
        )
    params = greedy_params(impl, pol_state)
    flat_src = _flatten_tree(params)

    quant = None
    if dtype == "int8":
        flat_f32 = {
            k: (v.astype(np.float32) if np.issubdtype(v.dtype, np.floating) else v)
            for k, v in flat_src.items()
        }
        flat, scales = {}, {}
        for k, v in flat_f32.items():
            if not np.issubdtype(v.dtype, np.floating):
                flat[k] = v
                continue
            q, scale = _quantize_leaf(v)
            flat[k], scales[k] = q, scale
        n_repaired = 0
        if impl == "tabular":
            # Exhaustive argmax repair over the whole table: the greedy
            # contract holds for EVERY reachable observation, not just the
            # calibration capture.
            k_table = "q_table"
            flat[k_table], n_repaired = _repair_discrete_argmax(
                flat[k_table], flat_f32[k_table]
            )
        flat_deq = {
            k: (_dequantize_leaf(v, scales[k]) if k in scales else v)
            for k, v in flat.items()
        }
        manifest_stub = {
            "implementation": impl,
            "n_agents": cfg.sim.n_agents,
            "model": _model_spec(cfg, impl, flat_f32),
        }
        error_bound = _measure_quant_error(
            cfg, manifest_stub, flat_f32, flat_deq, ulp_budget,
            calibration_seed,
        )
        if impl == "tabular":
            error_bound["rows_repaired"] = int(n_repaired)
        quant = {
            "scheme": QUANT_SCHEME,
            "scales": {k: float(s) for k, s in scales.items()},
            "error_bound": error_bound,
        }
    else:
        cast = np.dtype(dtype)
        flat = {
            k: (v.astype(cast) if np.issubdtype(v.dtype, np.floating) else v)
            for k, v in flat_src.items()
        }

    os.makedirs(out_dir, exist_ok=True)
    np.savez(os.path.join(out_dir, PARAMS_FILE), **flat)
    manifest = {
        "kind": "policy_bundle",
        "format_version": BUNDLE_FORMAT_VERSION,
        "created": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "implementation": impl,
        "n_agents": cfg.sim.n_agents,
        "setting": cfg.setting,
        "config_hash": config_hash(cfg),
        "git_rev": git_rev(),
        "dtype": dtype,
        "obs_spec": dict(OBS_SPEC),
        "action_spec": _action_spec(impl),
        "model": _model_spec(cfg, impl, flat),
        "params_file": PARAMS_FILE,
        "param_count": int(sum(v.size for v in flat.values())),
        "param_bytes": int(sum(v.nbytes for v in flat.values())),
        "source": source,
    }
    if quant is not None:
        manifest["quant"] = quant
    if impl in RECURRENT_IMPLEMENTATIONS:
        # The serving contract for session-carrying policies: engines size
        # their hidden ring from this block, and the stateless microbatch
        # path refuses any bundle that carries one.
        manifest["hidden_state"] = _hidden_state_spec(manifest["model"])
    if aot_buckets:
        manifest["aot"] = aot_compile_bundle(manifest, flat, aot_buckets)
    with open(os.path.join(out_dir, MANIFEST_FILE), "w") as f:
        json.dump(manifest, f, indent=2)
    return out_dir


def _dequantize_flat(flat: dict, manifest: dict) -> dict:
    """Reconstruct float32 leaves from an int8 bundle's stored ints +
    manifest scales (identity for unquantized bundles)."""
    scales = (manifest.get("quant") or {}).get("scales") or {}
    return {
        k: (_dequantize_leaf(v, scales[k]) if k in scales else v)
        for k, v in flat.items()
    }


def aot_compile_bundle(
    manifest: dict, flat: dict, buckets: list, max_batch: int = 256
) -> dict:
    """AOT-compile the bundle's padding-bucket serving programs
    (``jit(...).lower().compile()``) into the process-wide executable cache
    (serve/engine.py) so warmup/hot-swap of this architecture stops paying
    cold-compile. Returns the manifest ``aot`` block."""
    from p2pmicrogrid_tpu.serve.engine import PolicyEngine

    params = _unflatten_tree(_dequantize_flat(flat, manifest))
    engine = PolicyEngine(
        manifest=manifest, params=params, max_batch=max_batch,
        device="default",
    )
    warmed = engine.warmup(sorted(set(int(b) for b in buckets)),
                           include_step=False)
    return {"buckets": warmed, "max_batch": max_batch}


def load_policy_bundle(bundle_dir: str, dequantize: bool = True) -> Tuple[dict, dict]:
    """(manifest, nested params dict of np arrays) from a bundle directory.

    int8 bundles are dequantized to float32 through the manifest's per-leaf
    scales by default (every consumer — engine, continual grafting, the
    promotion gate — then sees ordinary float params); ``dequantize=False``
    returns the raw stored ints (tests, size accounting).

    Refuses bundles written by a NEWER format version — fields this reader
    does not understand could change greedy semantics silently.
    """
    mpath = os.path.join(bundle_dir, MANIFEST_FILE)
    if not os.path.exists(mpath):
        raise FileNotFoundError(f"no {MANIFEST_FILE} under {bundle_dir}")
    with open(mpath) as f:
        manifest = json.load(f)
    if manifest.get("kind") != "policy_bundle":
        raise ValueError(
            f"{mpath} is not a policy bundle manifest "
            f"(kind={manifest.get('kind')!r})"
        )
    version = manifest.get("format_version")
    if not isinstance(version, int) or version > BUNDLE_FORMAT_VERSION:
        raise ValueError(
            f"bundle {bundle_dir} has format_version {version!r}; this "
            f"reader understands <= {BUNDLE_FORMAT_VERSION} — upgrade the "
            "serving code, do not guess at a newer format"
        )
    ppath = os.path.join(bundle_dir, manifest.get("params_file", PARAMS_FILE))
    with np.load(ppath) as z:
        flat = {k: z[k] for k in z.files}
    if dequantize:
        flat = _dequantize_flat(flat, manifest)
    return manifest, _unflatten_tree(flat)


def export_bundle_from_checkpoint(
    cfg,
    ckpt_dir: str,
    out_dir: str,
    dtype: str = "float32",
) -> str:
    """Export the newest checkpoint step under ``ckpt_dir`` as a bundle.

    Template-free: the checkpoint is read structure-free
    (``train.checkpoint.restore_raw``) and only the greedy subtree is
    touched, so the export works even when the full learner-state template
    is expensive to build (the raw read skips optimizer/replay
    reconstruction entirely).
    """
    from p2pmicrogrid_tpu.train.checkpoint import restore_raw

    raw, episode, step_path = restore_raw(ckpt_dir)
    return export_policy_bundle(
        cfg,
        raw,
        out_dir,
        source={"checkpoint": os.path.abspath(step_path), "episode": episode},
        dtype=dtype,
    )
