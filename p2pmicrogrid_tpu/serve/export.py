"""Policy bundles: frozen, versioned greedy-parameter exports for serving.

A training checkpoint (train/checkpoint.py) is the WHOLE learner state —
optimizers, replay rings, target copies, exploration schedules — because
resume needs all of it. Serving needs none of it: the greedy decision path
of every implementation reads exactly one parameter subtree (the Q-table,
the online Q-network, the deterministic actor). A *policy bundle* is that
subtree alone, frozen to disk next to a manifest that pins provenance
(config hash, git rev, implementation) and the serving contract (obs/action
spec, community size), so an engine can refuse mismatched inputs instead of
silently mis-serving.

Layout of a bundle directory::

    <dir>/manifest.json   kind="policy_bundle", format_version, provenance,
                          obs/action spec, model arch fields
    <dir>/params.npz      flat '/'-joined tree paths -> arrays

Size matters at the north star: a 1000-agent DDPG checkpoint carries actor +
critic + 2 targets + 2 Adam states + replay (~6x the actor alone before
replay); the bundle is the actor subtree, optionally dtype-cast (float16
halves it again). ``tools/check_artifacts_schema.py`` validates manifests.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional, Tuple

import numpy as np

BUNDLE_FORMAT_VERSION = 1
MANIFEST_FILE = "manifest.json"
PARAMS_FILE = "params.npz"

# The one parameter subtree each implementation's GREEDY path reads
# (tabular_act -> q_table; dqn_act -> online; ddpg greedy -> actor).
GREEDY_FIELD = {"tabular": "q_table", "dqn": "online", "ddpg": "actor"}

# On-disk dtypes for floating leaves. bfloat16 is deliberately absent: numpy
# cannot persist it natively and a bit-punned encoding would make bundles
# unreadable without this codebase — float16 is the compact option.
EXPORT_DTYPES = ("float32", "float16")

OBS_SPEC = {
    "dim": 4,
    "features": ["time_norm", "norm_temp", "norm_balance", "p2p_mean"],
}


def _path_key(entry) -> str:
    from jax.tree_util import DictKey, GetAttrKey, SequenceKey

    if isinstance(entry, DictKey):
        return str(entry.key)
    if isinstance(entry, GetAttrKey):
        return entry.name
    if isinstance(entry, SequenceKey):
        return str(entry.idx)
    return str(entry)


def _flatten_tree(tree) -> dict:
    """'/'-joined path -> np.ndarray for every leaf of a params pytree."""
    import jax

    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(_path_key(p) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _unflatten_tree(flat: dict) -> dict:
    """Inverse of ``_flatten_tree`` into plain nested dicts (what
    ``flax.linen.Module.apply`` accepts as params)."""
    root: dict = {}
    for key, arr in flat.items():
        parts = key.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root


def greedy_params(implementation: str, pol_state):
    """Extract the greedy parameter subtree from a learner state.

    Accepts both live state objects (TabularState/DQNState/DDPGState/
    DDPGParams) and the raw field-keyed dicts orbax returns from a
    structure-free checkpoint read (``train.checkpoint.restore_raw``).
    Always returns a dict-rooted tree (the bare tabular array is wrapped)
    so the npz leaf paths are never empty.
    """
    try:
        field = GREEDY_FIELD[implementation]
    except KeyError:
        raise ValueError(
            f"unknown implementation {implementation!r}; "
            f"expected one of {sorted(GREEDY_FIELD)}"
        ) from None
    if isinstance(pol_state, dict):
        node = pol_state.get(field)
    else:
        node = getattr(pol_state, field, None)
    if node is None:
        have = (
            sorted(pol_state)
            if isinstance(pol_state, dict)
            else type(pol_state).__name__
        )
        raise ValueError(
            f"state has no {field!r} subtree for implementation "
            f"{implementation!r} (got {have}); is this the right checkpoint?"
        )
    return {field: node} if implementation == "tabular" else node


def _model_spec(cfg, implementation: str, flat_params: dict) -> dict:
    """Architecture fields the engine needs to rebuild the greedy forward
    pass exactly (bin counts for the tabular discretizer, hidden widths for
    the nets, the agent-shared flag for DDPG)."""
    if implementation == "tabular":
        return {"qlearning": dataclasses.asdict(cfg.qlearning)}
    if implementation == "dqn":
        return {"hidden": cfg.dqn.hidden}
    # ddpg: a per-agent actor stacks a leading [A] axis on every Dense
    # kernel (ndim 3); the agent-shared actor is unbatched (ndim 2). Detect
    # from the exported params, not cfg — an eval-path restore may have
    # broadcast a shared checkpoint onto per-agent stacks already.
    kernel = flat_params.get("Dense_0/kernel")
    share = kernel is not None and kernel.ndim == 2
    return {"actor_hidden": cfg.ddpg.actor_hidden, "share_across_agents": share}


def _action_spec(implementation: str) -> dict:
    if implementation in ("tabular", "dqn"):
        return {
            "type": "discrete",
            "values": [0.0, 0.5, 1.0],  # models/dqn.py ACTION_VALUES
            "semantics": "heat-pump power fraction",
        }
    return {
        "type": "continuous",
        "low": 0.0,
        "high": 1.0,
        "semantics": "heat-pump power fraction",
    }


def export_policy_bundle(
    cfg,
    pol_state,
    out_dir: str,
    source: Optional[dict] = None,
    dtype: str = "float32",
) -> str:
    """Freeze ``pol_state``'s greedy parameters into a bundle at ``out_dir``.

    ``source`` (e.g. ``{"checkpoint": dir, "episode": n}``) is recorded
    verbatim in the manifest for provenance. ``dtype`` casts floating leaves
    on disk (``float16`` halves the bundle; integer leaves are untouched).
    Note that a float16 export QUANTIZES the parameters — the engine's
    bit-identical-to-checkpoint guarantee for discrete policies holds for
    float32 bundles (the default); a float16 Q-table can collapse near-tied
    action values and flip an argmax. Returns ``out_dir``.
    """
    from p2pmicrogrid_tpu.telemetry import config_hash
    from p2pmicrogrid_tpu.telemetry.registry import git_rev

    if dtype not in EXPORT_DTYPES:
        raise ValueError(f"dtype must be one of {EXPORT_DTYPES}, got {dtype!r}")
    impl = cfg.train.implementation
    params = greedy_params(impl, pol_state)
    flat = _flatten_tree(params)
    cast = np.dtype(dtype)
    flat = {
        k: (v.astype(cast) if np.issubdtype(v.dtype, np.floating) else v)
        for k, v in flat.items()
    }

    os.makedirs(out_dir, exist_ok=True)
    np.savez(os.path.join(out_dir, PARAMS_FILE), **flat)
    manifest = {
        "kind": "policy_bundle",
        "format_version": BUNDLE_FORMAT_VERSION,
        "created": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "implementation": impl,
        "n_agents": cfg.sim.n_agents,
        "setting": cfg.setting,
        "config_hash": config_hash(cfg),
        "git_rev": git_rev(),
        "dtype": dtype,
        "obs_spec": dict(OBS_SPEC),
        "action_spec": _action_spec(impl),
        "model": _model_spec(cfg, impl, flat),
        "params_file": PARAMS_FILE,
        "param_count": int(sum(v.size for v in flat.values())),
        "param_bytes": int(sum(v.nbytes for v in flat.values())),
        "source": source,
    }
    with open(os.path.join(out_dir, MANIFEST_FILE), "w") as f:
        json.dump(manifest, f, indent=2)
    return out_dir


def load_policy_bundle(bundle_dir: str) -> Tuple[dict, dict]:
    """(manifest, nested params dict of np arrays) from a bundle directory.

    Refuses bundles written by a NEWER format version — fields this reader
    does not understand could change greedy semantics silently.
    """
    mpath = os.path.join(bundle_dir, MANIFEST_FILE)
    if not os.path.exists(mpath):
        raise FileNotFoundError(f"no {MANIFEST_FILE} under {bundle_dir}")
    with open(mpath) as f:
        manifest = json.load(f)
    if manifest.get("kind") != "policy_bundle":
        raise ValueError(
            f"{mpath} is not a policy bundle manifest "
            f"(kind={manifest.get('kind')!r})"
        )
    version = manifest.get("format_version")
    if not isinstance(version, int) or version > BUNDLE_FORMAT_VERSION:
        raise ValueError(
            f"bundle {bundle_dir} has format_version {version!r}; this "
            f"reader understands <= {BUNDLE_FORMAT_VERSION} — upgrade the "
            "serving code, do not guess at a newer format"
        )
    ppath = os.path.join(bundle_dir, manifest.get("params_file", PARAMS_FILE))
    with np.load(ppath) as z:
        flat = {k: z[k] for k in z.files}
    return manifest, _unflatten_tree(flat)


def export_bundle_from_checkpoint(
    cfg,
    ckpt_dir: str,
    out_dir: str,
    dtype: str = "float32",
) -> str:
    """Export the newest checkpoint step under ``ckpt_dir`` as a bundle.

    Template-free: the checkpoint is read structure-free
    (``train.checkpoint.restore_raw``) and only the greedy subtree is
    touched, so the export works even when the full learner-state template
    is expensive to build (the raw read skips optimizer/replay
    reconstruction entirely).
    """
    from p2pmicrogrid_tpu.train.checkpoint import restore_raw

    raw, episode, step_path = restore_raw(ckpt_dir)
    return export_policy_bundle(
        cfg,
        raw,
        out_dir,
        source={"checkpoint": os.path.abspath(step_path), "episode": episode},
        dtype=dtype,
    )
