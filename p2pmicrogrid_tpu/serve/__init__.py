"""Serving layer: exported policy bundles + batched TPU inference engine.

Training produces a learner-state checkpoint (optimizers, replay rings,
target copies — everything resume needs); serving needs none of that. This
package is the deployment half of the paper's decision loop — each
15-minute slot every household needs a greedy heat-pump action from the
trained policy given its observation:

* ``export``   freeze a checkpoint's GREEDY parameters into a versioned
               on-disk policy bundle (manifest + npz).
* ``engine``   load a bundle and serve ``act(obs_batch)`` through
               power-of-two padding buckets of pre-compiled programs, with
               stateful per-household sessions and a microbatching queue.
* ``loadgen``  open-loop Poisson request streams + latency/throughput/
               padding-waste reporting (the ``serve-bench`` CLI command),
               plus the wire-level network mode (``serve-bench --network``).
* ``registry`` multi-bundle routing table keyed by manifest config_hash:
               atomic hot-swap, percentage-split A/B, household pinning.
* ``gateway``  the network front (``serve-gateway`` CLI): asyncio HTTP/1.1
               endpoints bridging remote households into the microbatch
               queue, with admission control and drain-before-close.
* ``router``   the fleet tier (``serve-bench --fleet``): consistent-hash
               routing of households over N gateway replicas with health
               probing, retry/failover/re-pinning, retry budgets,
               two-phase fleet-wide hot-swap and aggregated fleet stats.
* ``faults``   deterministic, seed-driven fault injection (kill/restart,
               stall, 500s, connection drops, payload corruption) so
               chaos runs replay exactly (``serve-bench --fleet --chaos``).
* ``wire``     the persistent multiplexed transport: length-prefixed JSON
               frames with request ids over keep-alive connections
               (client pool with reconnect + idempotent replay; the
               shared server accept-loop body).
* ``auth``     trust termination: HMAC-signed per-household bearer tokens
               (``serve-token`` CLI) and stdlib-``ssl`` TLS helpers
               (test certs via the system openssl).
* ``procfleet`` real-subprocess replicas under a relaunch supervisor —
               ``serve-bench --fleet --process`` measures SLOs through
               actual SIGKILLs and OS process boundaries.
* ``proxy``    the router as a standalone proxy process (``serve-router``
               CLI): TLS + auth terminate at the fleet front, not in the
               client library.
"""

from p2pmicrogrid_tpu.serve.continuous import (
    ContinuousBatcher,
    serve_bench_continuous_compare,
)
from p2pmicrogrid_tpu.serve.engine import (
    MicroBatchQueue,
    PolicyEngine,
    Sessions,
)
from p2pmicrogrid_tpu.serve.faults import (
    FaultEvent,
    FaultInjector,
    FaultPlan,
    FaultSchedule,
    kill_restart_plan,
)
from p2pmicrogrid_tpu.serve.gateway import (
    AdmissionConfig,
    GatewayServer,
    ServeGateway,
    build_gateway,
    build_registry,
)
from p2pmicrogrid_tpu.serve.export import (
    BUNDLE_FORMAT_VERSION,
    export_bundle_from_checkpoint,
    export_policy_bundle,
    load_policy_bundle,
)
from p2pmicrogrid_tpu.serve.loadgen import (
    RetryBudget,
    RetryPolicy,
    bursty_arrivals,
    make_arrivals,
    plan_open_loop,
    poisson_arrivals,
    run_network_loadgen,
    serve_bench,
    serve_bench_network,
)
from p2pmicrogrid_tpu.serve.auth import (
    AuthError,
    TokenAuthenticator,
    ensure_test_certs,
    client_ssl_context,
    generate_secret,
    load_secret,
    load_secret_chain,
    mint_token,
    rotate_secret,
    server_ssl_context,
    verify_token,
)
from p2pmicrogrid_tpu.serve.loadgen import serve_bench_wire_compare
from p2pmicrogrid_tpu.serve.procfleet import ProcessFleet
from p2pmicrogrid_tpu.serve.promotion import (
    CanaryBudgets,
    CanaryController,
    CanaryResult,
    GateBudgets,
    GateVerdict,
    StageTraffic,
    evaluate_bundle_cost,
    make_crafted_bundle,
    promotion_bench,
    run_promotion_gate,
    run_promotion_pipeline,
)
from p2pmicrogrid_tpu.serve.proxy import ProxyServer, RouterProxy
from p2pmicrogrid_tpu.serve.registry import BundleRegistry, ServingBundle
from p2pmicrogrid_tpu.serve.wire import (
    MuxConnection,
    MuxPool,
    SyncMuxProbe,
    WireProtocolError,
    encode_frame,
    read_frame,
)
from p2pmicrogrid_tpu.serve.router import (
    ConsistentHashRing,
    FleetRouter,
    FleetSwapError,
    LocalFleet,
    NoHealthyReplicas,
    Replica,
    RouterResult,
    run_fleet_loadgen,
    serve_bench_fleet,
)

__all__ = [
    "AdmissionConfig",
    "AuthError",
    "BUNDLE_FORMAT_VERSION",
    "BundleRegistry",
    "CanaryBudgets",
    "CanaryController",
    "CanaryResult",
    "ConsistentHashRing",
    "ContinuousBatcher",
    "GateBudgets",
    "GateVerdict",
    "StageTraffic",
    "FaultEvent",
    "FaultInjector",
    "FaultPlan",
    "FaultSchedule",
    "FleetRouter",
    "FleetSwapError",
    "GatewayServer",
    "LocalFleet",
    "MicroBatchQueue",
    "MuxConnection",
    "MuxPool",
    "NoHealthyReplicas",
    "PolicyEngine",
    "ProcessFleet",
    "ProxyServer",
    "Replica",
    "RetryBudget",
    "RetryPolicy",
    "RouterProxy",
    "RouterResult",
    "ServeGateway",
    "ServingBundle",
    "Sessions",
    "SyncMuxProbe",
    "TokenAuthenticator",
    "WireProtocolError",
    "build_gateway",
    "build_registry",
    "bursty_arrivals",
    "client_ssl_context",
    "encode_frame",
    "ensure_test_certs",
    "evaluate_bundle_cost",
    "export_bundle_from_checkpoint",
    "export_policy_bundle",
    "generate_secret",
    "kill_restart_plan",
    "make_crafted_bundle",
    "promotion_bench",
    "run_promotion_gate",
    "run_promotion_pipeline",
    "load_policy_bundle",
    "load_secret",
    "load_secret_chain",
    "make_arrivals",
    "mint_token",
    "rotate_secret",
    "plan_open_loop",
    "poisson_arrivals",
    "read_frame",
    "run_fleet_loadgen",
    "run_network_loadgen",
    "serve_bench",
    "serve_bench_continuous_compare",
    "serve_bench_fleet",
    "serve_bench_network",
    "serve_bench_wire_compare",
    "server_ssl_context",
    "verify_token",
]
