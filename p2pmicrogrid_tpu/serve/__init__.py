"""Serving layer: exported policy bundles + batched TPU inference engine.

Training produces a learner-state checkpoint (optimizers, replay rings,
target copies — everything resume needs); serving needs none of that. This
package is the deployment half of the paper's decision loop — each
15-minute slot every household needs a greedy heat-pump action from the
trained policy given its observation:

* ``export``   freeze a checkpoint's GREEDY parameters into a versioned
               on-disk policy bundle (manifest + npz).
* ``engine``   load a bundle and serve ``act(obs_batch)`` through
               power-of-two padding buckets of pre-compiled programs, with
               stateful per-household sessions and a microbatching queue.
* ``loadgen``  open-loop Poisson request streams + latency/throughput/
               padding-waste reporting (the ``serve-bench`` CLI command),
               plus the wire-level network mode (``serve-bench --network``).
* ``registry`` multi-bundle routing table keyed by manifest config_hash:
               atomic hot-swap, percentage-split A/B, household pinning.
* ``gateway``  the network front (``serve-gateway`` CLI): asyncio HTTP/1.1
               endpoints bridging remote households into the microbatch
               queue, with admission control and drain-before-close.
"""

from p2pmicrogrid_tpu.serve.engine import (
    MicroBatchQueue,
    PolicyEngine,
    Sessions,
)
from p2pmicrogrid_tpu.serve.gateway import (
    AdmissionConfig,
    GatewayServer,
    ServeGateway,
    build_gateway,
)
from p2pmicrogrid_tpu.serve.export import (
    BUNDLE_FORMAT_VERSION,
    export_bundle_from_checkpoint,
    export_policy_bundle,
    load_policy_bundle,
)
from p2pmicrogrid_tpu.serve.loadgen import (
    plan_open_loop,
    poisson_arrivals,
    run_network_loadgen,
    serve_bench,
    serve_bench_network,
)
from p2pmicrogrid_tpu.serve.registry import BundleRegistry, ServingBundle

__all__ = [
    "AdmissionConfig",
    "BUNDLE_FORMAT_VERSION",
    "BundleRegistry",
    "GatewayServer",
    "MicroBatchQueue",
    "PolicyEngine",
    "ServeGateway",
    "ServingBundle",
    "Sessions",
    "build_gateway",
    "export_bundle_from_checkpoint",
    "export_policy_bundle",
    "load_policy_bundle",
    "plan_open_loop",
    "poisson_arrivals",
    "run_network_loadgen",
    "serve_bench",
    "serve_bench_network",
]
