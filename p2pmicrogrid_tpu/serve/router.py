"""Resilient fleet tier: consistent-hash routing over N gateway replicas.

One gateway process (serve/gateway.py) fronts one host's engines — a
single point of failure for the whole community. This module is the tier
above it, sized for the paper's deployment story (millions of households
deciding every 15-minute slot):

* **Consistent-hash routing.** Households map onto a ring of replica
  virtual nodes by the same deterministic sha256 household hash
  ``serve/registry.py`` uses for A/B splits. Losing a replica moves ONLY
  the households that hashed to it (they slide clockwise to the next
  healthy replica); every other household keeps its replica — and with it
  the warm per-household session/affinity state that replica holds.

* **Health: active probes + passive signals.** A prober sweeps each
  replica's ``/readyz`` on an interval; ``fail_threshold`` consecutive
  failures eject a replica from routing, ``ok_threshold`` consecutive
  successes re-admit it. Request-path transport errors and 5xx responses
  feed the same consecutive counters, so a crashed replica stops
  receiving traffic after a handful of failed requests — typically well
  before the next probe sweep notices.

* **Retry discipline** (``loadgen.RetryPolicy``): per-request deadline,
  capped jittered exponential backoff, server ``Retry-After`` honored,
  and a token-bucket ``RetryBudget`` so a fleet-wide brown-out degrades
  to ~budget-ratio extra load instead of a retry storm. A replica dying
  mid-request fails over: the failed replica is excluded for the rest of
  that request, the household re-routes to the next healthy replica on
  the ring, and a success there RE-PINS the household (it stays on its
  failover target — flapping back the moment the original recovers would
  tear warm session state twice).

* **Graceful degradation.** No healthy replica, or a retry the budget
  refuses: the router sheds locally — an immediate 503 with
  ``Retry-After`` — rather than queueing unboundedly in front of a fleet
  that cannot absorb the load.

* **Two-phase fleet swap.** ``swap_fleet(config_hash)`` pushes
  ``POST /admin/swap`` to every healthy replica, then verifies each
  replica's ``/readyz`` reports the new ``config_hash`` before declaring
  the flip (failed pushes/verifies roll the pushed replicas back). Each
  per-replica swap is atomic and in-flight requests finish on the bundle
  that admitted them, so a fleet-wide swap drops zero requests.

* **One fleet view.** ``fleet_stats()`` aggregates per-replica
  ``GET /stats`` into a single snapshot; router counters (ejections,
  failovers, retries, backoff time, sheds) stream through the attached
  ``Telemetry`` into the SQLite warehouse next to the per-bundle serve
  traces (``data/results.py::FLEET_VIEW_SQL`` joins them back together).

``LocalFleet`` runs N in-process replicas (each its own engines + queues +
asyncio loop thread) with kill/restart hooks for the deterministic fault
harness (serve/faults.py); ``serve_bench_fleet`` drives the open-loop
Poisson loadgen through the router over a live fleet while a fault plan
kills and restarts replicas mid-run — the ``serve-bench --fleet --chaos``
CLI and the committed ``FLEET_*.jsonl`` captures.
"""

from __future__ import annotations

import asyncio
import bisect
import hashlib
import http.client
import json
import random
import threading
import time
import weakref
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from p2pmicrogrid_tpu.serve.faults import FaultInjector, FaultPlan, FaultSchedule
from p2pmicrogrid_tpu.serve.loadgen import (
    RetryBudget,
    RetryPolicy,
    _http_post_json,
    _http_request_json,
    _retry_after_s,
    make_arrivals,
    synthetic_obs,
)
from p2pmicrogrid_tpu.serve.wire import (
    FrameTooLarge,
    MuxPool,
    SyncMuxProbe,
    WireProtocolError,
)
from p2pmicrogrid_tpu.telemetry.tracing import (
    TraceContext,
    record_span,
    root_context,
)

# WireProtocolError covers a peer answering malformed frames (version
# skew, corruption): act() must score it as one failed request, never let
# it escape and crash the caller's gather.
_TRANSPORT_ERRORS = (
    ConnectionError, OSError, EOFError, ValueError,
    asyncio.TimeoutError, asyncio.IncompleteReadError, WireProtocolError,
)

# Client errors that re-routing or retrying cannot fix: the REQUEST (or its
# credential) is wrong, not the replica. 401/403 matter here: a rejected
# bearer must be terminal — it never consumes the retry budget, so garbage
# credentials cannot starve the budget honest retries depend on.
_TERMINAL_CLIENT_STATUSES = (400, 401, 403, 404, 405, 413)


@dataclass(frozen=True)
class Replica:
    """One addressable gateway replica. ``mux_port`` is the persistent
    multiplexed listener (serve/wire.py) when the replica exposes one —
    the router prefers it; ``port`` stays the HTTP/1.1 compatibility
    endpoint (probes, swaps, stats)."""

    replica_id: str
    host: str
    port: int
    mux_port: Optional[int] = None


class NoHealthyReplicas(RuntimeError):
    """Every replica is ejected — the router must shed, not queue."""


class FleetSwapError(RuntimeError):
    """A two-phase fleet swap failed (pushed replicas were rolled back)."""


# -- consistent-hash ring ------------------------------------------------------


def _ring_point(key: str) -> int:
    """Deterministic 64-bit ring position (stable across processes —
    hashlib, not the salted builtin ``hash``)."""
    return int.from_bytes(hashlib.sha256(key.encode()).digest()[:8], "big")


class ConsistentHashRing:
    """Classic consistent hashing with virtual nodes.

    Each replica owns ``vnodes`` points; a key routes to the first point
    clockwise. ``vnodes`` trades balance for lookup-table size: at 64
    vnodes a 3-replica ring splits keys within a few percent of evenly.
    ``lookup(key, accept)`` walks clockwise past points whose replica the
    predicate rejects — the consistent-hashing failover rule that moves
    ONLY the rejected replica's keys, to their next-clockwise survivor.
    """

    def __init__(self, vnodes: int = 64):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = vnodes
        self._points: List[int] = []       # sorted vnode positions
        self._owners: List[str] = []       # replica id per point
        self._replicas: set = set()

    def add(self, replica_id: str) -> None:
        if replica_id in self._replicas:
            raise ValueError(f"replica {replica_id!r} already on the ring")
        self._replicas.add(replica_id)
        for v in range(self.vnodes):
            point = _ring_point(f"{replica_id}#{v}")
            i = bisect.bisect_left(self._points, point)
            self._points.insert(i, point)
            self._owners.insert(i, replica_id)

    def remove(self, replica_id: str) -> None:
        if replica_id not in self._replicas:
            raise KeyError(f"replica {replica_id!r} not on the ring")
        self._replicas.discard(replica_id)
        keep = [
            (p, o)
            for p, o in zip(self._points, self._owners)
            if o != replica_id
        ]
        self._points = [p for p, _ in keep]
        self._owners = [o for _, o in keep]

    def __len__(self) -> int:
        return len(self._replicas)

    def lookup(
        self, key: str, accept: Optional[Callable[[str], bool]] = None
    ) -> Optional[str]:
        """First replica clockwise from ``key`` whose id passes
        ``accept`` (default: any). None on an empty/filtered-out ring."""
        if not self._points:
            return None
        start = bisect.bisect_right(self._points, _ring_point(key))
        n = len(self._points)
        seen: set = set()
        for step in range(n):
            owner = self._owners[(start + step) % n]
            if owner in seen:
                continue
            if accept is None or accept(owner):
                return owner
            seen.add(owner)
        return None


# -- router --------------------------------------------------------------------


@dataclass
class _ReplicaState:
    replica: Replica
    healthy: bool = True
    consecutive_fail: int = 0
    consecutive_ok: int = 0
    ejections: int = 0
    last_error: str = ""


@dataclass
class RouterResult:
    """One routed request's outcome."""

    status: int                      # final HTTP status (-1 transport, 503 shed)
    actions: Optional[list] = None
    config_hash: Optional[str] = None
    replica_id: Optional[str] = None
    retries: int = 0
    failovers: int = 0
    shed: bool = False               # the ROUTER refused (budget/no replicas)
    retry_after_s: Optional[float] = None
    error: Optional[str] = None
    gave_up: bool = False

    @property
    def ok(self) -> bool:
        return self.status == 200


class FleetRouter:
    """Client-side fleet front: consistent-hash routing + health + retry.

    Thread-safe: routing state is lock-held, ``act`` runs on an asyncio
    loop while the prober thread updates health concurrently.
    """

    def __init__(
        self,
        replicas: Sequence[Replica],
        retry: Optional[RetryPolicy] = None,
        budget: Optional[RetryBudget] = None,
        vnodes: int = 64,
        fail_threshold: int = 3,
        ok_threshold: int = 2,
        probe_timeout_s: float = 2.0,
        request_timeout_s: float = 30.0,
        shed_retry_after_s: float = 1.0,
        telemetry=None,
        jitter_seed: int = 0,
        ssl_context=None,
        token: Optional[str] = None,
        transport: str = "auto",
        mux_pool_size: int = 2,
        mux_max_frame_bytes: Optional[int] = None,
        probe_transport: str = "auto",
    ):
        if not replicas:
            raise ValueError("pass at least one replica")
        if transport not in ("auto", "http", "mux"):
            raise ValueError(
                f"transport must be 'auto', 'http' or 'mux', got {transport!r}"
            )
        if probe_transport not in ("auto", "http", "mux"):
            raise ValueError(
                "probe_transport must be 'auto', 'http' or 'mux', got "
                f"{probe_transport!r}"
            )
        self.retry = retry or RetryPolicy()
        self.budget = budget or RetryBudget()
        self.fail_threshold = fail_threshold
        self.ok_threshold = ok_threshold
        self.probe_timeout_s = probe_timeout_s
        self.request_timeout_s = request_timeout_s
        self.shed_retry_after_s = shed_retry_after_s
        self.telemetry = telemetry
        # Trust termination toward the replicas: a client SSLContext when
        # the fleet serves TLS, and the router's own bearer (normally the
        # operator wildcard — it must probe /stats and push /admin/swap).
        self.ssl_context = ssl_context
        self.token = token
        # 'auto' uses a replica's mux listener when it advertises one and
        # falls back to per-request HTTP; 'http'/'mux' force a wire.
        self.transport = transport
        self.mux_pool_size = mux_pool_size
        # MUST match the replicas' admission.max_body_bytes when that is
        # configured below the 1 MiB wire default: the client-side cap is
        # what turns an over-cap request into a terminal 413 here — with
        # a larger client cap the server drains + answers an id-less 413
        # the pool cannot attribute, and the request dies as a timeout
        # that (wrongly) penalizes replica health.
        self.mux_max_frame_bytes = mux_max_frame_bytes
        # Mux pools are event-loop-bound (asyncio futures); tests drive
        # act() through many short-lived loops, so pools key on the loop
        # weakly — a dead loop's pools (and their sockets) fall away with
        # it instead of poisoning the next loop's requests.
        self._mux_pools: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )
        # Probe sweeps reuse ONE persistent framed connection per replica
        # (wire.SyncMuxProbe) instead of a fresh (TLS) handshake per
        # replica per sweep — the per-sweep cost that dominated at fleet
        # scale. 'auto' probes over mux when the replica advertises a
        # listener, HTTP otherwise; half-open connections fail the probe
        # via timeout/reset exactly like a dead HTTP endpoint would.
        self.probe_transport = probe_transport
        self._probe_conns: Dict[str, SyncMuxProbe] = {}
        self._lock = threading.RLock()
        self._ring = ConsistentHashRing(vnodes=vnodes)
        self._state: Dict[str, _ReplicaState] = {}
        self._order: List[str] = []
        missing = [r.replica_id for r in replicas if r.mux_port is None]
        if transport == "mux" and missing:
            # Fail at construction, not as per-request "transport errors"
            # that would eject every (healthy) replica and read as a
            # fleet-wide outage instead of a configuration mistake.
            raise ValueError(
                "transport='mux' but replica(s) advertise no "
                f"mux_port: {', '.join(missing)}"
            )
        if probe_transport == "mux" and missing:
            # Same construction-time refusal: a forced mux probe against a
            # mux-less replica would read as that replica being down
            # forever.
            raise ValueError(
                "probe_transport='mux' but replica(s) advertise no "
                f"mux_port: {', '.join(missing)}"
            )
        for r in replicas:
            self._state[r.replica_id] = _ReplicaState(replica=r)
            self._order.append(r.replica_id)
            self._ring.add(r.replica_id)
        self._pins: Dict[str, str] = {}   # household -> failover target
        # config_hash -> bundle_dir learned through register_fleet: what
        # lets the prober RE-register a runtime candidate on a relaunched
        # replica before re-pushing a missed fleet swap (_push_swap).
        self.known_bundles: Dict[str, str] = {}
        self.register_timeout_s = 180.0
        # In-flight per-replica re-register workers (_push_swap): the
        # engine compile a register costs must never block the prober.
        self._realigners: Dict[str, threading.Thread] = {}
        self._anon_rr = 0
        self._rng = random.Random(jitter_seed)
        self._prober: Optional[threading.Thread] = None
        self._prober_stop = threading.Event()
        self.fleet_config_hash: Optional[str] = None
        self.counters: Dict[str, float] = {
            "requests": 0, "retries": 0, "failovers": 0, "repins": 0,
            "ejections": 0, "readmissions": 0, "shed": 0,
            "budget_denied": 0, "corrupt_detected": 0, "swaps": 0,
            "swap_aligns": 0, "probes": 0, "backoff_ms": 0.0,
            "reconnects": 0, "auth_denied": 0, "registers": 0,
        }

    # -- counters / telemetry ------------------------------------------------

    def _bump(self, name: str, inc: float = 1) -> None:
        # Telemetry.counter is an unlocked read-modify-write; the router's
        # lock serializes the prober thread against the act() event loop so
        # the warehouse counters can't lose increments.
        with self._lock:
            self.counters[name] += inc
            if self.telemetry is not None:
                self.telemetry.counter(f"router.{name}", inc)

    # -- wire ----------------------------------------------------------------

    def _http_conn(self, rep: Replica, timeout_s: float):
        """A synchronous probe/stats connection honoring the fleet TLS."""
        if self.ssl_context is not None:
            return http.client.HTTPSConnection(
                rep.host, rep.port, timeout=timeout_s,
                context=self.ssl_context,
            )
        return http.client.HTTPConnection(rep.host, rep.port, timeout=timeout_s)

    def _auth_headers(self) -> dict:
        headers = {"Content-Type": "application/json"}
        if self.token is not None:
            headers["Authorization"] = f"Bearer {self.token}"
        return headers

    def _pool_for(self, rep: Replica) -> Optional[MuxPool]:
        """The replica's persistent mux pool on the RUNNING loop, or None
        when this replica (or the configured transport) is HTTP-only.
        (transport='mux' against mux-less replicas is rejected at
        construction, so the fall-through here is always intentional.)"""
        if self.transport == "http" or rep.mux_port is None:
            return None
        loop = asyncio.get_running_loop()
        pools = self._mux_pools.get(loop)
        if pools is None:
            pools = {}
            self._mux_pools[loop] = pools
        pool = pools.get(rep.replica_id)
        if pool is None:
            kw = {}
            if self.mux_max_frame_bytes is not None:
                kw["max_frame_bytes"] = self.mux_max_frame_bytes
            pool = MuxPool(
                rep.host, rep.mux_port, size=self.mux_pool_size,
                ssl=self.ssl_context,
                on_reconnect=lambda: self._bump("reconnects"),
                **kw,
            )
            pools[rep.replica_id] = pool
        return pool

    async def _post_act(
        self, rep: Replica, payload: dict, timeout_s: float,
        trace: Optional[str] = None,
    ):
        """(status, doc, headers) over the replica's preferred wire. Pool
        replay is OFF here: the router's own retry/failover loop is the
        retry authority — the pool reconnects, the router re-sends.
        ``trace`` is the encoded per-attempt trace context (mux frame
        field / HTTP header)."""
        pool = self._pool_for(rep)
        if pool is not None:
            return await pool.request(
                "/v1/act", payload, timeout_s, token=self.token,
                replay=False, trace=trace,
            )
        return await _http_post_json(
            rep.host, rep.port, "/v1/act", payload, timeout_s,
            ssl=self.ssl_context, token=self.token, trace=trace,
        )

    async def close_pools(self) -> None:
        """Close the RUNNING loop's mux pools (bench teardown). Pools on
        already-dead loops were dropped with their loops."""
        pools = self._mux_pools.get(asyncio.get_running_loop())
        if pools:
            for pool in list(pools.values()):
                await pool.close()
            pools.clear()

    # -- membership / health -------------------------------------------------

    @property
    def replica_ids(self) -> List[str]:
        with self._lock:
            return list(self._order)

    def replica(self, replica_id: str) -> Replica:
        with self._lock:
            return self._state[replica_id].replica

    def healthy_ids(self) -> List[str]:
        with self._lock:
            return [r for r in self._order if self._state[r].healthy]

    def is_healthy(self, replica_id: str) -> bool:
        with self._lock:
            return self._state[replica_id].healthy

    def mark_result(
        self, replica_id: str, ok: bool, error: str = ""
    ) -> None:
        """Feed one health observation (probe or request outcome) into a
        replica's consecutive counters; flips eject/re-admit at the
        thresholds."""
        with self._lock:
            st = self._state.get(replica_id)
            if st is None:
                return
            if ok:
                st.consecutive_ok += 1
                st.consecutive_fail = 0
                if (
                    not st.healthy
                    and st.consecutive_ok >= self.ok_threshold
                ):
                    st.healthy = True
                    readmitted = True
                else:
                    readmitted = False
                ejected = False
            else:
                st.consecutive_fail += 1
                st.consecutive_ok = 0
                st.last_error = error
                if (
                    st.healthy
                    and st.consecutive_fail >= self.fail_threshold
                ):
                    st.healthy = False
                    st.ejections += 1
                    ejected = True
                else:
                    ejected = False
                readmitted = False
        if ejected:
            self._bump("ejections")
        if readmitted:
            self._bump("readmissions")

    def probe_once(self) -> Dict[str, bool]:
        """One synchronous ``/readyz`` sweep over every replica; returns
        {replica_id: probe ok}. Drives eject/re-admit via mark_result —
        callable directly (tests, deterministic sweeps) or from the
        background prober."""
        results: Dict[str, bool] = {}
        for rid in self.replica_ids:
            rep = self.replica(rid)
            ok, error = self._probe(rep)
            results[rid] = ok
            self._bump("probes")
            self.mark_result(rid, ok, error=error)
        return results

    def _probe_conn_for(self, rep: Replica) -> SyncMuxProbe:
        """The replica's persistent probe connection (created lazily; it
        survives across sweeps — that persistence IS the point)."""
        with self._lock:
            conn = self._probe_conns.get(rep.replica_id)
            if conn is None:
                conn = SyncMuxProbe(
                    rep.host, rep.mux_port,
                    ssl_context=self.ssl_context,
                    timeout_s=self.probe_timeout_s,
                )
                self._probe_conns[rep.replica_id] = conn
        return conn

    def _probe_readyz(self, rep: Replica) -> Tuple[int, Optional[dict]]:
        """One ``GET /readyz`` over the probe transport: the replica's
        persistent mux connection when it advertises one (no fresh TLS
        handshake per sweep), a fresh HTTP connection otherwise. Raises
        OSError-family on transport failure — a half-open mux connection
        (SIGKILLed peer, stalled stream) surfaces as a timeout/reset here
        exactly like a dead HTTP endpoint."""
        use_mux = self.probe_transport == "mux" or (
            self.probe_transport == "auto" and rep.mux_port is not None
        )
        if use_mux:
            return self._probe_conn_for(rep).request("/readyz")
        conn = self._http_conn(rep, self.probe_timeout_s)
        try:
            conn.request("GET", "/readyz")
            resp = conn.getresponse()
            raw = resp.read()
            try:
                doc = json.loads(raw) if raw else {}
            except (UnicodeDecodeError, json.JSONDecodeError):
                doc = {}
            return resp.status, doc if isinstance(doc, dict) else None
        finally:
            conn.close()

    def _probe(self, rep: Replica) -> Tuple[bool, str]:
        try:
            status, doc = self._probe_readyz(rep)
            if status != 200:
                return False, f"/readyz answered {status}"
            with self._lock:
                fleet_hash = self.fleet_config_hash
            served = (doc or {}).get("config_hash")
            if fleet_hash and served and served != fleet_hash:
                # A replica that missed a fleet swap (killed/restarted
                # around it) must NOT be re-admitted on its stale default —
                # it would serve the old config to its households forever,
                # a silent half-swapped fleet. Push the swap so it
                # converges, and stay unready until a later probe verifies.
                self._push_swap(rep, fleet_hash)
                self._bump("swap_aligns")
                return False, (
                    f"/readyz config_hash {served} != fleet "
                    f"{fleet_hash} (swap re-pushed)"
                )
            return True, ""
        except (
            OSError, http.client.HTTPException, WireProtocolError,
        ) as err:
            return False, f"{type(err).__name__}: {err}"

    def _push_swap(self, rep: Replica, config_hash: str) -> None:
        """Best-effort synchronous ``/admin/swap`` push (probe thread).

        A 404 means the replica does not KNOW the hash — the process-mode
        failure the autopilot hits when a replica relaunches after a
        promotion: the fresh child only loaded its launch-time bundles,
        and the promoted candidate was registered at runtime. When the
        router learned the candidate's bundle dir (``register_fleet``
        records it in ``known_bundles``), it re-registers the bundle on
        the replica and re-pushes the swap — otherwise a crashed replica
        would resurrect the retired incumbent for its households forever."""
        status = self._admin_post_sync(
            rep, "/admin/swap", {"config_hash": config_hash}
        )
        if status == 404:
            with self._lock:
                bundle_dir = self.known_bundles.get(config_hash)
                # The register is an engine compile + warmup on the
                # replica (tens of seconds) — it must NOT run on the
                # probe thread, or one realigning replica freezes health
                # sweeps (and therefore ejection/failover) for the whole
                # fleet. One realign worker per replica at a time; the
                # replica stays unready until a later sweep verifies.
                busy = self._realigners.get(rep.replica_id)
                if bundle_dir is None or (busy is not None and
                                          busy.is_alive()):
                    return

                def realign() -> None:
                    reg = self._admin_post_sync(
                        rep, "/admin/register",
                        {"bundle_dir": bundle_dir},
                        timeout_s=self.register_timeout_s,
                    )
                    if reg == 200:
                        self._admin_post_sync(
                            rep, "/admin/swap",
                            {"config_hash": config_hash},
                        )

                worker = threading.Thread(target=realign, daemon=True)
                self._realigners[rep.replica_id] = worker
            worker.start()

    def _admin_post_sync(
        self, rep: Replica, path: str, payload: dict,
        timeout_s: Optional[float] = None,
    ) -> Optional[int]:
        """One synchronous admin POST (probe thread); returns the HTTP
        status or None on transport failure — best-effort, the caller's
        next probe sweep retries."""
        body = json.dumps(payload)
        conn = self._http_conn(rep, timeout_s or self.probe_timeout_s)
        try:
            conn.request(
                "POST", path, body=body, headers=self._auth_headers(),
            )
            resp = conn.getresponse()
            resp.read()
            return resp.status
        except (OSError, http.client.HTTPException):
            return None  # the replica stays unready; a later probe retries
        finally:
            conn.close()

    def start_probing(self, interval_s: float = 0.5) -> None:
        """Background prober: ``probe_once`` every ``interval_s``."""
        if self._prober is not None:
            raise RuntimeError("prober already running")
        self._prober_stop.clear()

        def run() -> None:
            while not self._prober_stop.wait(interval_s):
                self.probe_once()

        self._prober = threading.Thread(target=run, daemon=True)
        self._prober.start()

    def stop_probing(self) -> None:
        self._prober_stop.set()
        if self._prober is not None:
            self._prober.join(timeout=10.0)
            self._prober = None
        self.close_probe_conns()

    def close_probe_conns(self) -> None:
        """Close the persistent per-replica probe connections (teardown;
        the next probe_once reconnects on demand)."""
        with self._lock:
            conns = list(self._probe_conns.values())
            self._probe_conns.clear()
        for conn in conns:
            conn.close()

    # -- routing -------------------------------------------------------------

    def route(
        self, household: Optional[str], exclude: frozenset = frozenset()
    ) -> str:
        """The replica id serving this household right now.

        Ring lookup among healthy replicas, honoring a failover pin when
        its target is still usable. ``exclude`` is per-request state: the
        replicas that already failed THIS request — skipped unless that
        would leave nowhere to go. Anonymous requests round-robin over
        healthy replicas (hashing the constant empty key would pile all
        anonymous traffic onto one replica)."""
        with self._lock:
            healthy = [r for r in self._order if self._state[r].healthy]
            if not healthy:
                raise NoHealthyReplicas(
                    f"all {len(self._order)} replicas unhealthy"
                )
            candidates = [r for r in healthy if r not in exclude] or healthy
            if not household:
                rid = candidates[self._anon_rr % len(candidates)]
                self._anon_rr += 1
                return rid
            pinned = self._pins.get(household)
            if pinned is not None and pinned in candidates:
                return pinned
            allowed = set(candidates)
            rid = self._ring.lookup(household, accept=allowed.__contains__)
            if rid is None:  # unreachable: candidates is non-empty
                raise NoHealthyReplicas("ring lookup found no candidate")
            return rid

    def _record_route(self, household: Optional[str], rid: str) -> None:
        """After a SUCCESS on ``rid``: pin the household iff it is not on
        its home (pure-ring) replica. Pins are recorded only for failover
        placements, so the pin map grows with failovers, not with
        households; a household whose pin target dies re-pins on its next
        request, and one that lands home again drops its pin."""
        if not household:
            return
        repinned = False
        with self._lock:
            home = self._ring.lookup(household)
            if rid == home:
                self._pins.pop(household, None)
            elif self._pins.get(household) != rid:
                self._pins[household] = rid
                repinned = True
        if repinned:
            self._bump("repins")

    @property
    def pinned_count(self) -> int:
        with self._lock:
            return len(self._pins)

    def pinned_households(self, limit: int = 10_000) -> Dict[str, str]:
        """A snapshot of failover pins, CAPPED at ``limit`` entries
        (ROADMAP item 4): pins record only failover placements so the map
        stays small in steady state, but after a chaos storm at a
        million-household population an uncapped copy would materialize
        per-household state on every observability poll. ``pinned_count``
        is the O(1) total; pass a larger limit explicitly to widen the
        sample."""
        with self._lock:
            if len(self._pins) <= limit:
                return dict(self._pins)
            out: Dict[str, str] = {}
            for h, rid in self._pins.items():
                if len(out) >= limit:
                    break
                out[h] = rid
            return out

    # -- request path --------------------------------------------------------

    async def act(
        self,
        household: Optional[str],
        obs_row,
        deadline_s: Optional[float] = None,
        trace: Optional[TraceContext] = None,
    ) -> RouterResult:
        """Route one act request with retry/failover; never raises for
        server-side failure — the outcome (including router-side sheds)
        comes back as a ``RouterResult``.

        With a ``trace`` (telemetry/tracing.py ``TraceContext``), the
        whole retry/failover anatomy becomes spans in the attached
        telemetry's warehouse: one ``router.act`` root per request, a
        ``router.attempt`` child per try (attrs: replica_id, status,
        whether it was a failover hop) and a ``router.backoff`` child per
        sleep — and each attempt's child context rides the wire, so the
        server-side spans hang off the exact attempt that caused them."""
        policy = self.retry
        t0 = time.monotonic()
        t0_epoch = time.time()
        deadline = t0 + (
            deadline_s if deadline_s is not None else policy.deadline_s
        )

        def finish(result: RouterResult) -> RouterResult:
            if trace is not None and self.telemetry is not None:
                elapsed = time.monotonic() - t0
                record_span(
                    self.telemetry, trace, "router.act", t0_epoch, elapsed,
                    status=result.status, retries=result.retries,
                    failovers=result.failovers, replica_id=result.replica_id,
                    household=household,
                )
                self.telemetry.histogram(
                    "router.latency_ms", elapsed * 1e3,
                    trace_id=trace.trace_id,
                )
            return result
        # host-sync: caller-supplied host observation row, not device data.
        payload = {"obs": np.asarray(obs_row, dtype=np.float32).tolist()}
        if household:
            payload["household"] = household
        self._bump("requests")
        self.budget.on_attempt()
        exclude: set = set()
        prev_rid: Optional[str] = None
        tries = 0
        failovers = 0
        status, doc, headers = -1, None, {}
        rid = None
        while True:
            try:
                rid = self.route(household, exclude=frozenset(exclude))
            except NoHealthyReplicas as err:
                self._bump("shed")
                return finish(RouterResult(
                    status=503, shed=True,
                    retry_after_s=self.shed_retry_after_s,
                    error=str(err), retries=tries, failovers=failovers,
                ))
            was_failover = (
                prev_rid is not None and rid != prev_rid
                and prev_rid in exclude
            )
            if was_failover:
                # A failover is leaving a FAULTED replica — a 429 retry
                # that round-robins (anonymous traffic) or re-routes is
                # load balancing, not failover, and must not pollute the
                # failover_count SLO in committed captures.
                failovers += 1
                self._bump("failovers")
            rep = self.replica(rid)
            timeout = max(0.05, min(
                self.request_timeout_s, deadline - time.monotonic()
            ))
            # Per-attempt child context: deterministic from the root +
            # attempt index, encoded onto the wire so the replica's spans
            # hang off THIS attempt (not the request in the abstract).
            attempt_ctx = (
                trace.child(f"attempt{tries}") if trace is not None else None
            )
            t_att = time.monotonic()
            t_att_epoch = time.time()
            try:
                status, doc, headers = await self._post_act(
                    rep, payload, timeout,
                    trace=attempt_ctx.encode() if attempt_ctx else None,
                )
            except FrameTooLarge as err:
                # The REQUEST is over the wire cap — the mux mirror of an
                # HTTP 413: terminal client error, no health penalty, no
                # failover (the same payload would "fail" every replica
                # in turn and read as a fleet outage).
                return finish(RouterResult(
                    status=413, replica_id=rid, error=str(err),
                    retries=tries, failovers=failovers,
                ))
            except _TRANSPORT_ERRORS as err:
                status, doc, headers = -1, None, {}
                transport_error = f"{type(err).__name__}: {err}"
            else:
                transport_error = ""
            tries += 1
            corrupt = status == 200 and doc is None
            if corrupt:
                self._bump("corrupt_detected")
                status = -1
            if attempt_ctx is not None and self.telemetry is not None:
                record_span(
                    self.telemetry, attempt_ctx, "router.attempt",
                    t_att_epoch, time.monotonic() - t_att,
                    replica_id=rid, try_index=tries - 1, status=status,
                    failover=was_failover,
                    error=transport_error or None,
                )
            if status == 200:
                self.mark_result(rid, True)
                self._record_route(household, rid)
                return finish(RouterResult(
                    status=200,
                    actions=doc.get("actions"),
                    config_hash=doc.get("config_hash"),
                    replica_id=rid,
                    retries=tries - 1,
                    failovers=failovers,
                ))
            if status in _TERMINAL_CLIENT_STATUSES:
                # The REQUEST (or its credential) is bad, not the replica
                # — retrying the same payload elsewhere cannot help, and
                # auth rejections must never charge the retry budget.
                if status in (401, 403):
                    self._bump("auth_denied")
                return finish(RouterResult(
                    status=status, replica_id=rid,
                    error=(doc or {}).get("error"),
                    retries=tries - 1, failovers=failovers,
                ))
            if status == -1 or status >= 500 or corrupt:
                # Replica fault: feed health, fail over away from it for
                # the remainder of this request.
                self.mark_result(
                    rid, False,
                    error=transport_error or f"status {status}",
                )
                exclude.add(rid)
            # 429 = saturated-but-alive: no health penalty, no exclusion —
            # backing off and re-trying (possibly the same replica) is the
            # correct response to admission-control shed.
            prev_rid = rid
            now = time.monotonic()
            if tries >= policy.max_attempts or now >= deadline:
                break
            if not self.budget.try_spend():
                # Budget-governed degradation: a brown-out must not turn
                # into a retry storm. Shed at the router with Retry-After.
                self._bump("budget_denied")
                self._bump("shed")
                return finish(RouterResult(
                    status=503, shed=True,
                    retry_after_s=self.shed_retry_after_s,
                    error="retry budget exhausted",
                    replica_id=rid, retries=tries - 1,
                    failovers=failovers, gave_up=True,
                ))
            with self._lock:
                backoff = policy.backoff_s(
                    tries - 1, self._rng, _retry_after_s(headers)
                )
            if now + backoff >= deadline:
                break
            self._bump("retries")
            self._bump("backoff_ms", backoff * 1e3)
            await asyncio.sleep(backoff)
            if trace is not None and self.telemetry is not None:
                record_span(
                    self.telemetry, trace.child(f"backoff{tries - 1}"),
                    "router.backoff", time.time() - backoff, backoff,
                    try_index=tries - 1,
                )
        return finish(RouterResult(
            status=status, replica_id=rid,
            error=(doc or {}).get("error") if isinstance(doc, dict) else None,
            retries=tries - 1, failovers=failovers,
            retry_after_s=_retry_after_s(headers),
            gave_up=tries > 1,
        ))

    # -- fleet orchestration -------------------------------------------------

    async def _get_json(
        self, rep: Replica, path: str, timeout_s: float
    ) -> Tuple[int, Optional[dict]]:
        """Async GET over a fresh connection (swap verify) — delegates
        the wire framing to loadgen's one shared HTTP client."""
        status, doc, _ = await _http_request_json(
            rep.host, rep.port, "GET", path, None, timeout_s,
            ssl=self.ssl_context, token=self.token,
        )
        return status, doc

    async def swap_fleet(
        self,
        config_hash: str,
        timeout_s: float = 10.0,
        poll_interval_s: float = 0.05,
    ) -> dict:
        """Two-phase fleet-wide hot-swap: push ``/admin/swap`` to every
        healthy replica, verify each ``/readyz`` reports the new
        ``config_hash``, then flip (clear failover pins, record the fleet
        hash). Any push/verify failure rolls the pushed replicas back to
        their previous defaults and raises ``FleetSwapError`` — the fleet
        is never left half-swapped. Zero requests drop: each per-replica
        swap is atomic and in-flight requests finish on the bundle that
        admitted them."""
        targets = [
            (rid, self.replica(rid)) for rid in self.healthy_ids()
        ]
        if not targets:
            raise FleetSwapError("no healthy replicas to swap")
        previous: Dict[str, Optional[str]] = {}
        for rid, rep in targets:
            try:
                _, doc = await self._get_json(rep, "/readyz", timeout_s)
            except _TRANSPORT_ERRORS as err:
                raise FleetSwapError(
                    f"{rid}: unreachable before swap ({err})"
                ) from None
            previous[rid] = (doc or {}).get("config_hash")
        pushed: List[str] = []
        try:
            for rid, rep in targets:
                try:
                    status, doc, _ = await _http_post_json(
                        rep.host, rep.port, "/admin/swap",
                        {"config_hash": config_hash}, timeout_s,
                        ssl=self.ssl_context, token=self.token,
                    )
                except _TRANSPORT_ERRORS as err:
                    raise FleetSwapError(
                        f"{rid}: swap push failed ({err})"
                    ) from None
                if status != 200:
                    raise FleetSwapError(
                        f"{rid}: swap push answered {status}: "
                        f"{(doc or {}).get('error')}"
                    )
                pushed.append(rid)
            for rid, rep in targets:
                end = time.monotonic() + timeout_s
                while True:
                    try:
                        status, doc = await self._get_json(
                            rep, "/readyz", timeout_s
                        )
                    except _TRANSPORT_ERRORS:
                        status, doc = -1, None
                    if (
                        status == 200
                        and (doc or {}).get("config_hash") == config_hash
                    ):
                        break
                    if time.monotonic() >= end:
                        raise FleetSwapError(
                            f"{rid}: /readyz never confirmed "
                            f"{config_hash} (last: {doc})"
                        )
                    await asyncio.sleep(poll_interval_s)
        except FleetSwapError:
            # Roll back best-effort: a half-swapped fleet double-serves
            # configs indefinitely; a rolled-back fleet is merely stale.
            for rid in pushed:
                prev = previous.get(rid)
                if prev and prev != config_hash:
                    rep = self.replica(rid)
                    try:
                        await _http_post_json(
                            rep.host, rep.port, "/admin/swap",
                            {"config_hash": prev}, timeout_s,
                            ssl=self.ssl_context, token=self.token,
                        )
                    except _TRANSPORT_ERRORS:
                        pass
            raise
        with self._lock:
            # Mirror the per-gateway swap semantics fleet-wide: every
            # household re-routes fresh against the new default.
            self._pins.clear()
            self.fleet_config_hash = config_hash
        self._bump("swaps")
        if self.telemetry is not None:
            self.telemetry.event(
                "fleet_swap", config_hash=config_hash,
                replicas=[rid for rid, _ in targets],
            )
        return {
            "config_hash": config_hash,
            "replicas": [rid for rid, _ in targets],
            "previous": previous,
        }

    # -- fleet-wide candidate lifecycle (ISSUE 11) ---------------------------

    async def _admin_post(
        self, rep: Replica, path: str, payload: dict, timeout_s: float
    ):
        return await _http_post_json(
            rep.host, rep.port, path, payload, timeout_s,
            ssl=self.ssl_context, token=self.token,
        )

    async def register_fleet(
        self, bundle_dir: str, timeout_s: float = 180.0
    ) -> str:
        """Push ``/admin/register {bundle_dir}`` to every healthy replica
        (the bundle dir must be reachable from the replica processes — a
        shared filesystem, which one-host fleets trivially have). ALL
        replicas must load it; any failure unregisters the bundle from the
        replicas that did (best-effort) and raises ``FleetSwapError`` — a
        candidate half-known to the fleet would turn the later split/swap
        pushes into partial failures. Returns the registered config_hash.
        The generous timeout covers an engine compile + warmup per
        replica. Idempotent: replicas already serving the hash answer
        ``already_registered``."""
        targets = [(rid, self.replica(rid)) for rid in self.healthy_ids()]
        if not targets:
            raise FleetSwapError("no healthy replicas to register on")

        # Concurrent pushes: a register costs an engine compile + warmup
        # PER REPLICA (the 180 s budget exists for it) — serial awaits
        # would multiply every canary phase's wall-clock by fleet size.
        async def push_one(rid: str, rep: Replica):
            try:
                status, doc, _ = await self._admin_post(
                    rep, "/admin/register",
                    {"bundle_dir": bundle_dir}, timeout_s,
                )
            except _TRANSPORT_ERRORS as err:
                return rid, None, f"register push failed ({err})"
            if status != 200:
                return rid, None, (
                    f"register answered {status}: "
                    f"{(doc or {}).get('error')}"
                )
            return rid, (doc or {}).get("config_hash"), None

        results = await asyncio.gather(
            *(push_one(rid, rep) for rid, rep in targets)
        )
        config_hash = next((h for _, h, _ in results if h), None)
        failures = [(rid, err) for rid, _, err in results if err]
        if failures:
            # All-or-nothing: roll the successes back (unregister is
            # idempotent, so pushing to every target is safe).
            if config_hash:
                await self.unregister_fleet(config_hash, timeout_s)
            raise FleetSwapError(
                "; ".join(f"{rid}: {err}" for rid, err in failures)
            )
        self._bump("registers")
        with self._lock:
            if config_hash:
                self.known_bundles[config_hash] = bundle_dir
        if self.telemetry is not None:
            self.telemetry.event(
                "fleet_register", config_hash=config_hash,
                bundle_dir=bundle_dir, replicas=[rid for rid, _ in targets],
            )
        return config_hash

    async def unregister_fleet(
        self, config_hash: str, timeout_s: float = 30.0
    ) -> dict:
        """Best-effort ``/admin/unregister`` on EVERY replica (healthy or
        not — an ejected replica that re-admits must not keep serving an
        orphaned candidate). Per-replica outcomes are returned, never
        raised: unregistration is cleanup, and cleanup retries are the
        caller's cadence loop."""
        return await self._admin_broadcast(
            "/admin/unregister", {"config_hash": config_hash}, timeout_s
        )

    async def _admin_broadcast(
        self, path: str, payload: dict, timeout_s: float
    ) -> Dict[str, str]:
        """One admin POST to EVERY replica concurrently, best-effort;
        per-replica outcomes, never raises (cleanup semantics)."""
        async def one(rid: str) -> tuple:
            rep = self.replica(rid)
            try:
                status, doc, _ = await self._admin_post(
                    rep, path, payload, timeout_s
                )
                return rid, (
                    "ok" if status == 200
                    else f"{status}: {(doc or {}).get('error')}"
                )
            except _TRANSPORT_ERRORS as err:
                return rid, f"unreachable: {err}"

        return dict(await asyncio.gather(
            *(one(rid) for rid in self.replica_ids)
        ))

    async def split_fleet(
        self, config_hash: str, percent: float, timeout_s: float = 10.0
    ) -> None:
        """Push the canary split to every healthy replica (clearing pins
        so the stage re-rolls household routing — the fleet analogue of
        ``registry.clear_pins`` + ``set_split``). Any failure rolls the
        split back off the replicas that took it and raises: a
        half-split fleet would expose the candidate to an unknown,
        unattributable traffic share."""
        targets = [(rid, self.replica(rid)) for rid in self.healthy_ids()]
        if not targets:
            raise FleetSwapError("no healthy replicas to split")
        payload = {
            "split": {"config_hash": config_hash, "percent": percent},
            "clear_pins": True,
        }

        async def push_one(rid: str, rep: Replica) -> tuple:
            try:
                status, doc, _ = await self._admin_post(
                    rep, "/admin/swap", payload, timeout_s
                )
            except _TRANSPORT_ERRORS as err:
                return rid, f"split push failed ({err})"
            if status != 200:
                return rid, (
                    f"split answered {status}: {(doc or {}).get('error')}"
                )
            return rid, None

        results = await asyncio.gather(
            *(push_one(rid, rep) for rid, rep in targets)
        )
        failures = [(rid, err) for rid, err in results if err]
        if failures:
            # Roll the split back off every replica that took it.
            await self._admin_broadcast(
                "/admin/swap", {"split": None, "clear_pins": True},
                timeout_s,
            )
            raise FleetSwapError(
                "; ".join(f"{rid}: {err}" for rid, err in failures)
            )

    async def clear_split_fleet(self, timeout_s: float = 10.0) -> dict:
        """Best-effort split + pin clear on EVERY replica, plus this
        router's own failover pins — the canary abort's routing reset.
        Returns per-replica outcomes (cleanup semantics, like
        ``unregister_fleet``)."""
        outcomes = await self._admin_broadcast(
            "/admin/swap", {"split": None, "clear_pins": True}, timeout_s
        )
        with self._lock:
            self._pins.clear()
        return outcomes

    async def clear_pins_fleet(self, timeout_s: float = 10.0) -> dict:
        """Best-effort household-pin clear on every replica (stage
        widening: re-roll routing without touching the split) + the
        router's failover pins."""
        outcomes = await self._admin_broadcast(
            "/admin/swap", {"clear_pins": True}, timeout_s
        )
        with self._lock:
            self._pins.clear()
        return outcomes

    async def flush_fleet(self, timeout_s: float = 30.0) -> dict:
        """Best-effort ``/admin/flush`` on every replica: buffered
        per-bundle telemetry lands in the warehouse before a canary
        stage's attribution read."""
        return await self._admin_broadcast("/admin/flush", {}, timeout_s)

    # -- observability -------------------------------------------------------

    def fleet_stats(self, timeout_s: float = 5.0) -> dict:
        """One aggregated fleet view over per-replica ``GET /stats``.

        Dead replicas appear with an ``error`` instead of a snapshot; the
        totals sum whatever answered. Emitted as a ``fleet_stats`` event
        through the router telemetry (-> warehouse) when attached."""
        per_replica: Dict[str, dict] = {}
        processes: Dict[str, dict] = {}
        totals = {
            "requests": 0, "act_requests": 0, "act_ok": 0, "act_rows": 0,
            "shed": 0, "http_errors": 0, "swaps": 0, "faults_injected": 0,
            "auth_401": 0, "auth_403": 0, "mux_requests": 0,
            "mux_connections": 0,
        }
        engine_totals = {"requests": 0, "batches": 0, "padded_rows": 0}
        for rid in self.replica_ids:
            rep = self.replica(rid)
            conn = self._http_conn(rep, timeout_s)
            try:
                conn.request("GET", "/stats", headers=self._auth_headers())
                resp = conn.getresponse()
                doc = json.loads(resp.read())
                per_replica[rid] = doc
                gw = doc.get("gateway", {})
                for key in totals:
                    v = gw.get(key)
                    if isinstance(v, (int, float)):
                        totals[key] += v
                for b in doc.get("bundles", {}).values():
                    for key in engine_totals:
                        v = b.get(key)
                        if isinstance(v, (int, float)):
                            engine_totals[key] += v
                # Per-replica process attribution (pid, RSS, relaunch
                # count) — in process mode each replica is its own pid,
                # so memory and churn are attributable per replica.
                proc = doc.get("process")
                if isinstance(proc, dict):
                    processes[rid] = {
                        "pid": proc.get("pid"),
                        "rss_bytes": proc.get("rss_bytes"),
                        "restarts": proc.get("restarts"),
                    }
            except (OSError, ValueError, http.client.HTTPException) as err:
                per_replica[rid] = {
                    "error": f"{type(err).__name__}: {err}"
                }
            finally:
                conn.close()
        with self._lock:
            health = {
                rid: {
                    "healthy": st.healthy,
                    "consecutive_fail": st.consecutive_fail,
                    "ejections": st.ejections,
                    "last_error": st.last_error,
                }
                for rid, st in self._state.items()
            }
            counters = dict(self.counters)
            pinned = len(self._pins)
        snapshot = {
            "kind": "fleet_stats",
            "n_replicas": len(per_replica),
            "n_healthy": sum(1 for h in health.values() if h["healthy"]),
            "fleet_config_hash": self.fleet_config_hash,
            "transport": self.transport,
            "tls": self.ssl_context is not None,
            "router": counters,
            "retry_budget": {
                "tokens": self.budget.tokens,
                "spent": self.budget.spent,
                "denied": self.budget.denied,
            },
            "pinned_households": pinned,
            "gateway_totals": totals,
            "engine_totals": engine_totals,
            "processes": processes,
            "health": health,
            "replicas": per_replica,
        }
        if self.telemetry is not None:
            self.telemetry.event(
                "fleet_stats",
                n_replicas=snapshot["n_replicas"],
                n_healthy=snapshot["n_healthy"],
                pinned_households=pinned,
                gateway_totals=totals,
                router=counters,
                processes=processes,
            )
        return snapshot


# -- in-process fleet harness --------------------------------------------------


class LocalFleet:
    """N in-process gateway replicas over the same bundle set.

    Each replica owns its engines/queues (``build_registry``) and serves
    from its own ``GatewayServer`` loop thread on an ephemeral port.
    ``kill`` severs a replica abruptly (connection resets, no drain) but
    keeps its registry warm; ``restart`` rebinds the SAME port with a
    fresh gateway over the warm registry — the fault harness's
    kill/restart cycle without paying XLA recompiles mid-bench. The
    per-replica ``FaultInjector`` (when a plan is given) survives
    restarts, so request-fault determinism spans the kill window.
    """

    def __init__(
        self,
        bundle_dirs: Sequence[str],
        n_replicas: int = 3,
        max_batch: int = 64,
        max_wait_s: float = 0.002,
        admission=None,
        results_db: Optional[str] = None,
        device: str = "auto",
        warmup: bool = True,
        fault_plan: Optional[FaultPlan] = None,
        host: str = "127.0.0.1",
        run_name: str = "fleet",
        mux: bool = False,
        tls=None,
        authenticator=None,
        batching: str = "micro",
        max_slots: int = 256,
        shard_warehouse: bool = False,
    ):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self.bundle_dirs = list(bundle_dirs)
        self.n_replicas = n_replicas
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.admission = admission
        self.results_db = results_db
        self.device = device
        self.warmup = warmup
        self.fault_plan = fault_plan
        self.host = host
        self.run_name = run_name
        # Wire/trust knobs mirrored from the gateway: each replica serves
        # the mux listener / TLS / token auth the process fleet does, so
        # the in-process harness exercises the same surfaces the real
        # fleet deploys.
        self.mux = mux
        self.tls = tls
        self.authenticator = authenticator
        # Queue front per replica bundle: "continuous" (slot-level
        # join/leave sessions — required for recurrent bundles) or the
        # classic "micro" coalescing queue.
        self.batching = batching
        self.max_slots = max_slots
        # Sharded warehouse write path (ROADMAP item 4): with
        # ``shard_warehouse`` on, each replica binds its OWN WAL-mode
        # SQLite shard (``<results_db stem>.shard-<rid><ext>``) instead of
        # funneling every per-request row into one file — the single-DB
        # funnel is the first thing to fall over at a million households.
        # ``shard_paths`` lists the files for a read-time federation
        # (``telemetry-query --shard`` / merge_warehouse_shards).
        self.shard_warehouse = shard_warehouse
        self.shard_paths: List[str] = []
        self._lock = threading.Lock()
        self._entries: Dict[str, dict] = {}
        self.kills: List[str] = []
        self.restarts: List[str] = []

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> List[Replica]:
        from p2pmicrogrid_tpu.serve.gateway import (
            GatewayServer,
            ServeGateway,
            build_registry,
            make_bundle_factory,
        )

        try:
            for i in range(self.n_replicas):
                rid = f"replica-{i}"
                injector = (
                    FaultInjector(self.fault_plan, rid)
                    if self.fault_plan is not None else None
                )
                rep_db, shard_id = self.results_db, None
                if self.shard_warehouse and self.results_db:
                    from p2pmicrogrid_tpu.data.results import shard_db_path

                    rep_db, shard_id = shard_db_path(self.results_db, rid), rid
                    self.shard_paths.append(rep_db)
                registry = build_registry(
                    self.bundle_dirs,
                    max_batch=self.max_batch,
                    max_wait_s=self.max_wait_s,
                    results_db=rep_db,
                    device=self.device,
                    warmup=self.warmup,
                    run_name=f"{self.run_name}-{rid}",
                    batching=self.batching,
                    max_slots=self.max_slots,
                    shard_id=shard_id,
                )
                factory = make_bundle_factory(
                    max_batch=self.max_batch,
                    max_wait_s=self.max_wait_s,
                    results_db=rep_db,
                    device=self.device,
                    warmup=self.warmup,
                    run_name=f"{self.run_name}-{rid}",
                    batching=self.batching,
                    max_slots=self.max_slots,
                    shard_id=shard_id,
                )
                gateway = ServeGateway(
                    registry, admission=self.admission, host=self.host,
                    port=0, own_bundles=False, fault_injector=injector,
                    replica_id=rid,
                    mux_port=0 if self.mux else None,
                    tls=self.tls, authenticator=self.authenticator,
                    bundle_factory=factory,
                )
                server = GatewayServer(gateway)
                try:
                    host, port = server.start()
                except BaseException:
                    registry.close_all()
                    raise
                with self._lock:
                    self._entries[rid] = {
                        "registry": registry,
                        "gateway": gateway,
                        "server": server,
                        "injector": injector,
                        "factory": factory,
                        "host": host,
                        "port": port,
                        "mux_port": gateway.mux_port,
                        "alive": True,
                    }
        except BaseException:
            self.stop_all()
            raise
        return self.replicas

    @property
    def replicas(self) -> List[Replica]:
        with self._lock:
            return [
                Replica(
                    replica_id=rid, host=e["host"], port=e["port"],
                    mux_port=e.get("mux_port"),
                )
                for rid, e in self._entries.items()
            ]

    def entry(self, replica_id: str) -> dict:
        with self._lock:
            return self._entries[replica_id]

    def reference_engine(self):
        """The default bundle's engine on the first replica — the direct
        comparator for the fleet bench's bit-exactness check."""
        with self._lock:
            first = self._entries[next(iter(self._entries))]
        registry = first["registry"]
        return registry.get(registry.default_hash).engine

    def activate_faults(self, t0: Optional[float] = None) -> None:
        """Anchor every replica injector's fault windows at one instant
        (the loadgen start), so a plan's windows line up fleet-wide."""
        t0 = time.monotonic() if t0 is None else t0
        with self._lock:
            injectors = [
                e["injector"] for e in self._entries.values()
                if e["injector"] is not None
            ]
        for injector in injectors:
            injector.activate(t0)

    # -- chaos hooks ---------------------------------------------------------

    def kill(self, replica_id: str) -> None:
        """Abrupt replica death: open connections reset, no drain; the
        registry (engines, queues, telemetry) stays warm for restart."""
        with self._lock:
            e = self._entries[replica_id]
            server, alive = e["server"], e["alive"]
            e["alive"] = False
            self.kills.append(replica_id)
        if alive and server is not None:
            server.kill()

    def restart(self, replica_id: str) -> None:
        """Bring a killed replica back on its ORIGINAL port (the router's
        address book must stay valid) over the warm registry."""
        from p2pmicrogrid_tpu.serve.gateway import (
            GatewayServer,
            ServeGateway,
        )

        with self._lock:
            e = self._entries[replica_id]
            if e["alive"]:
                raise RuntimeError(f"{replica_id} is already running")
            gateway = ServeGateway(
                e["registry"], admission=self.admission, host=e["host"],
                port=e["port"], own_bundles=False,
                fault_injector=e["injector"], replica_id=replica_id,
                mux_port=e.get("mux_port"),
                tls=self.tls, authenticator=self.authenticator,
                bundle_factory=e.get("factory"),
            )
            server = GatewayServer(gateway)
        server.start()
        with self._lock:
            e["gateway"] = gateway
            e["server"] = server
            e["alive"] = True
            self.restarts.append(replica_id)

    def stop_all(self) -> None:
        """Drain-stop every live replica, then close every registry
        (queues + telemetry). Idempotent."""
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            if e["alive"] and e["server"] is not None:
                try:
                    e["server"].stop()
                except Exception:  # noqa: BLE001 — close every replica
                    pass
                e["alive"] = False
        for e in entries:
            e["registry"].close_all()

    def __enter__(self) -> "LocalFleet":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop_all()


# -- fleet loadgen + bench -----------------------------------------------------


@dataclass
class FleetLoadgenResult:
    """Per-request outcomes of one open-loop run through the router."""

    latencies_s: np.ndarray      # [N] send -> final outcome (incl. retries)
    statuses: np.ndarray         # [N] final status (-1 transport, 503 shed)
    retries: np.ndarray          # [N]
    failovers: np.ndarray        # [N]
    router_shed: np.ndarray      # [N] bool: the ROUTER refused this request
    config_hashes: List
    replica_ids: List
    actions: List                # per request: served actions (None if not ok)
    makespan_s: float

    @property
    def n_requests(self) -> int:
        return int(self.statuses.shape[0])

    @property
    def n_ok(self) -> int:
        return int((self.statuses == 200).sum())

    @property
    def n_shed(self) -> int:
        """Requests refused honestly under back-pressure: replica 429s
        and ROUTER sheds (RouterResult.shed — no healthy replicas, or
        retry budget spent). A replica-originated 503 (draining, queue
        shutdown) is NOT a shed: that request was admitted and then
        refused, which is exactly the broken promise availability must
        count against the fleet."""
        return int(
            (self.statuses == 429).sum() + self.router_shed.sum()
        )

    @property
    def n_failed(self) -> int:
        return self.n_requests - self.n_ok - self.n_shed

    @property
    def shed_rate(self) -> float:
        return self.n_shed / self.n_requests if self.n_requests else 0.0

    @property
    def availability(self) -> float:
        """Answered fraction of ADMITTED requests — the chaos SLO: a shed
        request was refused honestly (and told when to retry); an
        admitted-but-unanswered one is a broken promise."""
        admitted = self.n_requests - self.n_shed
        return self.n_ok / admitted if admitted else 1.0

    @property
    def total_retries(self) -> int:
        return int(self.retries.sum())

    @property
    def retry_rate(self) -> float:
        return self.total_retries / self.n_requests if self.n_requests else 0.0

    @property
    def failover_total(self) -> int:
        return int(self.failovers.sum())

    @property
    def throughput_rps(self) -> float:
        return self.n_ok / self.makespan_s if self.makespan_s > 0 else 0.0

    def latency_ms(self, q: float) -> float:
        ok = self.latencies_s[self.statuses == 200]
        return float(np.percentile(ok, q) * 1e3) if ok.size else 0.0


def run_fleet_loadgen(
    router: FleetRouter,
    obs: np.ndarray,
    arrivals: np.ndarray,
    households: List[str],
    deadline_s: Optional[float] = None,
    trace_seed: Optional[int] = None,
    household_ids: Optional[List[str]] = None,
) -> FleetLoadgenResult:
    """The open-loop Poisson schedule fired through the ROUTER (retry,
    failover and shed semantics included) instead of at one gateway.

    ``trace_seed`` (not None) traces every request: request ``i`` carries
    ``root_context(trace_seed, i)`` through ``router.act`` — the router
    records the root + attempt/backoff spans, the replicas their server
    spans, and the warehouse stitches the cross-process tree back
    together (``TRACE_TREE_SQL``).

    ``household_ids`` (one id PER REQUEST, len == len(arrivals)) replaces
    the default round-robin over ``households`` — the hook the synthetic
    population engine (scale/population.py) uses to drive a realistic
    Zipf-skewed household mix through the same router path."""
    obs = np.asarray(obs, dtype=np.float32)  # host-sync: host-side inputs
    arrivals = np.asarray(arrivals, dtype=float)  # host-sync: host schedule
    n = int(arrivals.shape[0])
    if household_ids is not None and len(household_ids) != n:
        raise ValueError(
            f"household_ids carries {len(household_ids)} ids for "
            f"{n} arrivals — the population sequence must be per-request"
        )
    latencies = np.zeros(n)
    statuses = np.full(n, -1, dtype=np.int64)
    retries = np.zeros(n, dtype=np.int64)
    failovers = np.zeros(n, dtype=np.int64)
    router_shed = np.zeros(n, dtype=bool)
    hashes: List = [None] * n
    replica_ids: List = [None] * n
    actions: List = [None] * n

    async def one(i: int, t0: float) -> None:
        delay = (arrivals[i] - arrivals[0]) - (time.perf_counter() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        t_send = time.perf_counter()
        hid = (
            household_ids[i] if household_ids is not None
            else households[i % len(households)]
        )
        result = await router.act(
            hid, obs[i], deadline_s=deadline_s,
            trace=(
                root_context(trace_seed, i)
                if trace_seed is not None else None
            ),
        )
        latencies[i] = time.perf_counter() - t_send
        statuses[i] = result.status
        retries[i] = result.retries
        failovers[i] = result.failovers
        router_shed[i] = result.shed
        hashes[i] = result.config_hash
        replica_ids[i] = result.replica_id
        actions[i] = result.actions

    async def run() -> float:
        t0 = time.perf_counter()
        try:
            await asyncio.gather(*(one(i, t0) for i in range(n)))
        finally:
            # The mux pools are bound to THIS loop: close them before it
            # dies so their sockets FIN now, not at garbage collection.
            await router.close_pools()
        return time.perf_counter() - t0

    makespan = asyncio.run(run())
    return FleetLoadgenResult(
        latencies_s=latencies,
        statuses=statuses,
        retries=retries,
        failovers=failovers,
        router_shed=router_shed,
        config_hashes=hashes,
        replica_ids=replica_ids,
        actions=actions,
        makespan_s=makespan,
    )


def serve_bench_fleet(
    router: FleetRouter,
    n_agents: int,
    fleet: Optional[LocalFleet] = None,
    fault_plan: Optional[FaultPlan] = None,
    reference_engine=None,
    rate_hz: float = 256.0,
    n_requests: int = 1024,
    n_households: int = 16,
    seed: int = 0,
    slo_ms: float = 100.0,
    deadline_s: Optional[float] = None,
    probe_interval_s: float = 0.1,
    emit: Optional[Callable[[dict], None]] = None,
    extra_headline: Optional[dict] = None,
    unauth_router: Optional["FleetRouter"] = None,
    unauth_probe_requests: int = 32,
    chaos_join_grace_s: float = 10.0,
    recover_wait_s: float = 0.0,
    gateway_baseline: Optional[dict] = None,
    burst_factor: float = 1.0,
    burst_dwell_s: float = 0.25,
    trace_seed: Optional[int] = None,
    household_ids: Optional[List[str]] = None,
) -> List[dict]:
    """Fleet-level SLO benchmark: the serve-bench open-loop schedule
    through the router over a live fleet, optionally with a fault plan
    killing/restarting replicas mid-run (``serve-bench --fleet --chaos``).

    Emits metric rows (headline LAST, ``serve_bench_fleet``) with the
    chaos SLOs: wire percentiles over served requests, availability over
    admitted requests, failover/retry counts, and — when a
    ``reference_engine`` is given — a bit-exactness verdict comparing
    every served action against a direct ``PolicyEngine.act`` on the same
    observations.

    ``unauth_router`` (a second router over the same fleet holding NO
    bearer token) runs the auth acceptance check after the main schedule:
    ``unauth_probe_requests`` credential-less requests must come back 401
    with ZERO retries and ZERO retry-budget spend — the headline's
    ``auth_probe`` block records it, and ``auth_shed_rate`` reports the
    gateways' 401/403 fraction of all act requests.

    ``household_ids`` (one per request) overrides the round-robin
    ``n_households`` mix with an explicit per-request id sequence — the
    synthetic population engine's entry point (scale/population.py).

    ``gateway_baseline`` (a prior ``fleet_stats()['gateway_totals']``):
    gateway stats are cumulative per process, so pre-run traffic (the
    ``--wire-compare`` pass) would dilute the headline's auth-shed rate
    and request attribution — the baseline is subtracted from the totals
    this run reports.
    """
    arrivals, burst_config = make_arrivals(
        rate_hz, n_requests, seed=seed,
        burst_factor=burst_factor, burst_dwell_s=burst_dwell_s,
    )
    obs = synthetic_obs(n_requests, n_agents, seed=seed)
    households = [f"house-{i:04d}" for i in range(n_households)]
    schedule = None
    if fault_plan is not None and fleet is not None:
        schedule = FaultSchedule(fault_plan, fleet.kill, fleet.restart)
        fleet.activate_faults()
    router.start_probing(probe_interval_s)
    try:
        if schedule is not None:
            schedule.start()
        result = run_fleet_loadgen(
            router, obs, arrivals, households, deadline_s=deadline_s,
            trace_seed=trace_seed, household_ids=household_ids,
        )
        if schedule is not None:
            # Let a restart scheduled NEAR the run's end still apply (the
            # fleet should come back whole), but never block teardown on
            # events planned far past the run — those are cancelled, and
            # the headline's chaos.applied vs the plan shows the gap.
            last = max(
                (e.at_s for e in fault_plan.lifecycle_events()),
                default=0.0,
            )
            # Process-mode relaunches pay a child's full startup (JAX
            # import + engine warmup), so the harness passes a larger
            # grace there; in-process restarts finish in milliseconds.
            schedule.join(timeout_s=min(
                max(0.0, last - result.makespan_s) + 5.0,
                chaos_join_grace_s,
            ))
            schedule.stop()
    finally:
        router.stop_probing()
    # One post-chaos sweep so health/pins reflect the recovered fleet.
    router.probe_once()
    if recover_wait_s > 0:
        # Wait (bounded) for the whole fleet to report healthy — process
        # mode's supervisor relaunch must be VISIBLE in the headline's
        # fleet stats (restart counts, fresh pid), not racing past it.
        end = time.monotonic() + recover_wait_s
        while time.monotonic() < end:
            if all(router.probe_once().values()):
                break
            time.sleep(0.5)

    bit_exact = None
    mismatches = 0
    if reference_engine is not None:
        ok_idx = [
            i for i in range(result.n_requests)
            if result.statuses[i] == 200 and result.actions[i] is not None
        ]
        if ok_idx:
            got = np.asarray(  # host-sync: wire responses, host data
                [result.actions[i] for i in ok_idx], dtype=np.float32
            )
            want = reference_engine.act(obs[ok_idx])
            mismatches = int((got != want).any(axis=-1).sum())
            bit_exact = mismatches == 0

    auth_probe = None
    if unauth_router is not None and unauth_probe_requests > 0:
        # Fire credential-less requests through a token-less router over
        # the SAME fleet: every one must terminate 401 on its FIRST
        # attempt. Any retry or budget spend here means auth failures
        # leak into the retry machinery — the regression this guards.
        probe_obs = synthetic_obs(
            unauth_probe_requests, n_agents, seed=seed + 1
        )
        spent_before = unauth_router.budget.spent

        async def _probe_unauth():
            try:
                return await asyncio.gather(*(
                    unauth_router.act(f"intruder-{i:03d}", probe_obs[i])
                    for i in range(unauth_probe_requests)
                ))
            finally:
                await unauth_router.close_pools()

        probe_results = asyncio.run(_probe_unauth())
        auth_probe = {
            "requests": unauth_probe_requests,
            "n_401": sum(1 for r in probe_results if r.status == 401),
            "retries": sum(r.retries for r in probe_results),
            "budget_spent": unauth_router.budget.spent - spent_before,
        }

    stats = router.fleet_stats()
    base = gateway_baseline or {}

    def _net_total(key: str) -> float:
        return max(0, stats["gateway_totals"].get(key, 0) - base.get(key, 0))

    p50, p95, p99 = (result.latency_ms(q) for q in (50, 95, 99))
    rows = [
        {
            "metric": f"fleet_latency_ms_p{q}",
            "value": round(v, 3),
            "unit": "ms",
            "vs_baseline": round(slo_ms / v, 2) if v > 0 else 0.0,
        }
        for q, v in (("50", p50), ("95", p95), ("99", p99))
    ]
    rows.append(
        {
            "metric": "fleet_availability",
            "value": round(result.availability, 6),
            "unit": "fraction",
            "vs_baseline": round(result.availability, 6),
        }
    )
    rows.append(
        {
            "metric": "fleet_throughput_rps",
            "value": round(result.throughput_rps, 1),
            "unit": "requests/sec",
            "vs_baseline": round(result.throughput_rps / rate_hz, 3),
        }
    )
    rows.append(
        {
            "metric": "fleet_retry_rate",
            "value": round(result.retry_rate, 4),
            "unit": "retries/request",
            "vs_baseline": round(
                max(0.0, 1.0 - min(1.0, result.retry_rate)), 4
            ),
        }
    )
    counters = stats["router"]
    chaos = {
        "seed": fault_plan.seed if fault_plan is not None else None,
        "events": len(fault_plan.events) if fault_plan is not None else 0,
        "applied": schedule.applied if schedule is not None else [],
        "errors": schedule.errors if schedule is not None else [],
        "kills": list(fleet.kills) if fleet is not None else [],
        "restarts": list(fleet.restarts) if fleet is not None else [],
    }
    rows.append(
        {
            "metric": "serve_bench_fleet",
            "value": round(p99, 3),
            "unit": "ms",
            "vs_baseline": round(slo_ms / p99, 2) if p99 > 0 else 0.0,
            "p50_ms": round(p50, 3),
            "p95_ms": round(p95, 3),
            "p99_ms": round(p99, 3),
            "throughput_rps": round(result.throughput_rps, 1),
            "availability": round(result.availability, 6),
            "failover_count": int(counters["failovers"]),
            "retry_rate": round(result.retry_rate, 4),
            "shed_rate": round(result.shed_rate, 4),
            "n_requests": result.n_requests,
            "n_ok": result.n_ok,
            "n_shed": result.n_shed,
            "n_failed": result.n_failed,
            "n_replicas": stats["n_replicas"],
            "n_healthy": stats["n_healthy"],
            "ejections": int(counters["ejections"]),
            "readmissions": int(counters["readmissions"]),
            "repins": int(counters["repins"]),
            "pinned_households": stats["pinned_households"],
            "budget_denied": int(counters["budget_denied"]),
            "backoff_ms_total": round(counters["backoff_ms"], 3),
            "reconnects": int(counters["reconnects"]),
            "transport": router.transport,
            "tls": router.ssl_context is not None,
            "auth_shed_rate": round(
                (_net_total("auth_401") + _net_total("auth_403"))
                / max(1, _net_total("act_requests")),
                6,
            ),
            "auth_401": int(_net_total("auth_401")),
            "auth_403": int(_net_total("auth_403")),
            "auth_probe": auth_probe,
            "processes": stats["processes"],
            "bit_exact": bit_exact,
            "bit_exact_mismatches": mismatches,
            "served_replicas": sorted(
                {r for r in result.replica_ids if r is not None}
            ),
            "served_config_hashes": sorted(
                {h for h in result.config_hashes if h is not None}
            ),
            "chaos": chaos,
            "n_households": n_households,
            "offered_rate_rps": rate_hz,
            "slo_ms": slo_ms,
            "burst_config": burst_config,
            "trace_seed": trace_seed,
            **(extra_headline or {}),
        }
    )
    if emit is not None:
        for row in rows:
            emit(row)
    return rows
