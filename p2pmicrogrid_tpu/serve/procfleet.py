"""Process-isolated serve fleet: real subprocess replicas under supervision.

``LocalFleet`` (serve/router.py) runs N replicas inside ONE interpreter —
perfect for fast deterministic tests, but its replicas share a GIL and a
"kill" is an in-process abort. Every SLO number measured that way carries
an asterisk: the operating system never actually took a replica away.
``ProcessFleet`` removes the asterisk:

* **Each replica is a real OS process** — spawned through the existing
  ``serve-gateway`` CLI (`python -m p2pmicrogrid_tpu.cli serve-gateway`),
  its ephemeral HTTP + mux ports read from the ``gateway_listening`` JSON
  line the CLI prints once its sockets accept. TLS cert/key, the fleet
  auth secret and a fault plan ride in as flags, so the child terminates
  trust and injects faults exactly like an in-process gateway.
* **kill() is a real SIGKILL.** No drain, no Python-level cleanup — the
  kernel reclaims the process mid-request, which is the one failure mode
  the in-process harness cannot produce (clients see half-open
  connections, not polite resets).
* **A supervisor relaunches dead replicas** with capped deterministic
  exponential backoff (``min(cap, base * 2**restarts)`` — the same
  no-jitter rule as ``train/resilience.supervise``: replayability over
  thundering herds of one). Relaunches rebind the ORIGINAL ports (the
  router's address book stays valid) and pass ``--restarts N`` so fleet
  stats attribute churn per replica.
* **Fault-plan replay across restarts.** A relaunched child rebuilds its
  ``FaultInjector`` from the same plan + replica id, so a chaos run's
  injected fault sequence is a pure function of (plan seed, per-replica
  request order) in process mode too. Request-fault windows anchor at
  each child's first request (there is no cross-process monotonic clock
  to share), which the process-mode captures document.

The harness duck-types ``LocalFleet``'s chaos surface (``replicas``,
``kill``, ``restart``, ``activate_faults``, ``kills``/``restarts``,
``stop_all``, context manager), so ``serve_bench_fleet`` and the
``FaultSchedule`` drive both fleets identically.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from p2pmicrogrid_tpu.serve.router import Replica

_LOG_TAIL_LINES = 200


class ProcessFleet:
    """N ``serve-gateway`` subprocesses + a relaunch supervisor."""

    def __init__(
        self,
        bundle_dirs: Sequence[str],
        n_replicas: int = 3,
        max_batch: int = 64,
        max_wait_s: float = 0.002,
        max_queue_depth: int = 256,
        wait_budget_ms: float = 50.0,
        host: str = "127.0.0.1",
        mux: bool = True,
        tls_cert: Optional[str] = None,
        tls_key: Optional[str] = None,
        auth_secret_file: Optional[str] = None,
        fault_plan_file: Optional[str] = None,
        results_db: Optional[str] = None,
        serve_device: str = "auto",
        batching: str = "micro",
        max_slots: int = 256,
        shard_warehouse: bool = False,
        supervise: bool = True,
        backoff_s: float = 0.25,
        backoff_cap_s: float = 4.0,
        startup_timeout_s: float = 180.0,
        python: Optional[str] = None,
        env: Optional[dict] = None,
    ):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if (tls_cert is None) != (tls_key is None):
            raise ValueError("pass --tls cert AND key together, or neither")
        self.bundle_dirs = list(bundle_dirs)
        self.n_replicas = n_replicas
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.max_queue_depth = max_queue_depth
        self.wait_budget_ms = wait_budget_ms
        self.host = host
        self.mux = mux
        self.tls_cert = tls_cert
        self.tls_key = tls_key
        self.auth_secret_file = auth_secret_file
        self.fault_plan_file = fault_plan_file
        self.results_db = results_db
        self.serve_device = serve_device
        self.batching = batching
        self.max_slots = max_slots
        # Sharded warehouse write path (ROADMAP item 4): each child binds
        # its own WAL shard file + shard identity instead of contending on
        # one DB across processes. A relaunched replica rebinds the SAME
        # shard — its committed prefix survives the SIGKILL and the next
        # run appends beside it (the merge is keyed by run_id, so torn
        # tails never collide with the relaunch's rows).
        self.shard_warehouse = shard_warehouse
        self.shard_paths: List[str] = []
        if shard_warehouse and results_db:
            from p2pmicrogrid_tpu.data.results import shard_db_path

            self.shard_paths = [
                shard_db_path(results_db, f"replica-{i}")
                for i in range(n_replicas)
            ]
        self.supervise = supervise
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self.startup_timeout_s = startup_timeout_s
        self.python = python or sys.executable
        self.env = env
        self._lock = threading.Lock()
        # rid -> {proc, host, port, mux_port, alive, restarts, log,
        #         listening (threading.Event), deliberate_down}
        self._entries: Dict[str, dict] = {}
        self._supervisor: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.kills: List[str] = []
        self.restarts: List[str] = []

    # -- child lifecycle -----------------------------------------------------

    def _child_argv(self, rid: str, port: int, mux_port: Optional[int],
                    restarts: int) -> List[str]:
        argv = [self.python, "-m", "p2pmicrogrid_tpu.cli", "serve-gateway"]
        for bundle in self.bundle_dirs:
            argv += ["--bundle", bundle]
        argv += [
            "--host", self.host,
            "--port", str(port),
            "--max-batch", str(self.max_batch),
            "--max-wait-ms", str(self.max_wait_s * 1e3),
            "--max-queue-depth", str(self.max_queue_depth),
            "--wait-budget-ms", str(self.wait_budget_ms),
            "--serve-device", self.serve_device,
            "--batching", self.batching,
            "--max-sessions", str(self.max_slots),
            "--replica-id", rid,
            "--restarts", str(restarts),
        ]
        if self.mux:
            argv += ["--mux-port", str(mux_port if mux_port else 0)]
        if self.tls_cert:
            argv += ["--tls-cert", self.tls_cert, "--tls-key", self.tls_key]
        if self.auth_secret_file:
            argv += ["--auth-secret-file", self.auth_secret_file]
        if self.fault_plan_file:
            argv += ["--chaos-plan", self.fault_plan_file]
        if self.results_db:
            if self.shard_warehouse:
                from p2pmicrogrid_tpu.data.results import shard_db_path

                argv += [
                    "--results-db", shard_db_path(self.results_db, rid),
                    "--shard-id", rid,
                ]
            else:
                argv += ["--results-db", self.results_db]
        return argv

    def _spawn(self, rid: str, port: int = 0,
               mux_port: Optional[int] = None, restarts: int = 0) -> dict:
        child_env = dict(os.environ)
        child_env.update(self.env or {})
        proc = subprocess.Popen(
            self._child_argv(rid, port, mux_port, restarts),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=child_env,
        )
        entry = {
            "proc": proc,
            "host": self.host,
            "port": port,
            "mux_port": mux_port,
            "alive": True,
            "restarts": restarts,
            "log": deque(maxlen=_LOG_TAIL_LINES),
            "listening": threading.Event(),
            "deliberate_down": False,
        }
        reader = threading.Thread(
            target=self._read_child, args=(rid, entry), daemon=True
        )
        entry["reader"] = reader
        reader.start()
        return entry

    def _read_child(self, rid: str, entry: dict) -> None:
        """Stream one child's merged stdout/stderr, capturing a bounded
        log tail and resolving the ``gateway_listening`` line into the
        replica's addresses."""
        proc = entry["proc"]
        assert proc.stdout is not None
        for line in proc.stdout:
            entry["log"].append(line.rstrip("\n"))
            if '"gateway_listening"' in line and not entry["listening"].is_set():
                try:
                    doc = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if doc.get("kind") == "gateway_listening":
                    entry["port"] = int(doc["port"])
                    entry["mux_port"] = doc.get("mux_port")
                    entry["listening"].set()

    def _await_listening(self, rid: str, entry: dict) -> None:
        end = time.monotonic() + self.startup_timeout_s
        while not entry["listening"].wait(0.1):
            if entry["proc"].poll() is not None:
                tail = "\n".join(list(entry["log"])[-20:])
                raise RuntimeError(
                    f"{rid} exited rc={entry['proc'].returncode} before "
                    f"listening; log tail:\n{tail}"
                )
            if time.monotonic() >= end:
                entry["proc"].kill()
                raise RuntimeError(
                    f"{rid} did not print gateway_listening within "
                    f"{self.startup_timeout_s:g}s"
                )

    # -- public lifecycle ----------------------------------------------------

    def start(self) -> List[Replica]:
        try:
            for i in range(self.n_replicas):
                rid = f"replica-{i}"
                entry = self._spawn(rid)
                with self._lock:
                    self._entries[rid] = entry
            for rid, entry in list(self._entries.items()):
                self._await_listening(rid, entry)
        except BaseException:
            self.stop_all()
            raise
        if self.supervise:
            self._stop.clear()
            self._supervisor = threading.Thread(
                target=self._supervise, daemon=True
            )
            self._supervisor.start()
        return self.replicas

    @property
    def replicas(self) -> List[Replica]:
        with self._lock:
            return [
                Replica(
                    replica_id=rid, host=e["host"], port=e["port"],
                    mux_port=e.get("mux_port"),
                )
                for rid, e in self._entries.items()
            ]

    def entry(self, replica_id: str) -> dict:
        with self._lock:
            return self._entries[replica_id]

    def pid(self, replica_id: str) -> Optional[int]:
        with self._lock:
            proc = self._entries[replica_id]["proc"]
        return proc.pid if proc.poll() is None else None

    def log_tail(self, replica_id: str, n: int = 40) -> str:
        with self._lock:
            log = list(self._entries[replica_id]["log"])
        return "\n".join(log[-n:])

    def activate_faults(self, t0=None) -> None:
        """No-op on the process fleet: each child's injector self-anchors
        at its first request (no cross-process monotonic clock exists to
        share). The per-scope coin determinism is unaffected."""

    # -- chaos hooks ---------------------------------------------------------

    def kill(self, replica_id: str) -> None:
        """Real SIGKILL: the kernel reclaims the replica mid-request —
        no drain, no resets, clients discover the death as timeouts and
        refused reconnects. The supervisor (when on) relaunches it."""
        with self._lock:
            entry = self._entries[replica_id]
            proc = entry["proc"]
            entry["alive"] = False
            self.kills.append(replica_id)
        if proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10.0)

    def restart(self, replica_id: str) -> None:
        """Manual relaunch on the ORIGINAL ports. With the supervisor on
        this is usually a no-op — it already relaunched the replica (a
        fault plan's restart event is then already satisfied)."""
        with self._lock:
            entry = self._entries[replica_id]
            if entry["proc"].poll() is None:
                return  # already running (supervisor beat us to it)
            self._relaunch_locked(replica_id, entry)
        self._await_listening(replica_id, self._entries[replica_id])

    def _relaunch_locked(self, rid: str, entry: dict) -> None:
        restarts = entry["restarts"] + 1
        fresh = self._spawn(
            rid, port=entry["port"], mux_port=entry.get("mux_port"),
            restarts=restarts,
        )
        fresh["restarts"] = restarts
        self._entries[rid] = fresh
        self.restarts.append(rid)

    def _supervise(self) -> None:
        """Relaunch dead children with capped deterministic backoff —
        the serving mirror of ``train/resilience.supervise``."""
        while not self._stop.wait(0.05):
            with self._lock:
                dead = [
                    (rid, e) for rid, e in self._entries.items()
                    if e["proc"].poll() is not None
                    and not e["deliberate_down"]
                ]
            for rid, entry in dead:
                delay = min(
                    self.backoff_cap_s,
                    self.backoff_s * (2 ** entry["restarts"]),
                )
                if self._stop.wait(delay):
                    return
                with self._lock:
                    # Re-check under the lock: stop_all may have marked
                    # the fleet down while we backed off.
                    if entry["deliberate_down"] or self._stop.is_set():
                        continue
                    if self._entries[rid]["proc"].poll() is None:
                        continue  # someone else already relaunched
                    self._relaunch_locked(rid, self._entries[rid])
                try:
                    self._await_listening(rid, self._entries[rid])
                except RuntimeError:
                    pass  # next sweep backs off longer and retries

    def stop_all(self) -> None:
        """Stop the supervisor, then terminate every child (SIGTERM →
        bounded wait → SIGKILL). Idempotent."""
        self._stop.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=10.0)
            self._supervisor = None
        with self._lock:
            entries = list(self._entries.values())
            for e in entries:
                e["deliberate_down"] = True
        for e in entries:
            proc = e["proc"]
            if proc.poll() is None:
                proc.terminate()
        end = time.monotonic() + 15.0
        for e in entries:
            proc = e["proc"]
            try:
                proc.wait(timeout=max(0.1, end - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
            e["alive"] = False

    def __enter__(self) -> "ProcessFleet":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop_all()
