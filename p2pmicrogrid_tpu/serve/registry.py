"""Hot-swap bundle registry: multi-bundle serving keyed by config_hash.

One gateway process serves MANY policy bundles — the new default rolling
out next to the incumbent, an A/B candidate taking a percentage slice —
and traffic must move between them without dropping a request. The
registry is the routing table that makes that safe:

* **Identity is the manifest ``config_hash``.** A bundle's manifest pins
  the training config that produced it (serve/export.py); the hash is
  what the telemetry warehouse joins on, so routing by it means every
  served request is attributable to the exact config that answered it.
* **Atomic swap.** ``swap(config_hash)`` retargets the default bundle in
  one lock-held assignment. Requests already submitted to the old
  bundle's queue complete there (the queue keeps its engine reference);
  requests routed after the swap go to the new default. Nothing is ever
  torn down mid-request by a swap — ``remove`` is a separate, explicit
  step the operator takes once the old bundle has drained.
* **Percentage-split A/B.** ``set_split(hash_b, percent)`` routes that
  share of households to bundle B, deterministically by household-id
  hash, so a household does not flip arms between slots.
* **Household pinning (bundle affinity).** The first routed request pins
  a household to its bundle; later requests reuse the pin. Serving
  sessions carry cross-slot state (engine.Sessions), so a household must
  see one policy's trajectory, not an interleaving of two. A ``swap``
  clears pins — that is the point of a swap: every household re-routes
  to the new default/split outcome on its next slot. Removing a bundle
  clears only the pins that pointed at it.

Thread-safety: every mutation and ``route`` hold one RLock; the gateway's
asyncio handlers and the microbatch worker threads can hit the registry
concurrently.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass
class ServingBundle:
    """One registered bundle: the engine, its coalescing queue front and
    (optionally) the telemetry bound to the bundle's config_hash."""

    config_hash: str
    engine: object          # serve.engine.PolicyEngine
    queue: object           # serve.engine.MicroBatchQueue
    telemetry: object = None

    @property
    def implementation(self) -> Optional[str]:
        return self.engine.manifest.get("implementation")


def _household_slot(household_id: str) -> int:
    """Deterministic [0, 100) slot for a household id — stable across
    processes and restarts (hashlib, not ``hash()``, which is salted)."""
    digest = hashlib.sha256(household_id.encode()).hexdigest()
    return int(digest[:8], 16) % 100


class BundleRegistry:
    """Routing table over >= 1 ``ServingBundle``s with atomic hot-swap,
    percentage-split A/B and per-household bundle affinity."""

    def __init__(self):
        self._lock = threading.RLock()
        self._bundles: Dict[str, ServingBundle] = {}
        self._default: Optional[str] = None
        self._split: Optional[Tuple[str, float]] = None  # (hash_b, percent)
        self._pins: Dict[str, str] = {}
        # Incremental per-bundle pin tallies, maintained on every pin
        # mutation (ROADMAP item 4): stats() must stay O(bundles) — at a
        # million pinned households, re-counting the id-keyed map per
        # snapshot would make every /stats poll iterate the id space.
        self._pin_counts: Dict[str, int] = {}
        self.swap_count = 0

    # -- membership ----------------------------------------------------------

    def register(
        self,
        engine,
        queue,
        telemetry=None,
        default: bool = False,
    ) -> str:
        """Add a bundle; returns its config_hash. The first registered
        bundle becomes the default; ``default=True`` retargets it."""
        config_hash = engine.manifest.get("config_hash")
        if not config_hash:
            raise ValueError("bundle manifest carries no config_hash")
        with self._lock:
            if config_hash in self._bundles:
                raise ValueError(
                    f"bundle {config_hash} already registered — a second "
                    "copy of the same config cannot be routed distinctly"
                )
            self._bundles[config_hash] = ServingBundle(
                config_hash=config_hash,
                engine=engine,
                queue=queue,
                telemetry=telemetry,
            )
            if default or self._default is None:
                self._default = config_hash
        return config_hash

    def remove(self, config_hash: str) -> ServingBundle:
        """Unregister (the caller drains/closes the returned bundle). The
        default and the split arm cannot be removed while active."""
        with self._lock:
            if config_hash not in self._bundles:
                raise KeyError(f"no bundle {config_hash} registered")
            if config_hash == self._default:
                raise ValueError(
                    f"bundle {config_hash} is the default — swap first"
                )
            if self._split and self._split[0] == config_hash:
                raise ValueError(
                    f"bundle {config_hash} is the active split arm — "
                    "clear the split first"
                )
            bundle = self._bundles.pop(config_hash)
            # Control-plane op (not the per-request path): dropping one
            # bundle's pins rebuilds the map once per remove.
            self._pins = {
                h: c for h, c in self._pins.items() if c != config_hash
            }
            self._pin_counts.pop(config_hash, None)
            return bundle

    def get(self, config_hash: str) -> ServingBundle:
        with self._lock:
            return self._bundles[config_hash]

    @property
    def hashes(self) -> List[str]:
        with self._lock:
            return list(self._bundles)

    @property
    def default_hash(self) -> Optional[str]:
        with self._lock:
            return self._default

    @property
    def split(self) -> Optional[Tuple[str, float]]:
        with self._lock:
            return self._split

    # -- routing control -----------------------------------------------------

    def swap(self, config_hash: str) -> str:
        """Atomically make ``config_hash`` the default bundle and clear
        every household pin: a swap means every household re-routes on its
        next request. In-flight requests finish on the bundle that
        admitted them. Returns the PREVIOUS default hash."""
        with self._lock:
            if config_hash not in self._bundles:
                raise KeyError(f"no bundle {config_hash} registered")
            previous, self._default = self._default, config_hash
            if self._split and self._split[0] == config_hash:
                # The candidate just became the default; the experiment
                # routing to it is moot.
                self._split = None
            self._pins.clear()
            self._pin_counts.clear()
            self.swap_count += 1
            return previous

    def set_split(self, config_hash: str, percent: float) -> None:
        """Route ``percent``% of households (deterministic by id hash) to
        ``config_hash``; the rest stay on the default. Existing pins are
        kept — only unpinned households land in the new split."""
        if not 0.0 < percent < 100.0:
            raise ValueError(f"percent must be in (0, 100), got {percent}")
        with self._lock:
            if config_hash not in self._bundles:
                raise KeyError(f"no bundle {config_hash} registered")
            if config_hash == self._default:
                raise ValueError(
                    "split arm must differ from the default bundle"
                )
            self._split = (config_hash, float(percent))

    def clear_split(self) -> None:
        with self._lock:
            self._split = None

    def clear_pins(self) -> None:
        """Drop every household's bundle affinity so the NEXT request
        re-routes against the current default/split. The canary ramp
        (serve/promotion.py) calls this when WIDENING a split: pins
        recorded at 5% would otherwise freeze the arm's membership —
        set_split only assigns unpinned households, so the 25% stage
        would keep serving the 5% population. Re-rolling is monotone for
        the households already in the arm (the split hash is per-
        household deterministic: slot < 5 implies slot < 25), so their
        sessions survive the widening."""
        with self._lock:
            self._pins.clear()
            self._pin_counts.clear()

    # -- routing hot path ----------------------------------------------------

    def route(self, household_id: Optional[str] = None) -> ServingBundle:
        """The bundle serving this household. Households pinned during a
        split keep their bundle (session affinity); new ones are assigned
        by the split (or the default). Pins are only recorded WHILE a
        split is active — with no split every household serves the
        default anyway, and pinning each of millions of household ids
        would grow the pin map without bound for zero routing
        information. Anonymous requests (no id) always serve from the
        DEFAULT: a split is a household experiment, and hashing the empty
        id would send ALL anonymous traffic to one arm (sha256('') is a
        constant slot) instead of a percentage."""
        with self._lock:
            if self._default is None:
                raise RuntimeError("no bundles registered")
            if household_id:
                pinned = self._pins.get(household_id)
                if pinned is not None and pinned in self._bundles:
                    return self._bundles[pinned]
            chosen = self._default
            if self._split is not None and household_id:
                arm, percent = self._split
                if _household_slot(household_id) < percent:
                    chosen = arm
                # O(1) per request: one dict write + tally adjust — the
                # split hash above is constant-time, and nothing on this
                # path scales with how many households exist.
                previous = self._pins.get(household_id)
                if previous != chosen:
                    if previous is not None:
                        self._pin_counts[previous] = (
                            self._pin_counts.get(previous, 1) - 1
                        )
                    self._pin_counts[chosen] = (
                        self._pin_counts.get(chosen, 0) + 1
                    )
                self._pins[household_id] = chosen
            return self._bundles[chosen]

    # -- observability / lifecycle -------------------------------------------

    @property
    def pinned_count(self) -> int:
        with self._lock:
            return len(self._pins)

    def stats(self) -> dict:
        """Per-bundle serving stats snapshot — lock-held and O(bundles),
        NEVER O(pins): the per-bundle pinned tallies are maintained
        incrementally on the route path, so a million-household split does
        not turn every /stats poll into an id-space scan
        (tests/test_scale.py regression-tests this at 1M ids)."""
        import numpy as np

        with self._lock:
            bundles = {}
            for h, b in self._bundles.items():
                # list() first: the queue worker appends concurrently, and
                # a Python-level comprehension over a mutating deque
                # raises ("deque mutated during iteration"); list() is one
                # C call and cannot interleave.
                waits = [w for _, w in list(b.queue.recent_wait_ms)]
                bundles[h] = {
                    "implementation": b.implementation,
                    "n_agents": b.engine.n_agents,
                    "requests": b.engine.stats["rows"],
                    "batches": b.engine.stats["batches"],
                    "padded_rows": b.engine.stats["padded_rows"],
                    "queue_depth": b.queue.depth,
                    "recent_wait_p95_ms": (
                        round(float(np.percentile(waits, 95)), 3)
                        if waits else 0.0
                    ),
                    "pinned_households": self._pin_counts.get(h, 0),
                }
            return {
                "default": self._default,
                "split": (
                    {"config_hash": self._split[0], "percent": self._split[1]}
                    if self._split else None
                ),
                "swap_count": self.swap_count,
                "bundles": bundles,
            }

    def close_all(self) -> None:
        """Close EVERY bundle's queue (waits for its worker; the queue's
        own join timeout bounds a stuck one) and telemetry. Skipping any
        bundle would strand its worker thread and lose the telemetry rows
        still buffered in its warehouse sink, so there is no early-out
        here — every close runs. Idempotent; called by the owner once the
        gateway has drained."""
        with self._lock:
            bundles = list(self._bundles.values())
        for b in bundles:
            b.queue.close()
            if b.telemetry is not None:
                b.telemetry.close()
