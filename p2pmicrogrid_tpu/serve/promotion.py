"""Gated promotion + canary auto-rollback: the deployment safety rails.

Continual training (train/continual.py) emits candidate bundles on a
cadence; production must be UNABLE to regress no matter what the trainer
produced. Two independent rails stand between a candidate and traffic:

* **The promotion gate** (``run_promotion_gate``) — offline, before any
  traffic. The candidate must (a) BEAT the incumbent on the held-out
  greedy eval cost (train/health.make_greedy_eval over the fixed
  never-trained scenario set; ties lose — "no worse" is not a reason to
  ship), (b) evaluate FINITE (a NaN-poisoned bundle fails here, not in a
  household's heat pump), and (c) meet the serve-bench SLO budgets
  (p95/p99 latency, shed rate) measured on the candidate's own engine.
  Every verdict is a ``promotion`` event in the telemetry warehouse —
  ``telemetry-query --promotions`` answers "what happened the last time
  this config tried to ship".

* **The canary** (``CanaryController``) — online, for candidates the
  gate passed. The controller ramps the candidate through the existing
  ``BundleRegistry`` percentage-split A/B (PR 5): a stage sets the split,
  live traffic flows, and per-bundle attribution is read back through the
  warehouse join the ``--compare`` tooling uses — each arm's decision
  cost (the trace-reward attribution of what it actually served,
  data/trace_export.trace_reward), latency and error/nonfinite counts,
  keyed by config_hash. A healthy stage ramps up (default 5% → 25% →
  100%, the last stage a swap — fleet-wide two-phase via ``swap_fn`` =
  ``router.swap_fleet`` when fronting a fleet, the registry's atomic swap
  in-process); a regression or guard trip ABORTS the ramp, clears the
  split, restores the incumbent as default and reports ``rolled_back`` —
  all through routing-table mutations that never touch an in-flight
  request, so the abort drops zero traffic (asserted by the harness).

``promotion_bench`` is the seeded acceptance harness behind the committed
``PROMOTION_*.jsonl`` captures: crafted tabular candidates — genuinely
better, cost-regressed, NaN-poisoned, SLO-violating-slow — are pushed
through the full pipeline against a live gateway. The bad ones must be
blocked at the gate or rolled back mid-canary with availability 1.0 and
the incumbent serving bit-exact afterward; the good one must promote
end-to-end. Deterministic under its seed: gate SLO times are modeled
(``plan_open_loop``'s virtual clock), traffic obs/households are
seed-derived, and the crafted policies are closed-form.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import math
import sqlite3
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np


# -- budgets -------------------------------------------------------------------


@dataclass(frozen=True)
class GateBudgets:
    """What the offline gate requires of a candidate.

    ``cost_margin`` is subtracted from the incumbent's eval cost before
    the comparison: the candidate must satisfy ``cost < incumbent_cost -
    cost_margin`` (default 0 — a strict beat; ties and regressions both
    fail). ``max_reward_drop`` is the don't-heat-basin guard
    (train/health.py's measured failure mode: community cost IMPROVES
    while comfort collapses): the candidate's greedy reward may not fall
    more than ``max(|incumbent_reward|, 1) * max_reward_drop`` below the
    incumbent's — a cheaper candidate that stopped heating fails HERE,
    not in a cold house. The SLO half comes from a serve-bench run on the
    candidate's engine: p95/p99 within budget, shed rate at most
    ``max_shed_rate`` (0 for the in-process bench, which cannot shed —
    network/fleet gates report real shed rates).
    """

    cost_margin: float = 0.0
    max_reward_drop: float = 0.5
    slo_p95_ms: float = 100.0
    slo_p99_ms: float = 250.0
    max_shed_rate: float = 0.05
    # Per-regime no-regression rule (ISSUE 13): when the gate is given a
    # held-out regime set (``run_promotion_gate(regime_specs=...)``), the
    # candidate may not regress ANY regime's held-out eval cost by more
    # than ``max(|incumbent|, 1) * max_regime_regression`` — a candidate
    # that improves the MEAN by winning the easy worlds while losing a
    # cold snap or an islanding event does not ship. 0.0 = any regression
    # beyond float-noise scale blocks.
    max_regime_regression: float = 0.0
    # Quantized-candidate error budget (serve/export.py's int8 contract):
    # a continuous int8 candidate's MEASURED max ulp (manifest
    # quant.error_bound.max_ulp) must stay within this budget; None defers
    # to the budget the bundle itself declared at export (ulp_budget).
    # Discrete int8 candidates must carry bit_exact_argmax=True regardless.
    max_quant_ulp: Optional[float] = None


@dataclass(frozen=True)
class CanaryBudgets:
    """Per-stage regression thresholds for the live canary.

    ``max_cost_regression`` bounds the candidate arm's mean decision
    cost: ``cand <= inc + max(|inc|, 1) * max_cost_regression`` (scale-
    free, sign-safe). Latency is bounded both relatively
    (``max_p95_ratio`` x the incumbent arm's p95) and absolutely
    (``slo_p95_ms``). ANY candidate-arm server error or nonfinite action
    aborts when ``max_error_rate`` is 0. A stage with fewer than
    ``min_requests`` candidate-arm requests is inconclusive for the cost
    check (latency/error checks still apply) — size stages so they are
    not.
    """

    max_cost_regression: float = 0.05
    max_p95_ratio: float = 5.0
    slo_p95_ms: float = 500.0
    max_error_rate: float = 0.0
    min_requests: int = 8


# -- offline gate --------------------------------------------------------------


@dataclass
class GateVerdict:
    """One gate decision (also a ``promotion`` warehouse event)."""

    passed: bool
    candidate: Optional[str]
    incumbent: Optional[str]
    candidate_cost: float
    incumbent_cost: float
    candidate_reward: float
    incumbent_reward: float
    p95_ms: float
    p99_ms: float
    shed_rate: float
    reasons: List[str] = field(default_factory=list)
    # Per-regime held-out eval costs (regime name -> EUR), populated when
    # the gate ran with a regime set; empty otherwise.
    candidate_regime_costs: dict = field(default_factory=dict)
    incumbent_regime_costs: dict = field(default_factory=dict)

    @property
    def verdict(self) -> str:
        return "pass" if self.passed else "fail: " + "; ".join(self.reasons)

    def to_fields(self) -> dict:
        return {
            "passed": self.passed,
            "candidate": self.candidate,
            "incumbent": self.incumbent,
            "candidate_cost": _round_or_none(self.candidate_cost),
            "incumbent_cost": _round_or_none(self.incumbent_cost),
            "candidate_reward": _round_or_none(self.candidate_reward),
            "incumbent_reward": _round_or_none(self.incumbent_reward),
            "p95_ms": round(self.p95_ms, 3),
            "p99_ms": round(self.p99_ms, 3),
            "shed_rate": round(self.shed_rate, 6),
            "reasons": list(self.reasons),
            "candidate_regime_costs": {
                k: _round_or_none(v)
                for k, v in self.candidate_regime_costs.items()
            },
            "incumbent_regime_costs": {
                k: _round_or_none(v)
                for k, v in self.incumbent_regime_costs.items()
            },
        }


def _round_or_none(v: float):
    return round(float(v), 6) if math.isfinite(v) else None


def evaluate_bundle_cost(
    cfg, bundle_dir: str, s_eval: int = 8, eval_key: int = 1
) -> Tuple[float, float]:
    """Held-out greedy eval ``(cost, reward)`` of a BUNDLE: the greedy
    subtree is grafted into a fresh learner state
    (train/continual.state_from_bundle) and run through the same fixed
    never-trained scenario set training health uses — both sides of a
    gate comparison see identical scenarios, physics and eval keys, so
    the only free variable is the policy."""
    import jax

    from p2pmicrogrid_tpu.envs import make_ratings
    from p2pmicrogrid_tpu.serve.export import load_policy_bundle
    from p2pmicrogrid_tpu.train import make_policy
    from p2pmicrogrid_tpu.train.continual import state_from_bundle
    from p2pmicrogrid_tpu.train.health import make_greedy_eval

    manifest, params = load_policy_bundle(bundle_dir)
    ps = state_from_bundle(
        cfg, manifest, params, jax.random.PRNGKey(cfg.train.seed)
    )
    policy = make_policy(cfg)
    ratings = make_ratings(cfg, np.random.default_rng(cfg.train.seed))
    greedy_eval = make_greedy_eval(cfg, policy, ratings, s_eval=s_eval)
    cost, reward = greedy_eval(ps, jax.random.PRNGKey(eval_key))
    return float(cost), float(reward)


def run_promotion_gate(
    cfg,
    candidate_dir: str,
    incumbent_dir: str,
    budgets: GateBudgets = GateBudgets(),
    telemetry=None,
    s_eval: int = 8,
    bench_rate_hz: float = 256.0,
    bench_requests: int = 512,
    bench_seed: int = 0,
    max_batch: int = 64,
    service_time_fn: Optional[Callable[[int, int], float]] = None,
    device: str = "auto",
    incumbent_eval: Optional[Tuple[float, float]] = None,
    regime_specs: Optional[Sequence] = None,
    regime_s_per_regime: int = 4,
    regime_eval_fn: Optional[Callable[[str], dict]] = None,
    incumbent_regime_eval: Optional[dict] = None,
) -> GateVerdict:
    """The offline promotion gate (module docstring). ``service_time_fn``
    overrides the SLO bench's batch timing (the deterministic modeled
    clock in tests/harness; None measures the real engine).
    ``incumbent_eval`` (a prior ``evaluate_bundle_cost`` result) skips
    re-evaluating an unchanged incumbent — the harness gates many
    candidates against one. A candidate already condemned by the
    poison/eval checks skips the SLO bench entirely (engine compile +
    bench wall-clock buys nothing on a verdict that cannot flip); the
    verdict's SLO fields read 0 in that case.

    ``regime_specs`` (regime names / ``regimes.RegimeSpec``s) turns on the
    per-regime dimension: both bundles run the held-out mixed-regime eval
    (``regimes.evaluate.evaluate_bundle_regimes`` — one compiled program
    shared by both sides) and the candidate may not regress ANY regime
    beyond ``budgets.max_regime_regression``, even when its mean cost
    improves. ``regime_eval_fn(bundle_dir) -> {regime: cost}`` overrides
    the evaluator (tests/harness); ``incumbent_regime_eval`` reuses a
    prior incumbent result across many candidates."""
    from p2pmicrogrid_tpu.serve.engine import PolicyEngine
    from p2pmicrogrid_tpu.serve.export import load_policy_bundle
    from p2pmicrogrid_tpu.serve.loadgen import serve_bench

    cand_manifest, cand_params = load_policy_bundle(candidate_dir)
    inc_manifest, _ = load_policy_bundle(incumbent_dir)
    candidate = cand_manifest.get("config_hash")
    incumbent = inc_manifest.get("config_hash")
    reasons: List[str] = []

    # Parameter-level poison check BEFORE any eval: a NaN net fails the
    # eval's finiteness check too, but a NaN Q-TABLE does not (argmax
    # over NaN rows still picks a finite action) — the parameters
    # themselves are the only place that poisoning is visible.
    import jax

    nonfinite_params = 0
    for leaf in jax.tree_util.tree_leaves(cand_params):
        arr = np.asarray(leaf)  # host-sync: bundle params are host arrays
        if np.issubdtype(arr.dtype, np.floating):
            nonfinite_params += int((~np.isfinite(arr)).sum())
    if nonfinite_params:
        reasons.append(
            f"candidate carries {nonfinite_params} non-finite "
            "parameter(s) — poisoned bundle"
        )

    # Quantization error-bound contract (serve/export.py): an int8 candidate
    # must carry its measured error bound and stay inside the budget —
    # discrete policies a bit-exact greedy argmax, continuous actors the
    # measured max-ulp within the enforced budget (the gate's
    # ``max_quant_ulp`` override, else the bundle's own declared budget).
    quant = cand_manifest.get("quant") or {}
    if cand_manifest.get("dtype") == "int8" and not quant:
        reasons.append(
            "int8 candidate manifest carries no quant block (scales + "
            "error_bound) — the bundle cannot be dequantized or its "
            "contract verified"
        )
    if quant:
        eb = quant.get("error_bound") or {}
        discrete = (
            (cand_manifest.get("action_spec") or {}).get("type") == "discrete"
            or eb.get("kind") == "discrete_argmax"
        )
        if discrete:
            if not eb.get("bit_exact_argmax", False):
                reasons.append(
                    "quantized discrete candidate does not certify a "
                    "bit-exact greedy argmax (quant.error_bound."
                    "bit_exact_argmax) — violates the int8 contract"
                )
        else:
            max_ulp = eb.get("max_ulp")
            budget = (
                budgets.max_quant_ulp
                if budgets.max_quant_ulp is not None
                else eb.get("ulp_budget")
            )
            if not isinstance(max_ulp, (int, float)) or not isinstance(
                budget, (int, float)
            ):
                reasons.append(
                    "quantized continuous candidate carries no measured "
                    "max_ulp/ulp_budget (quant.error_bound) — cannot verify "
                    "the int8 contract"
                )
            elif max_ulp > budget:
                reasons.append(
                    f"quantized candidate measured max ulp {max_ulp:.0f} "
                    f"exceeds the enforced budget {budget:.0f}"
                )

    cand_cost = cand_reward = inc_cost = inc_reward = float("nan")
    if not reasons:
        # A candidate the quant-contract checks already condemned skips the
        # eval passes entirely (the stripped-quant case would even eval raw
        # un-dequantized int8 params — a garbage cost number), same
        # rationale as the SLO-bench skip below.
        cand_cost, cand_reward = evaluate_bundle_cost(
            cfg, candidate_dir, s_eval=s_eval
        )
        inc_cost, inc_reward = incumbent_eval or evaluate_bundle_cost(
            cfg, incumbent_dir, s_eval=s_eval
        )
        if not (math.isfinite(cand_cost) and math.isfinite(cand_reward)):
            reasons.append(
                f"candidate eval is non-finite (cost={cand_cost}, "
                f"reward={cand_reward}) — poisoned parameters"
            )
        else:
            if not cand_cost < inc_cost - budgets.cost_margin:
                word = "ties" if cand_cost == inc_cost else "regresses"
                reasons.append(
                    f"candidate {word} the incumbent on held-out eval cost "
                    f"({cand_cost:.4f} vs {inc_cost:.4f}, margin "
                    f"{budgets.cost_margin:g}) — must BEAT it"
                )
            reward_floor = inc_reward - max(
                abs(inc_reward), 1.0
            ) * budgets.max_reward_drop
            if cand_reward < reward_floor:
                reasons.append(
                    f"candidate greedy reward {cand_reward:.2f} collapsed "
                    f"below the incumbent's {inc_reward:.2f} (floor "
                    f"{reward_floor:.2f}) — the don't-heat basin guard: "
                    "cost savings bought with comfort do not ship"
                )

    # Per-regime no-regression rule (ISSUE 13): runs only while the
    # candidate is still in the running (a mean-eval failure cannot be
    # flipped by regime wins), BEFORE the SLO bench (regime evals are the
    # cheaper check and a regime regression makes the bench moot).
    cand_regimes: dict = {}
    inc_regimes: dict = {}
    want_regimes = regime_specs is not None or regime_eval_fn is not None
    if not reasons and want_regimes:
        if regime_eval_fn is None:
            from p2pmicrogrid_tpu.regimes import build_portfolio, make_regime_eval
            from p2pmicrogrid_tpu.regimes.evaluate import (
                evaluate_bundle_regimes,
            )
            from p2pmicrogrid_tpu.envs import make_ratings
            from p2pmicrogrid_tpu.train import make_policy

            specs = list(regime_specs)
            shared_eval_fn = make_regime_eval(
                cfg, make_policy(cfg),
                make_ratings(cfg, np.random.default_rng(cfg.train.seed)),
                build_portfolio(specs, len(specs)),
                s_per_regime=regime_s_per_regime,
            )

            def regime_eval_fn(bundle_dir):
                role = (
                    "candidate" if bundle_dir == candidate_dir
                    else "incumbent"
                )
                return evaluate_bundle_regimes(
                    cfg, bundle_dir, specs,
                    s_per_regime=regime_s_per_regime,
                    eval_fn=shared_eval_fn, held_out=True,
                    telemetry=telemetry, bundle_tag=role,
                )

        cand_regimes = {
            k: v for k, v in regime_eval_fn(candidate_dir).items()
            if isinstance(v, (int, float))
        }
        inc_regimes = {
            k: v
            for k, v in (
                incumbent_regime_eval or regime_eval_fn(incumbent_dir)
            ).items()
            if isinstance(v, (int, float))
        }
        for name in sorted(inc_regimes):
            if name not in cand_regimes:
                reasons.append(
                    f"candidate regime eval is missing held-out regime "
                    f"{name!r}"
                )
                continue
            c, i = cand_regimes[name], inc_regimes[name]
            if not (math.isfinite(c) and math.isfinite(i)):
                reasons.append(
                    f"non-finite held-out eval on regime {name!r} "
                    f"(candidate {c}, incumbent {i})"
                )
                continue
            ceiling = i + max(abs(i), 1.0) * budgets.max_regime_regression
            if c > ceiling:
                reasons.append(
                    f"candidate regresses held-out regime {name!r} "
                    f"({c:.4f} vs incumbent {i:.4f}, ceiling "
                    f"{ceiling:.4f}) — mean-cost wins do not excuse a "
                    "worst-regime loss"
                )

    p95 = p99 = shed_rate = 0.0
    if not reasons:
        # Only a candidate still in the running pays the SLO bench (the
        # engine build + compile + bench run cannot flip a verdict the
        # eval checks already failed).
        engine = PolicyEngine(
            bundle_dir=candidate_dir, max_batch=max_batch, device=device
        )
        bench_rows = serve_bench(
            engine,
            rate_hz=bench_rate_hz,
            n_requests=bench_requests,
            seed=bench_seed,
            service_time_fn=service_time_fn,
        )
        headline = bench_rows[-1]
        p95 = float(headline.get("p95_ms", 0.0))
        p99 = float(headline.get("p99_ms", 0.0))
        shed_rate = float(headline.get("shed_rate", 0.0))
        if p95 > budgets.slo_p95_ms:
            reasons.append(
                f"p95 {p95:.1f} ms over the {budgets.slo_p95_ms:g} ms budget"
            )
        if p99 > budgets.slo_p99_ms:
            reasons.append(
                f"p99 {p99:.1f} ms over the {budgets.slo_p99_ms:g} ms budget"
            )
        if shed_rate > budgets.max_shed_rate:
            reasons.append(
                f"shed rate {shed_rate:.4f} over the "
                f"{budgets.max_shed_rate:g} budget"
            )

    verdict = GateVerdict(
        passed=not reasons,
        candidate=candidate,
        incumbent=incumbent,
        candidate_cost=cand_cost,
        incumbent_cost=inc_cost,
        candidate_reward=cand_reward,
        incumbent_reward=inc_reward,
        p95_ms=p95,
        p99_ms=p99,
        shed_rate=shed_rate,
        reasons=reasons,
        candidate_regime_costs=cand_regimes,
        incumbent_regime_costs=inc_regimes,
    )
    if telemetry is not None:
        telemetry.event("promotion", phase="gate", **verdict.to_fields())
        telemetry.counter(
            "promotion.gate_pass" if verdict.passed else "promotion.gate_fail"
        )
    return verdict


# -- canary --------------------------------------------------------------------


@dataclass
class StageTraffic:
    """What one canary stage's live traffic looked like from the client.

    The driver (``drive_stage``) fires real requests at the serving
    front and reports per-request outcomes; per-arm COST attribution is
    read from the warehouse separately (the ``--compare`` join — the
    server-side record of what each bundle actually served).
    ``households`` matters for FAILED requests: an error response
    carries no ``config_hash``, so the controller attributes it to the
    arm the household's deterministic split slot routes to — without
    this, a candidate erroring on every request would be invisible to
    its own error guard.
    """

    statuses: np.ndarray                 # [N] final HTTP status (-1 transport)
    latencies_ms: np.ndarray             # [N]
    config_hashes: List[Optional[str]]   # serving bundle per request
    actions: List[Optional[list]]        # served actions per request
    households: List[Optional[str]] = field(default_factory=list)
    n_shed: int = 0                      # honest sheds (429 / router shed)


@dataclass
class StagePlan:
    index: int
    percent: float
    is_promote: bool


@dataclass
class CanaryStageReport:
    percent: float
    n_requests: int
    ok: bool
    arms: dict = field(default_factory=dict)   # config_hash -> metrics
    reasons: List[str] = field(default_factory=list)

    def to_fields(self) -> dict:
        return {
            "percent": self.percent,
            "n_requests": self.n_requests,
            "ok": self.ok,
            "arms": self.arms,
            "reasons": list(self.reasons),
        }


@dataclass
class CanaryResult:
    stages: List[CanaryStageReport] = field(default_factory=list)
    promoted: bool = False
    rolled_back: bool = False
    aborted_stage: Optional[int] = None
    n_requests: int = 0
    n_ok: int = 0
    n_shed: int = 0
    reasons: List[str] = field(default_factory=list)

    @property
    def availability(self) -> float:
        admitted = self.n_requests - self.n_shed
        return self.n_ok / admitted if admitted else 1.0

    @property
    def n_failed(self) -> int:
        return self.n_requests - self.n_ok - self.n_shed


class CanaryController:
    """Ramp a gate-passed candidate through live traffic, auto-rolling
    back on regression (module docstring).

    ``registry`` is the serving gateway's ``BundleRegistry`` (both
    bundles registered; incumbent is the default). ``swap_fn`` overrides
    the 100%-stage promotion mechanism — pass a closure over
    ``router.swap_fleet`` to promote a whole fleet two-phase; the default
    is the registry's atomic in-process swap. Rollback uses the same
    mechanism in reverse, so a fleet canary rolls the fleet back. When
    ``swap_fn`` is given, the pre-promote SPLIT stages need their own
    fleet-wide mechanism too (``split_fn``/``clear_split_fn``/
    ``clear_pins_fn`` — e.g. pushing ``/admin/swap`` splits to every
    replica): the local registry's split never touches fleet-routed
    traffic, so without them the ramp stages would pass VACUOUSLY (zero
    candidate traffic) and the 100% swap would be the first real
    exposure. The constructor refuses that configuration — a multi-stage
    fleet ramp without a ``split_fn`` raises instead of silently
    degrading to a 0→100% jump.
    ``results_db`` + ``flush_fn`` wire the per-stage warehouse
    attribution: ``flush_fn`` pushes the gateway bundles' buffered
    telemetry, then the controller reads each arm's ``serve_decision``
    rows since the stage started and attributes decision cost via
    ``data/trace_export.trace_reward`` — and each arm's ``serve_request``
    spans, whose server-measured p95 REPLACES the client-side latency in
    the SLO guards whenever present (``_arm_server_slo``): the serving
    bundle's own clock judges the canary, not the loadgen's.
    """

    def __init__(
        self,
        registry,
        candidate_hash: str,
        incumbent_hash: str,
        cfg=None,
        stages: Sequence[float] = (5.0, 25.0, 100.0),
        budgets: CanaryBudgets = CanaryBudgets(),
        telemetry=None,
        results_db: Optional[str] = None,
        flush_fn: Optional[Callable[[], None]] = None,
        swap_fn: Optional[Callable[[str], None]] = None,
        split_fn: Optional[Callable[[str, float], None]] = None,
        clear_split_fn: Optional[Callable[[], None]] = None,
        clear_pins_fn: Optional[Callable[[], None]] = None,
    ):
        if not stages or stages[-1] < 100.0:
            raise ValueError(
                f"stages must end at 100 (the promotion), got {stages!r}"
            )
        if any(not 0.0 < s <= 100.0 for s in stages) or list(stages) != sorted(
            set(stages)
        ):
            raise ValueError(
                f"stages must be strictly increasing in (0, 100], got {stages!r}"
            )
        if swap_fn is not None and split_fn is None and any(
            s < 100.0 for s in stages
        ):
            raise ValueError(
                "swap_fn (fleet-wide promotion) with pre-100% stages "
                "needs a fleet-wide split_fn too: the local registry's "
                "split never routes fleet traffic, so the ramp stages "
                "would pass vacuously and the 100% swap would be the "
                "candidate's FIRST real exposure. Pass split_fn/"
                "clear_split_fn (e.g. pushing /admin/swap splits to every "
                "replica) or ramp with stages=(100.0,)"
            )
        self.registry = registry
        self.candidate = candidate_hash
        self.incumbent = incumbent_hash
        self.cfg = cfg
        self.stages = list(stages)
        self.budgets = budgets
        self.telemetry = telemetry
        self.results_db = results_db
        self.flush_fn = flush_fn
        self._swap_fn = swap_fn
        self._split_fn = split_fn or registry.set_split
        self._clear_split_fn = clear_split_fn or registry.clear_split
        self._clear_pins_fn = clear_pins_fn or registry.clear_pins
        # Running incumbent decision-cost baseline (sum, n) across stages
        # — the comparator of last resort once the incumbent stops
        # serving (the 100% stage).
        self._inc_baseline: Tuple[float, int] = (0.0, 0)

    # -- routing mutations ---------------------------------------------------

    def _swap_to(self, config_hash: str) -> None:
        if self._swap_fn is not None:
            self._swap_fn(config_hash)
        else:
            self.registry.swap(config_hash)

    def _restore_incumbent(self, swapped: bool) -> None:
        """Abort path: clear the split, UNPIN every canaried household
        and restore the incumbent default. The unpin matters: split pins
        survive ``clear_split``, so without it the households already
        routed to the bad candidate would stay pinned to it forever — a
        "rolled-back" fleet still serving the regression to exactly the
        households the canary exposed. ``swapped`` (did the 100% stage's
        swap run?) drives the swap-back DIRECTLY: a fleet-wide
        ``swap_fn`` promotion never touches the local registry's
        default, so gating the reverse swap on ``registry.default_hash``
        alone would leave the FLEET on the bad candidate while reporting
        a rollback. Routing-table mutations only — in-flight requests
        finish on the bundle that admitted them, so a rollback drops
        zero requests."""
        self._clear_split_fn()
        self._clear_pins_fn()
        if swapped or self.registry.default_hash != self.incumbent:
            self._swap_to(self.incumbent)
        self._clear_split_fn()
        self._clear_pins_fn()

    # -- warehouse attribution -----------------------------------------------

    def _arm_decision_cost(
        self, config_hash: str, since_ts: float
    ) -> Tuple[Optional[float], int, int]:
        """(mean decision cost, n decisions, n nonfinite) for one arm
        from the warehouse's ``serve_decision`` rows since ``since_ts`` —
        the same per-bundle config_hash attribution ``telemetry-report
        --compare`` joins on."""
        if self.results_db is None or self.cfg is None:
            return None, 0, 0
        from p2pmicrogrid_tpu.data.trace_export import decision_cost

        con = sqlite3.connect(f"file:{self.results_db}?mode=ro", uri=True)
        try:
            rows = con.execute(
                "SELECT p.attrs_json FROM telemetry_points p "
                "JOIN telemetry_runs t ON t.run_id = p.run_id "
                "WHERE t.config_hash = ? AND p.kind = 'serve_decision' "
                "AND p.ts >= ?",
                (config_hash, since_ts),
            ).fetchall()
        finally:
            con.close()
        obs_rows, act_rows = [], []
        for (attrs_json,) in rows:
            try:
                attrs = json.loads(attrs_json) if attrs_json else {}
            except ValueError:
                continue
            if attrs.get("obs") is None or attrs.get("action") is None:
                continue
            obs_rows.append(attrs["obs"])
            act_rows.append(attrs["action"])
        if not obs_rows:
            return None, 0, 0
        # host-sync: warehouse JSON payloads, host data throughout.
        obs = np.asarray(obs_rows, dtype=np.float32)
        # host-sync: warehouse JSON payloads, host data throughout.
        act = np.asarray(act_rows, dtype=np.float32)
        nonfinite = int((~np.isfinite(obs)).any() or (~np.isfinite(act)).any())
        # Sanitize before the cost model: a NaN action poisons only its
        # own row's cost, and the nonfinite count above already condemns
        # the arm.
        cost = decision_cost(
            self.cfg, np.nan_to_num(obs), np.nan_to_num(act)
        )
        return float(cost.mean()), len(obs_rows), nonfinite

    def _arm_server_slo(
        self, config_hash: str, since_ts: float
    ) -> Tuple[Optional[float], int]:
        """(p95 latency ms, n requests) for one arm from the warehouse's
        ``serve_request`` rows since the stage started — the SERVER-side
        record of what the arm's engines actually did. The microbatch
        queue stamps every request with its measured enqueue->dispatch
        wait + batch service time in the serving bundle's telemetry run
        (keyed by config_hash), so a slow replica is charged by its own
        clock: client-side latencies — measured by whatever drove the
        stage — can under-report a stall the loadgen never waited out,
        and a fast loadgen clock must not be able to hide a slow arm."""
        if self.results_db is None:
            return None, 0
        con = sqlite3.connect(f"file:{self.results_db}?mode=ro", uri=True)
        try:
            rows = con.execute(
                "SELECT p.attrs_json FROM telemetry_points p "
                "JOIN telemetry_runs t ON t.run_id = p.run_id "
                "WHERE t.config_hash = ? AND p.kind = 'serve_request' "
                "AND p.ts >= ?",
                (config_hash, since_ts),
            ).fetchall()
            decision_rows = con.execute(
                "SELECT json_extract(p.attrs_json, '$.request_id') "
                "FROM telemetry_points p "
                "JOIN telemetry_runs t ON t.run_id = p.run_id "
                "WHERE t.config_hash = ? AND p.kind = 'serve_decision' "
                "AND p.ts >= ?",
                (config_hash, since_ts),
            ).fetchall()
        except sqlite3.OperationalError:
            return None, 0  # pre-warehouse DB
        finally:
            con.close()
        decision_ids = {str(r) for (r,) in decision_rows if r}
        latencies: List[float] = []
        id_latencies: List[float] = []
        for (attrs_json,) in rows:
            try:
                attrs = json.loads(attrs_json) if attrs_json else {}
            except ValueError:
                continue
            v = attrs.get("latency_ms")
            if not isinstance(v, (int, float)):
                continue
            latencies.append(float(v))
            rid = attrs.get("request_id")
            if rid and str(rid) in decision_ids:
                id_latencies.append(float(v))
        # Exact join: when requests and this arm's decisions share
        # request_ids, the SLO is computed over exactly the requests
        # that produced a recorded decision for THIS arm — a request
        # misattributed by the timestamp-era heuristics (shared queue,
        # clock skew) can no longer charge the wrong arm. Warehouses
        # written before ids existed fall back to every serve_request
        # row under the arm's config_hash, as before.
        if id_latencies:
            latencies = id_latencies
        if not latencies:
            return None, 0
        # host-sync: warehouse JSON payloads, host data.
        return float(np.percentile(np.asarray(latencies), 95)), len(latencies)

    # -- stage evaluation ----------------------------------------------------

    def _expected_arm(self, plan: StagePlan, household: Optional[str]) -> str:
        """The arm the routing table WOULD serve this household from —
        the attribution of last resort for requests whose response
        carries no config_hash (errors, transport failures). Mirrors
        ``BundleRegistry.route``: the promote stage serves everyone from
        the candidate; a split stage routes by the deterministic
        household slot; anonymous traffic serves the default."""
        from p2pmicrogrid_tpu.serve.registry import _household_slot

        if plan.is_promote:
            return self.candidate
        if household and _household_slot(household) < plan.percent:
            return self.candidate
        return self.incumbent

    def _arm_wire_metrics(
        self, traffic: StageTraffic, config_hash: str, plan: StagePlan
    ) -> dict:
        def arm_of(i: int) -> Optional[str]:
            h = traffic.config_hashes[i]
            if h is not None:
                return h
            household = (
                traffic.households[i]
                if i < len(traffic.households) else None
            )
            return self._expected_arm(plan, household)

        idx = [
            i for i in range(len(traffic.config_hashes))
            if arm_of(i) == config_hash
        ]
        errors = sum(
            1 for i in idx
            if traffic.statuses[i] >= 500 or traffic.statuses[i] < 0
        )
        ok = [i for i in idx if traffic.statuses[i] == 200]
        lat = traffic.latencies_ms[ok] if ok else np.zeros((0,))
        nonfinite = 0
        for i in ok:
            a = traffic.actions[i]
            if a is not None and not np.isfinite(
                # host-sync: wire JSON payloads, host data.
                np.asarray(a, dtype=np.float64)
            ).all():
                nonfinite += 1
        return {
            "requests": len(idx),
            "ok": len(ok),
            "errors": errors,
            "nonfinite_actions": nonfinite,
            "p95_ms": (
                round(float(np.percentile(lat, 95)), 3) if lat.size else 0.0
            ),
        }

    def _evaluate_stage(
        self, plan: StagePlan, traffic: StageTraffic, since_ts: float
    ) -> CanaryStageReport:
        b = self.budgets
        arms = {}
        for hash_ in (self.incumbent, self.candidate):
            m = self._arm_wire_metrics(traffic, hash_, plan)
            cost, n_cost, nonfinite_db = self._arm_decision_cost(
                hash_, since_ts
            )
            m["decision_cost"] = (
                round(cost, 6) if cost is not None else None
            )
            m["decisions"] = n_cost
            m["nonfinite_actions"] += nonfinite_db
            # Server-side SLO attribution (ISSUE 11 satellite): when the
            # warehouse carries the arm's own serve_request spans for this
            # stage, THEY are the latency the guards judge — the wire
            # number demotes to detail. A slow replica cannot hide behind
            # a fast loadgen clock.
            server_p95, server_n = self._arm_server_slo(hash_, since_ts)
            if server_p95 is not None:
                m["client_p95_ms"] = m["p95_ms"]
                m["p95_ms"] = round(server_p95, 3)
                m["server_requests"] = server_n
            arms[hash_] = m
        cand, inc = arms[self.candidate], arms[self.incumbent]
        # The incumbent baseline accumulates ACROSS stages: at the 100%
        # (promote) stage the incumbent serves nothing — without the
        # carried baseline, the final stage's cost check would be
        # inconclusive by construction and a slow-burn regression could
        # ship at full traffic.
        if (
            inc["decision_cost"] is not None and inc["decisions"] > 0
        ):
            s, n = self._inc_baseline
            self._inc_baseline = (
                s + inc["decision_cost"] * inc["decisions"],
                n + inc["decisions"],
            )
        if inc["decisions"] < b.min_requests and self._inc_baseline[1] >= (
            b.min_requests
        ):
            s, n = self._inc_baseline
            inc = dict(inc, decision_cost=round(s / n, 6), decisions=n)
            arms[self.incumbent]["baseline_decision_cost"] = inc[
                "decision_cost"
            ]
            arms[self.incumbent]["baseline_decisions"] = n
        reasons: List[str] = []
        if cand["nonfinite_actions"] > 0:
            reasons.append(
                f"candidate served {cand['nonfinite_actions']} nonfinite "
                "action(s) — poisoned bundle live"
            )
        cand_attempts = max(cand["requests"], 1)
        if cand["errors"] / cand_attempts > b.max_error_rate:
            reasons.append(
                f"candidate error rate {cand['errors']}/{cand['requests']} "
                f"over the {b.max_error_rate:g} budget"
            )
        if cand["p95_ms"] > b.slo_p95_ms:
            reasons.append(
                f"candidate p95 {cand['p95_ms']:.1f} ms over the "
                f"{b.slo_p95_ms:g} ms stage budget"
            )
        if (
            inc["p95_ms"] > 0
            and cand["p95_ms"] > b.max_p95_ratio * inc["p95_ms"]
        ):
            reasons.append(
                f"candidate p95 {cand['p95_ms']:.1f} ms > "
                f"{b.max_p95_ratio:g}x incumbent ({inc['p95_ms']:.1f} ms)"
            )
        if (
            cand["decision_cost"] is not None
            and inc["decision_cost"] is not None
            and min(cand["decisions"], inc["decisions"]) >= b.min_requests
        ):
            tol = max(abs(inc["decision_cost"]), 1.0) * b.max_cost_regression
            if cand["decision_cost"] > inc["decision_cost"] + tol:
                reasons.append(
                    f"candidate decision cost {cand['decision_cost']:.4f} "
                    f"regresses the incumbent's {inc['decision_cost']:.4f} "
                    f"past the {b.max_cost_regression:g} tolerance"
                )
        return CanaryStageReport(
            percent=plan.percent,
            n_requests=int(traffic.statuses.shape[0]),
            ok=not reasons,
            arms=arms,
            reasons=reasons,
        )

    # -- the ramp ------------------------------------------------------------

    def run(
        self, drive_stage: Callable[[StagePlan], StageTraffic]
    ) -> CanaryResult:
        """Execute the ramp. ``drive_stage(plan)`` must push live traffic
        while the stage's routing is in effect and report it as a
        ``StageTraffic``. Returns when the candidate promoted through
        the last stage or the ramp aborted and rolled back."""
        result = CanaryResult()
        swapped = False
        self._inc_baseline = (0.0, 0)
        try:
            for i, pct in enumerate(self.stages):
                plan = StagePlan(
                    index=i, percent=pct, is_promote=pct >= 100.0
                )
                if plan.is_promote:
                    # The final stage IS the promotion: default flips to
                    # the candidate (fleet-wide two-phase via swap_fn),
                    # then full traffic is watched before declaring it.
                    self._swap_to(self.candidate)
                    swapped = True
                else:
                    # Widening the split must re-roll household routing:
                    # pins recorded at the previous stage would freeze
                    # the arm's membership (registry.clear_pins).
                    self._clear_pins_fn()
                    self._split_fn(self.candidate, pct)
                since_ts = time.time()
                if self.flush_fn is not None:
                    self.flush_fn()  # stage boundary: drain pre-stage rows
                traffic = drive_stage(plan)
                if self.flush_fn is not None:
                    self.flush_fn()
                report = self._evaluate_stage(plan, traffic, since_ts)
                result.stages.append(report)
                result.n_requests += report.n_requests
                # host-sync: wire statuses, host data.
                result.n_ok += int((traffic.statuses == 200).sum())
                result.n_shed += traffic.n_shed
                if self.telemetry is not None:
                    self.telemetry.event(
                        "promotion",
                        phase="canary_stage",
                        candidate=self.candidate,
                        incumbent=self.incumbent,
                        stage=i,
                        **report.to_fields(),
                    )
                if not report.ok:
                    result.aborted_stage = i
                    result.reasons = report.reasons
                    self._restore_incumbent(swapped)
                    result.rolled_back = True
                    if self.telemetry is not None:
                        self.telemetry.event(
                            "promotion",
                            phase="rolled_back",
                            candidate=self.candidate,
                            incumbent=self.incumbent,
                            stage=i,
                            reasons=report.reasons,
                        )
                        self.telemetry.counter("promotion.rollbacks")
                    return result
            result.promoted = True
            if self.telemetry is not None:
                self.telemetry.event(
                    "promotion",
                    phase="promoted",
                    candidate=self.candidate,
                    incumbent=self.incumbent,
                    stages=[s.to_fields() for s in result.stages],
                )
                self.telemetry.counter("promotion.promotions")
            return result
        except BaseException:
            # A crashed driver/controller must not strand a half-ramped
            # fleet: restore the incumbent, then re-raise.
            if swapped or self.registry.split is not None:
                self._restore_incumbent(swapped)
                result.rolled_back = True
            raise


# -- seeded acceptance harness -------------------------------------------------

# The crafted tabular policies (closed-form, no training): the Q-table
# axis order is [A, time, temp, balance, p2p, action] with action values
# (0.0, 0.5, 1.0) — ops/obs.discretize maps obs[1] (normalized indoor
# temperature) onto the temp axis with bin 1 at the comfort band's
# center-ish; "cold" is the lower half.
INJECTION_KINDS = (
    "good", "cost_regressed", "nan_poisoned", "slo_violating",
)


def make_crafted_bundle(cfg, kind: str, out_dir: str) -> str:
    """Export a crafted tabular bundle for the harness.

    Closed-form policies over the temp axis (``ops/obs.discretize`` maps
    the normalized indoor temperature onto it; the lower half is "cold"):

    * ``incumbent``       — thermostat: full power when cold, off when
                            warm (the healthy reference policy).
    * ``good``            — eco-thermostat: full power only when VERY
                            cold, half power when mildly cold, off when
                            warm — strictly less energy than the
                            incumbent while still heating, so it beats
                            the incumbent's cost without collapsing
                            comfort (the genuinely-better candidate).
    * ``cost_regressed``  — always heat at full power: comfort is fine,
                            the energy bill is not (the gate's cost rule
                            must block it; forced past the gate, the
                            live decision-cost attribution must catch
                            its overheating waste).
    * ``nan_poisoned``    — the good table with NaNs written through it.
    * ``slo_violating``   — the good table (its latency injection lives
                            in the bench clock, mirroring faults.py's
                            stall kind — a bundle's params cannot carry
                            slowness, its serving measurement can).
    """
    import jax

    from p2pmicrogrid_tpu.serve.export import export_policy_bundle
    from p2pmicrogrid_tpu.train import init_policy_state

    if cfg.train.implementation != "tabular":
        raise ValueError("crafted harness bundles are tabular-only")
    ps = init_policy_state(cfg, jax.random.PRNGKey(cfg.train.seed))
    q = np.zeros(ps.q_table.shape, dtype=np.float32)
    ntp = cfg.qlearning.num_temp_states
    bins = np.arange(ntp)
    mid = ntp // 2
    cold = bins < mid                 # below the setpoint
    very_cold = bins < max(mid - 3, 1)  # well below it
    if kind == "incumbent":
        q[:, :, cold, :, :, 2] = 1.0   # cold -> full power
        q[:, :, ~cold, :, :, 0] = 1.0  # warm -> off
    elif kind in ("good", "nan_poisoned", "slo_violating"):
        q[:, :, very_cold, :, :, 2] = 1.0          # very cold -> full
        q[:, :, cold & ~very_cold, :, :, 1] = 1.0  # mildly cold -> half
        q[:, :, ~cold, :, :, 0] = 1.0              # warm -> off
        if kind == "nan_poisoned":
            q[..., :] = np.nan
    elif kind == "cost_regressed":
        q[..., 2] = 1.0  # always full power: pure energy waste
    else:
        raise ValueError(f"unknown crafted kind {kind!r}")
    import jax.numpy as jnp

    ps = ps._replace(q_table=jnp.asarray(q))
    return export_policy_bundle(
        cfg, ps, out_dir, source={"kind": f"crafted:{kind}"}
    )


def _drive_wire_stage(
    host: str,
    port: int,
    obs: np.ndarray,
    households: List[str],
    timeout_s: float = 30.0,
) -> StageTraffic:
    """Fire one request per obs row at a live gateway over real HTTP
    (sequential — the harness measures safety semantics, not throughput;
    serve-bench owns the SLO measurements)."""
    n = obs.shape[0]
    statuses = np.full(n, -1, dtype=np.int64)
    latencies = np.zeros(n)
    hashes: List[Optional[str]] = [None] * n
    actions: List[Optional[list]] = [None] * n
    sent_households: List[Optional[str]] = [
        households[i % len(households)] for i in range(n)
    ]
    n_shed = 0
    for i in range(n):
        body = json.dumps({
            "household": sent_households[i],
            "obs": obs[i].tolist(),
        })
        conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
        t0 = time.perf_counter()
        try:
            conn.request(
                "POST", "/v1/act", body=body,
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            raw = resp.read()
            latencies[i] = (time.perf_counter() - t0) * 1e3
            statuses[i] = resp.status
            if resp.status == 429:
                n_shed += 1
            if resp.status == 200:
                doc = json.loads(raw)
                hashes[i] = doc.get("config_hash")
                actions[i] = doc.get("actions")
        except (OSError, ValueError):
            latencies[i] = (time.perf_counter() - t0) * 1e3
        finally:
            conn.close()
    return StageTraffic(
        statuses=statuses,
        latencies_ms=latencies,
        config_hashes=hashes,
        actions=actions,
        households=sent_households,
        n_shed=n_shed,
    )


def run_promotion_pipeline(
    cfg,
    candidate_dir: str,
    incumbent_dir: str,
    gate_budgets: GateBudgets = GateBudgets(),
    canary_budgets: CanaryBudgets = CanaryBudgets(),
    stages: Sequence[float] = (5.0, 25.0, 100.0),
    results_db: Optional[str] = None,
    telemetry=None,
    seed: int = 0,
    requests_per_stage: int = 256,
    n_households: int = 128,
    skip_gate: bool = False,
    s_eval: int = 8,
    max_batch: int = 16,
    gate_service_time_fn: Optional[Callable[[int, int], float]] = None,
    incumbent_eval: Optional[Tuple[float, float]] = None,
    regime_specs: Optional[Sequence] = None,
    regime_s_per_regime: int = 4,
    batching: str = "continuous",
) -> dict:
    """Gate + canary for ONE candidate against a live in-process gateway.

    Builds a gateway over ``[incumbent, candidate]`` (incumbent default),
    runs the offline gate (unless ``skip_gate`` — the operator-override
    path whose misuse the canary exists to survive), then ramps the
    candidate with live wire traffic per stage. Returns the
    ``promotion_case``-row fields: gate verdict, per-stage canary
    reports, availability, rolled_back/promoted flags and a bit-exact
    check of the post-rollback (or post-promote) serving path against
    the bundle that should be serving.

    ``batching`` selects the gateway queue front; the default is now the
    slot-level ``"continuous"`` batcher (bit-exact vs ``"micro"`` for the
    stateless bundles promotion serves, verified per-request here by the
    post-ramp bit-exact check against a direct engine — so the committed
    ``PROMOTION_*``/``AUTOPILOT_*`` capture semantics carry over
    unchanged). Pass ``"micro"`` to reproduce the coalescing-window
    queue those captures were originally measured under.
    """
    import jax  # noqa: F401 — engine construction below needs a backend

    from p2pmicrogrid_tpu.serve.engine import PolicyEngine
    from p2pmicrogrid_tpu.serve.export import load_policy_bundle
    from p2pmicrogrid_tpu.serve.gateway import (
        AdmissionConfig,
        GatewayServer,
        build_gateway,
    )
    from p2pmicrogrid_tpu.serve.loadgen import synthetic_obs

    cand_hash = load_policy_bundle(candidate_dir)[0].get("config_hash")
    inc_hash = load_policy_bundle(incumbent_dir)[0].get("config_hash")

    gate_fields = None
    if not skip_gate:
        verdict = run_promotion_gate(
            cfg, candidate_dir, incumbent_dir,
            budgets=gate_budgets, telemetry=telemetry,
            s_eval=s_eval, bench_seed=seed, max_batch=max_batch,
            service_time_fn=gate_service_time_fn,
            incumbent_eval=incumbent_eval,
            regime_specs=regime_specs,
            regime_s_per_regime=regime_s_per_regime,
        )
        gate_fields = verdict.to_fields()
        if not verdict.passed:
            return {
                "candidate": cand_hash,
                "incumbent": inc_hash,
                "gate_verdict": verdict.verdict,
                "blocked_at_gate": True,
                "canary_stages": [],
                "availability": 1.0,
                "rolled_back": False,
                "promoted": False,
                "n_requests": 0,
                "bit_exact_after": None,
                "gate": gate_fields,
            }

    gateway = build_gateway(
        [incumbent_dir, candidate_dir],
        max_batch=max_batch,
        max_wait_s=0.005,
        results_db=results_db,
        device="cpu",
        admission=AdmissionConfig(
            max_queue_depth=100_000, wait_budget_ms=1e9
        ),
        run_name="promotion",
        batching=batching,
    )
    server = GatewayServer(gateway)
    host, port = server.start()
    try:
        def flush() -> None:
            for h in gateway.registry.hashes:
                tel = gateway.registry.get(h).telemetry
                if tel is not None:
                    tel.flush()

        households = [f"house-{i:04d}" for i in range(n_households)]

        def drive(plan: StagePlan) -> StageTraffic:
            obs = synthetic_obs(
                requests_per_stage, cfg.sim.n_agents,
                seed=seed + 101 * (plan.index + 1),
            )
            return _drive_wire_stage(host, port, obs, households)

        controller = CanaryController(
            gateway.registry,
            candidate_hash=cand_hash,
            incumbent_hash=inc_hash,
            cfg=cfg,
            stages=stages,
            budgets=canary_budgets,
            telemetry=telemetry,
            results_db=results_db,
            flush_fn=flush if results_db else None,
        )
        result = controller.run(drive)

        # After the ramp settles, the serving default must be the right
        # bundle AND serve bit-exact to a direct engine on that bundle —
        # a rolled-back fleet serving approximately-the-incumbent is
        # still a failed rollback.
        expect_dir = candidate_dir if result.promoted else incumbent_dir
        expect_hash = cand_hash if result.promoted else inc_hash
        check_obs = synthetic_obs(8, cfg.sim.n_agents, seed=seed + 9999)
        check = _drive_wire_stage(host, port, check_obs, households[:1])
        reference = PolicyEngine(
            bundle_dir=expect_dir, max_batch=max_batch, device="cpu"
        )
        want = reference.act(check_obs)
        bit_exact = bool(
            (check.statuses == 200).all()
            and all(h == expect_hash for h in check.config_hashes)
            # host-sync: wire JSON payloads, host data.
            and (np.asarray(check.actions, dtype=np.float32) == want).all()
        )
    finally:
        server.stop()

    return {
        "candidate": cand_hash,
        "incumbent": inc_hash,
        "gate_verdict": (
            "skipped" if skip_gate else "pass"
        ),
        "blocked_at_gate": False,
        "canary_stages": [s.to_fields() for s in result.stages],
        "availability": round(result.availability, 6),
        "rolled_back": result.rolled_back,
        "promoted": result.promoted,
        "n_requests": result.n_requests,
        "n_failed": result.n_failed,
        "aborted_stage": result.aborted_stage,
        "abort_reasons": result.reasons,
        "bit_exact_after": bit_exact,
        "gate": gate_fields,
    }


def promotion_bench(
    cfg,
    work_dir: str,
    cases: Sequence[str] = INJECTION_KINDS,
    seed: int = 0,
    requests_per_stage: int = 192,
    n_households: int = 128,
    stages: Sequence[float] = (5.0, 25.0, 100.0),
    results_db: Optional[str] = None,
    telemetry=None,
    emit: Optional[Callable[[dict], None]] = None,
    slo_stall_s: float = 0.25,
    gate_budgets: GateBudgets = GateBudgets(),
    canary_budgets: CanaryBudgets = CanaryBudgets(),
) -> List[dict]:
    """The seeded bad-candidate injection harness (``promote --inject``).

    One ``promotion_case`` metric row per case (gate verdict, canary
    stages, availability, rolled_back/promoted, bit-exactness after) and
    a final ``promotion_bench`` headline. Case semantics:

    * ``good``           — full pipeline; MUST promote end-to-end.
    * ``cost_regressed`` — gate blocks it; then the same candidate is
      forced past the gate (``skip_gate`` — the operator-override path)
      and MUST be rolled back mid-canary by live cost attribution.
    * ``nan_poisoned``   — gate blocks on a non-finite held-out eval.
    * ``slo_violating``  — gate blocks on the modeled serve-bench SLO
      (``slo_stall_s`` per batch on the virtual clock — the stall-fault
      analogue for a candidate that is correct but too slow).

    Deterministic under ``seed``: crafted closed-form policies, seeded
    obs/household streams, virtual-clock SLO timing.
    """
    import os

    os.makedirs(work_dir, exist_ok=True)
    incumbent_dir = make_crafted_bundle(
        cfg, "incumbent", os.path.join(work_dir, "incumbent")
    )
    # The incumbent's held-out eval is the same for every case: compute
    # it once instead of once per gate.
    incumbent_eval = evaluate_bundle_cost(cfg, incumbent_dir)
    rows: List[dict] = []
    outcomes: dict = {}

    def case_row(case: str, fields: dict, expected: str) -> dict:
        ok = {
            "promoted": fields.get("promoted", False)
            and not fields.get("rolled_back", False),
            "blocked": fields.get("blocked_at_gate", False),
            "rolled_back": fields.get("rolled_back", False)
            and not fields.get("promoted", False),
        }[expected]
        outcomes[case] = ok
        return {
            "metric": "promotion_case",
            "value": float(fields.get("availability", 1.0)),
            "unit": "availability",
            "vs_baseline": 1.0 if ok else 0.0,
            "case": case,
            "expected": expected,
            "outcome_ok": ok,
            "seed": seed,
            **fields,
        }

    for case in cases:
        cand_cfg = cfg.replace(
            train=dataclasses.replace(
                cfg.train,
                # Distinct config_hash per crafted candidate: the
                # registry/canary key. Generations continue the episode
                # count exactly like train/continual.py's candidates.
                starting_episodes=cfg.train.starting_episodes + 100
                + INJECTION_KINDS.index(case),
            )
        )
        cand_dir = make_crafted_bundle(
            cand_cfg, case, os.path.join(work_dir, case)
        )
        stall_fn = None
        if case == "slo_violating":
            stall_fn = lambda i, j: slo_stall_s  # noqa: E731
        fields = run_promotion_pipeline(
            cfg, cand_dir, incumbent_dir,
            gate_budgets=gate_budgets,
            canary_budgets=canary_budgets,
            stages=stages,
            results_db=results_db,
            telemetry=telemetry,
            seed=seed + INJECTION_KINDS.index(case),
            requests_per_stage=requests_per_stage,
            n_households=n_households,
            gate_service_time_fn=stall_fn,
            incumbent_eval=incumbent_eval,
        )
        expected = "promoted" if case == "good" else "blocked"
        rows.append(case_row(case, fields, expected))
        if case == "cost_regressed":
            # The dangerous half: force the regressed candidate past the
            # gate (operator override) — the canary must catch it live.
            forced = run_promotion_pipeline(
                cfg, cand_dir, incumbent_dir,
                gate_budgets=gate_budgets,
                canary_budgets=canary_budgets,
                stages=stages,
                results_db=results_db,
                telemetry=telemetry,
                seed=seed + 100,
                requests_per_stage=requests_per_stage,
                n_households=n_households,
                skip_gate=True,
            )
            rows.append(
                case_row("cost_regressed_forced", forced, "rolled_back")
            )

    all_safe = all(outcomes.values())
    rows.append(
        {
            "metric": "promotion_bench",
            "value": float(sum(outcomes.values())),
            "unit": "cases_ok",
            "vs_baseline": 1.0 if all_safe else 0.0,
            "cases": {k: bool(v) for k, v in outcomes.items()},
            "all_safe": all_safe,
            "seed": seed,
            "stages": list(stages),
            "requests_per_stage": requests_per_stage,
        }
    )
    if emit is not None:
        for row in rows:
            emit(row)
    return rows
