"""Open-loop load generation + SLO reporting for the serving engine.

The question serve-bench answers: at a given request rate, what latency do
households see from the batched engine, and how much compute does padding
waste? Methodology:

* **Arrivals are open-loop** (Poisson, fixed rate, independent of service
  times) — the standard way to expose queueing delay; a closed loop would
  let a slow server throttle its own offered load and flatter the tail.
* **Batching runs on a virtual clock.** ``plan_open_loop`` replays the
  microbatch policy (dispatch at ``max_batch`` queued or ``max_wait`` after
  the oldest arrival, server serially busy) deterministically over the
  arrival times, asking a ``service_time_fn`` how long each dispatched
  batch takes. serve-bench passes a ``service_time_fn`` that EXECUTES the
  batch on the real engine and returns the measured wall time, so queueing
  waits are exactly reproducible while service times are real; tests pass a
  synthetic model, making the whole percentile pipeline deterministic under
  a fixed seed.
* **Per-request latency** = batch completion - request arrival (queue wait
  + padded-batch service). Reported as p50/p95/p99 against an SLO budget,
  plus throughput (completed / makespan) and the padding-waste fraction.

Output goes through the telemetry stdout sink with the same one-JSON-per-
line hygiene as ``bench`` (rows follow the metric-row schema that
``tools/check_artifacts_schema.py`` validates; the LAST line is the
headline row carrying every stat).
"""

from __future__ import annotations

import asyncio
import json
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import numpy as np

from p2pmicrogrid_tpu.serve.wire import FrameTooLarge, WireProtocolError
from p2pmicrogrid_tpu.telemetry.tracing import record_span, root_context


# --- client retry primitives --------------------------------------------------
#
# Shared by the network loadgen's optional retry mode (below) and the fleet
# router (serve/router.py), which layers failover re-routing on top. They
# live here — not in router.py — so the import direction stays acyclic
# (router imports loadgen for the open-loop schedule machinery).


@dataclass(frozen=True)
class RetryPolicy:
    """Client-side retry discipline for transient serve failures.

    * ``max_attempts`` bounds tries per request (1 = never retry).
    * ``deadline_s`` is the per-request wall budget: no attempt or backoff
      sleep may start past it — a household's 15-minute-slot decision is
      worthless late, so requests fail fast rather than queue forever.
    * Backoff between attempts is capped exponential with multiplicative
      jitter: ``base * 2^attempt`` clipped to ``backoff_cap_s``, scaled by
      a uniform draw from [1 - jitter, 1]. Jitter de-synchronizes retry
      waves — a fleet-wide brown-out must not turn into a synchronized
      retry hammer on the recovering replica.
    * A server-supplied ``Retry-After`` (429/503 sheds carry one) takes
      precedence over the computed backoff when larger — the server knows
      its own recovery horizon better than the client's guess.
    """

    max_attempts: int = 4
    deadline_s: float = 10.0
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 2.0
    jitter: float = 0.5
    honor_retry_after: bool = True

    def backoff_s(
        self,
        attempt: int,
        rng: random.Random,
        retry_after_s: Optional[float] = None,
    ) -> float:
        """Sleep before attempt ``attempt + 1`` (attempt counts from 0)."""
        base = min(self.backoff_cap_s, self.backoff_base_s * (2.0 ** attempt))
        delay = base * (1.0 - self.jitter * rng.random())
        if retry_after_s is not None and self.honor_retry_after:
            delay = max(delay, retry_after_s)
        return delay


class RetryBudget:
    """Token-bucket retry budget (the anti-retry-storm governor).

    Every first attempt deposits ``ratio`` tokens (capped); every retry
    withdraws one. Under a brown-out the bucket drains and retries STOP
    fleet-wide at ~``ratio`` of offered load, instead of each client
    multiplying the overload by ``max_attempts`` — the retry-storm
    failure mode. ``min_tokens`` is the starting balance so low-traffic
    periods can still retry. Thread-safe (the router's probe thread and
    event loop share it).
    """

    def __init__(
        self, ratio: float = 0.2, min_tokens: float = 8.0,
        cap: float = 64.0,
    ):
        if ratio < 0:
            raise ValueError(f"ratio must be >= 0, got {ratio}")
        self.ratio = ratio
        self.cap = max(cap, min_tokens)
        self._tokens = min(min_tokens, self.cap)
        self._lock = threading.Lock()
        self.spent = 0
        self.denied = 0

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def on_attempt(self) -> None:
        """Deposit for one FIRST attempt (not retries)."""
        with self._lock:
            self._tokens = min(self.cap, self._tokens + self.ratio)

    def try_spend(self) -> bool:
        """Withdraw one token for a retry; False = budget exhausted."""
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.spent += 1
                return True
            self.denied += 1
            return False


# Arrival schedules are built on an integer-nanosecond virtual clock and
# only converted to float seconds at the edge. A float64 cumsum of ~10 us
# exponential gaps accumulates rounding drift that grows with n — at
# 100k rps x minutes (10^7+ arrivals) the drift reaches the same order as
# the gaps themselves, silently reshaping batch composition between runs
# of different lengths. int64 addition is exact; 2^53 ns (~104 days of
# virtual time) bounds where the final float conversion stays exact too.
_MAX_EXACT_NS = 1 << 53


def gaps_to_schedule_ns(gaps_s: np.ndarray) -> np.ndarray:
    """Quantize inter-arrival gaps (seconds) to >= 1 ns each and cumsum on
    the int64 nanosecond clock — the exact arrival schedule. The 1 ns
    floor keeps the schedule STRICTLY increasing (a zero-quantized gap
    would make two arrivals simultaneous and dispatch-order ambiguous)."""
    gaps_ns = np.rint(np.asarray(gaps_s, dtype=float) * 1e9).astype(np.int64)
    np.maximum(gaps_ns, 1, out=gaps_ns)
    t_ns = np.cumsum(gaps_ns)
    if t_ns.size and int(t_ns[-1]) >= _MAX_EXACT_NS:
        raise OverflowError(
            f"arrival schedule spans {int(t_ns[-1])} ns >= 2^53 — beyond "
            "~104 days of virtual time the float64 second conversion "
            "stops being nanosecond-exact; split the schedule"
        )
    return t_ns


def schedule_ns_to_s(t_ns: np.ndarray) -> np.ndarray:
    """int64 nanosecond schedule -> float64 seconds. Below 2^53 ns every
    tick is exactly representable, so ``round(t * 1e9)`` round-trips to
    the integer schedule (regression-tested in tests/test_scale.py)."""
    t_ns = np.asarray(t_ns, dtype=np.int64)
    if t_ns.size and int(t_ns[-1]) >= _MAX_EXACT_NS:
        raise OverflowError(
            f"schedule tick {int(t_ns[-1])} ns >= 2^53 is not exactly "
            "representable in float64 seconds"
        )
    return t_ns.astype(np.float64) / 1e9


def poisson_arrivals(rate_hz: float, n: int, seed: int = 0) -> np.ndarray:
    """Cumulative arrival times (seconds) of ``n`` Poisson requests,
    exact on the integer-nanosecond virtual clock (no cumsum drift at
    100k+ rps x minutes — see ``gaps_to_schedule_ns``)."""
    if rate_hz <= 0:
        raise ValueError(f"rate_hz must be > 0, got {rate_hz}")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate_hz, size=n)
    return schedule_ns_to_s(gaps_to_schedule_ns(gaps))


def bursty_arrivals(
    rate_hz: float,
    n: int,
    burst_factor: float = 8.0,
    burst_dwell_s: float = 0.25,
    seed: int = 0,
) -> np.ndarray:
    """Markov-modulated on/off Poisson arrivals (MMPP-2): ``n`` arrival
    times under a two-state process that alternates exponential dwells
    (mean ``burst_dwell_s``) between an ON rate ``burst_factor`` times the
    OFF rate, scaled so the MEAN offered rate stays ``rate_hz``:

        rate_on  = rate_hz * 2 * f / (1 + f)
        rate_off = rate_hz * 2     / (1 + f)        (f = burst_factor)

    This is the arrival family that exposes the batch-boundary-wait
    pathology the continuous batcher removes: every burst onset lands a
    clump of requests behind whatever the microbatch queue has in flight
    plus its coalescing window, so the p99 is set by waits, not compute.
    ``burst_factor=1`` degenerates to plain Poisson. Deterministic given
    the seed (one ``np.random.default_rng`` stream drives dwells and gaps
    in a fixed order).
    """
    if rate_hz <= 0:
        raise ValueError(f"rate_hz must be > 0, got {rate_hz}")
    if burst_factor < 1.0:
        raise ValueError(f"burst_factor must be >= 1, got {burst_factor}")
    if burst_factor == 1.0:
        return poisson_arrivals(rate_hz, n, seed=seed)
    if burst_dwell_s <= 0:
        raise ValueError(f"burst_dwell_s must be > 0, got {burst_dwell_s}")
    rng = np.random.default_rng(seed)
    rate_on = rate_hz * 2.0 * burst_factor / (1.0 + burst_factor)
    rate_off = rate_hz * 2.0 / (1.0 + burst_factor)
    # Same integer-nanosecond clock as poisson_arrivals: each drawn dwell
    # and gap is quantized to >= 1 ns at the draw, and the running clocks
    # are Python ints — exact at any n, so long schedules cannot drift a
    # request across a dwell boundary relative to short ones.
    arrivals_ns: List[int] = []
    t_ns = 0
    on = True  # start in a burst: the first dispatch already sees a clump
    while len(arrivals_ns) < n:
        dwell_ns = max(1, round(rng.exponential(burst_dwell_s) * 1e9))
        rate = rate_on if on else rate_off
        tt_ns = t_ns
        while len(arrivals_ns) < n:
            tt_ns += max(1, round(rng.exponential(1.0 / rate) * 1e9))
            if tt_ns >= t_ns + dwell_ns:
                break
            arrivals_ns.append(tt_ns)
        t_ns += dwell_ns
        on = not on
    return schedule_ns_to_s(np.asarray(arrivals_ns[:n], dtype=np.int64))


def make_arrivals(
    rate_hz: float,
    n: int,
    seed: int = 0,
    burst_factor: float = 1.0,
    burst_dwell_s: float = 0.25,
):
    """(arrivals, burst_config) — the one place the bench entry points
    resolve their arrival mode, so every headline reports the SAME
    ``burst_config`` block the schedule was actually generated under.
    ``burst_factor != 1`` routes through ``bursty_arrivals``, so an
    out-of-range value (< 1) fails ITS loud validation instead of being
    silently benched as plain Poisson."""
    if burst_factor != 1.0:
        return (
            bursty_arrivals(
                rate_hz, n, burst_factor=burst_factor,
                burst_dwell_s=burst_dwell_s, seed=seed,
            ),
            {
                "mode": "bursty",
                "burst_factor": burst_factor,
                "burst_dwell_s": burst_dwell_s,
                "seed": seed,
            },
        )
    return (
        poisson_arrivals(rate_hz, n, seed=seed),
        {"mode": "poisson", "seed": seed},
    )


@dataclass
class LoadgenResult:
    """Per-request latencies plus the batch schedule that produced them."""

    latencies_s: np.ndarray      # [N]
    batch_sizes: List[int]
    bucket_sizes: List[int]
    makespan_s: float            # first arrival -> last completion
    # Full batch schedule (per batch): first request index, dispatch instant
    # and measured/modeled service seconds — enough to reconstruct every
    # request's enqueue->dispatch wait for the per-request trace records.
    batch_starts: List[int] = field(default_factory=list)
    dispatch_s: List[float] = field(default_factory=list)
    service_s: List[float] = field(default_factory=list)

    @property
    def n_requests(self) -> int:
        return int(self.latencies_s.shape[0])

    @property
    def throughput_rps(self) -> float:
        return self.n_requests / self.makespan_s if self.makespan_s > 0 else 0.0

    @property
    def padding_waste(self) -> float:
        """Fraction of computed batch rows that were padding."""
        total = sum(self.bucket_sizes)
        return 1.0 - sum(self.batch_sizes) / total if total else 0.0

    def latency_ms(self, q: float) -> float:
        return float(np.percentile(self.latencies_s, q) * 1e3)


def plan_open_loop(
    arrivals: np.ndarray,
    service_time_fn: Callable[[int, int], float],
    max_batch: int,
    max_wait_s: float,
    bucket_fn: Optional[Callable[[int], int]] = None,
) -> LoadgenResult:
    """Deterministic replay of the microbatch policy over ``arrivals``.

    ``service_time_fn(i, j)`` serves requests [i, j) and returns the batch's
    service seconds (measured on a real engine, or modeled in tests).
    Dispatch rule, matching ``engine.MicroBatchQueue`` exactly: the batch's
    coalescing window is anchored at its OLDEST request's arrival — dispatch
    at ``max(server_free, oldest_arrival + max_wait_s)``, or as soon as
    ``max_batch`` requests have queued (but never before the server frees);
    every request arrived by the dispatch instant joins, up to the cap.
    """
    if bucket_fn is None:
        bucket_fn = lambda n: n
    arrivals = np.asarray(arrivals, dtype=float)
    n = arrivals.shape[0]
    latencies = np.zeros(n)
    batch_sizes: List[int] = []
    bucket_sizes: List[int] = []
    batch_starts: List[int] = []
    dispatch_s: List[float] = []
    service_s: List[float] = []
    free = 0.0
    i = 0
    while i < n:
        dispatch = max(free, arrivals[i] + max_wait_s)
        j = i + 1
        while j < n and (j - i) < max_batch and arrivals[j] <= dispatch:
            j += 1
        if (j - i) == max_batch:
            # Filled before the window closed: dispatch at the filling
            # arrival (or the moment the server frees, whichever is later).
            dispatch = max(free, arrivals[j - 1])
        service = service_time_fn(i, j)
        done = dispatch + service
        latencies[i:j] = done - arrivals[i:j]
        batch_sizes.append(j - i)
        bucket_sizes.append(bucket_fn(j - i))
        batch_starts.append(i)
        dispatch_s.append(float(dispatch))
        service_s.append(float(service))
        free = done
        i = j
    return LoadgenResult(
        latencies_s=latencies,
        batch_sizes=batch_sizes,
        bucket_sizes=bucket_sizes,
        makespan_s=float(free - arrivals[0]),
        batch_starts=batch_starts,
        dispatch_s=dispatch_s,
        service_s=service_s,
    )


def synthetic_obs(n: int, n_agents: int, seed: int = 0) -> np.ndarray:
    """Request observations drawn uniformly over the serving contract's
    feature ranges (obs_spec: time in [0,1), the normalized features in
    [-1, 1])."""
    rng = np.random.default_rng(seed)
    obs = np.empty((n, n_agents, 4), dtype=np.float32)
    obs[..., 0] = rng.uniform(0.0, 1.0, (n, n_agents))
    obs[..., 1:] = rng.uniform(-1.0, 1.0, (n, n_agents, 3))
    return obs


def _emit_request_traces(tel, arrivals: np.ndarray, result: LoadgenResult) -> None:
    """One ``serve_request`` event per request from the replayed batch
    schedule (same fields as ``MicroBatchQueue``'s live traces, plus the
    virtual-clock arrival/dispatch instants)."""
    for b, start in enumerate(result.batch_starts):
        size = result.batch_sizes[b]
        bucket = result.bucket_sizes[b]
        dispatch = result.dispatch_s[b]
        service_ms = result.service_s[b] * 1e3
        for r in range(start, start + size):
            wait_ms = (dispatch - arrivals[r]) * 1e3
            tel.event(
                "serve_request",
                source="loadgen",
                request=r,
                batch=b,
                batch_size=size,
                bucket=bucket,
                padded_rows=bucket - size,
                arrival_s=round(float(arrivals[r]), 6),
                dispatch_s=round(dispatch, 6),
                wait_ms=round(wait_ms, 3),
                service_ms=round(service_ms, 3),
                latency_ms=round(float(result.latencies_s[r]) * 1e3, 3),
            )


# --- wire-level load generation (serve-bench --network) ----------------------
#
# The virtual-clock planner above answers "what do the QUEUE + DEVICE cost?";
# the network mode answers "what does a household actually SEE?" — the same
# open-loop Poisson schedule fired over real sockets at the serve gateway
# (serve/gateway.py), so wire latencies include HTTP framing, the asyncio
# handler, queue coalescing and the engine batch. Shed requests (admission
# control answering 429) are a first-class stat, not an error.


@dataclass
class NetworkLoadgenResult:
    """Per-request wire measurements from one network loadgen run."""

    latencies_s: np.ndarray    # [N] send -> FINAL response (incl. retries)
    statuses: np.ndarray       # [N] final HTTP status (-1 = transport error)
    config_hashes: List       # per request: serving bundle hash (None if shed)
    makespan_s: float          # first send -> last completion
    # Per-request retry counts and gave-up flags (all-zero when the
    # loadgen runs in its default no-retry mode).
    retries: Optional[np.ndarray] = None
    gave_up: Optional[np.ndarray] = None
    # Wire bookkeeping: which transport ran, and (mux) how many physical
    # connections it cost — the whole point of the persistent wire is
    # that wire_connects stays tiny while n_requests grows.
    transport: str = "http"
    wire_connects: int = 0
    wire_reconnects: int = 0
    wire_replays: int = 0
    # Per-request served actions (lists, None when shed/failed) — recorded
    # only when the loadgen ran with record_actions=True (the
    # continuous-vs-microbatch bit-exactness comparison needs the payloads,
    # not just the latencies).
    actions: Optional[List] = None

    def __post_init__(self):
        n = int(self.statuses.shape[0])
        if self.retries is None:
            self.retries = np.zeros(n, dtype=np.int64)
        if self.gave_up is None:
            self.gave_up = np.zeros(n, dtype=bool)

    @property
    def n_requests(self) -> int:
        return int(self.statuses.shape[0])

    @property
    def n_ok(self) -> int:
        return int((self.statuses == 200).sum())

    @property
    def n_shed(self) -> int:
        return int((self.statuses == 429).sum())

    @property
    def n_errors(self) -> int:
        return int(
            ((self.statuses != 200) & (self.statuses != 429)).sum()
        )

    @property
    def shed_rate(self) -> float:
        return self.n_shed / self.n_requests if self.n_requests else 0.0

    @property
    def total_retries(self) -> int:
        return int(self.retries.sum())

    @property
    def retry_rate(self) -> float:
        """Retries per offered request (0.0 in no-retry mode)."""
        return self.total_retries / self.n_requests if self.n_requests else 0.0

    @property
    def n_gave_up(self) -> int:
        """Requests that retried and still failed (exhausted attempts,
        budget or deadline)."""
        return int(self.gave_up.sum())

    @property
    def throughput_rps(self) -> float:
        return self.n_ok / self.makespan_s if self.makespan_s > 0 else 0.0

    def latency_ms(self, q: float) -> float:
        """Percentile over SERVED requests (shed answers return in
        microseconds and would flatter the tail)."""
        ok = self.latencies_s[self.statuses == 200]
        return float(np.percentile(ok, q) * 1e3) if ok.size else 0.0


async def _http_request_json(
    host: str,
    port: int,
    method: str,
    path: str,
    payload: Optional[dict],
    timeout_s: float,
    ssl=None,
    token: Optional[str] = None,
    trace: Optional[str] = None,
):
    """One JSON request over a fresh connection; returns (status, parsed
    body, response headers). A non-empty body that fails to parse comes
    back as ``None`` (NOT ``{}``) so callers can tell payload corruption
    from an intentionally empty response and retry it. Stdlib-only
    HTTP/1.1 — mirrors the gateway's server side; the ONE copy of the
    client framing logic (the fleet router's GETs share it). ``ssl`` is a
    client SSLContext for TLS-terminating gateways; ``token`` rides as the
    ``Authorization: Bearer`` credential (serve/auth.py); ``trace`` is an
    encoded distributed-trace context (telemetry/tracing.py) carried as
    the ``x-p2p-trace`` header — the HTTP front's propagation channel."""
    body = json.dumps(payload).encode() if payload is not None else b""
    head = f"{method} {path} HTTP/1.1\r\nHost: {host}:{port}\r\n"
    if token is not None:
        head += f"Authorization: Bearer {token}\r\n"
    if trace is not None:
        head += f"x-p2p-trace: {trace}\r\n"
    if payload is not None:
        head += (
            "Content-Type: application/json\r\n"
            f"Content-Length: {len(body)}\r\n"
        )
    request = (head + "Connection: close\r\n\r\n").encode() + body
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port, ssl=ssl), timeout_s
    )
    try:
        writer.write(request)
        await writer.drain()
        status_line = await asyncio.wait_for(reader.readline(), timeout_s)
        parts = status_line.decode("latin-1").split()
        status = int(parts[1]) if len(parts) >= 2 else -1
        length = 0
        headers = {}
        while True:
            h = await asyncio.wait_for(reader.readline(), timeout_s)
            if h in (b"\r\n", b"\n", b""):
                break
            name, _, value = h.decode("latin-1").partition(":")
            headers[name.strip().lower()] = value.strip()
            if name.strip().lower() == "content-length":
                length = int(value.strip())
        raw = (
            await asyncio.wait_for(reader.readexactly(length), timeout_s)
            if length else b""
        )
        try:
            doc = json.loads(raw.decode()) if raw else {}
        except (UnicodeDecodeError, json.JSONDecodeError):
            doc = None  # detectably corrupt payload
        return status, doc, headers
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionError, OSError):
            pass


async def _http_post_json(
    host: str, port: int, path: str, payload: dict, timeout_s: float,
    ssl=None, token: Optional[str] = None, trace: Optional[str] = None,
):
    """(status, doc, headers) of one POST — see ``_http_request_json``."""
    return await _http_request_json(
        host, port, "POST", path, payload, timeout_s, ssl=ssl, token=token,
        trace=trace,
    )


def _retry_after_s(headers: Optional[dict]) -> Optional[float]:
    """The Retry-After header as seconds, when present and numeric."""
    if not headers:
        return None
    try:
        value = headers.get("retry-after")
        return float(value) if value is not None else None
    except (TypeError, ValueError):
        return None


def run_network_loadgen(
    host: str,
    port: int,
    obs: np.ndarray,
    arrivals: np.ndarray,
    households: List[str],
    path: str = "/v1/act",
    timeout_s: float = 30.0,
    retry: Optional[RetryPolicy] = None,
    retry_seed: int = 0,
    transport: str = "http",
    ssl=None,
    token_fn=None,
    mux_pool_size: int = 2,
    mux_max_frame_bytes: Optional[int] = None,
    record_actions: bool = False,
    trace_seed: Optional[int] = None,
    trace_telemetry=None,
) -> NetworkLoadgenResult:
    """Fire ``obs[i]`` at the gateway at ``arrivals[i]`` seconds (open loop:
    send times never wait on completions) and measure wire latencies.

    ``transport="http"`` (the committed-capture default) opens one
    connection per request — each simulated household is an independent
    remote client, and this is exactly the per-request wire cost the
    persistent protocol exists to kill. ``transport="mux"`` drives the
    SAME schedule through a shared persistent multiplexed pool
    (serve/wire.py ``MuxPool`` against the gateway's mux listener at
    ``port``): keep-alive framed connections, responses matched by id —
    the head-to-head comparison ``serve-bench --wire-compare`` reports.

    ``ssl`` is a client SSLContext (TLS gateways); ``token_fn(household)``
    supplies the per-household bearer (None = unauthenticated). 401/403
    answers are TERMINAL: never retried, never charged to the retry
    machinery — an auth failure cannot become a retry storm.

    ``retry=None`` (the default) preserves the capture semantics every
    committed ``SERVE_GATEWAY_*`` row was measured under: a 429 is a
    terminal shed, a transport error a terminal failure. With a
    ``RetryPolicy``, shed (429) and transient-failure (5xx / transport /
    corrupt-payload) responses are retried with capped jittered backoff,
    honoring the server's ``Retry-After``, inside the policy's deadline;
    the result then reports ``retry_rate`` and ``n_gave_up`` next to
    ``shed_rate``, and latency includes the backoff time a real client
    would spend. Retry sleeps are seeded (``retry_seed``) so two runs
    draw identical jitter.

    ``trace_seed`` (not None) turns on distributed tracing: request ``i``
    carries the deterministic root context ``root_context(trace_seed, i)``
    on the wire (HTTP header / mux frame field), so the server-side spans
    of two replays of one schedule stitch into byte-identical trees. With
    ``trace_telemetry`` the loadgen also records the client-side root span
    (``client.request``: send -> final response, retries included) — the
    tree's top without a router in front.
    """
    if transport not in ("http", "mux"):
        raise ValueError(f"transport must be 'http' or 'mux', got {transport!r}")
    obs = np.asarray(obs, dtype=np.float32)  # host-sync: host-side inputs
    arrivals = np.asarray(arrivals, dtype=float)
    n = int(arrivals.shape[0])
    latencies = np.zeros(n)
    statuses = np.full(n, -1, dtype=np.int64)
    retries = np.zeros(n, dtype=np.int64)
    gave_up = np.zeros(n, dtype=bool)
    hashes: List = [None] * n
    actions_out: Optional[List] = [None] * n if record_actions else None
    pool_box: List = [None]  # MuxPool, created inside the event loop

    async def attempt(
        payload: dict, attempt_timeout_s: float, token: Optional[str],
        trace: Optional[str] = None,
    ):
        """(status, doc, headers); transport failures -> status -1."""
        try:
            if transport == "mux":
                if pool_box[0] is None:
                    from p2pmicrogrid_tpu.serve.wire import MuxPool

                    # Match the gateway's admission.max_body_bytes when
                    # it is configured below the wire default: the
                    # client-side cap is what makes an over-cap request
                    # a terminal 413 instead of an unattributable hang.
                    kw = {}
                    if mux_max_frame_bytes is not None:
                        kw["max_frame_bytes"] = mux_max_frame_bytes
                    pool_box[0] = MuxPool(
                        host, port, size=mux_pool_size, ssl=ssl, **kw
                    )
                return await pool_box[0].request(
                    path, payload, attempt_timeout_s, token=token,
                    trace=trace,
                )
            return await _http_post_json(
                host, port, path, payload, attempt_timeout_s,
                ssl=ssl, token=token, trace=trace,
            )
        except FrameTooLarge as err:
            # Over-cap REQUEST on the mux wire: the terminal 413 the HTTP
            # wire answers for the same payload, not a transport failure.
            return 413, {"error": str(err)}, {}
        except (
            ConnectionError, OSError, EOFError, ValueError,
            asyncio.TimeoutError, asyncio.IncompleteReadError,
            WireProtocolError,  # malformed peer frames (mux transport)
        ):
            # Transport failures score as status -1 (n_errors), they must
            # not abort the whole open-loop schedule mid-run.
            return -1, {}, {}

    async def one(i: int, t0: float) -> None:
        delay = (arrivals[i] - arrivals[0]) - (time.perf_counter() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        household = households[i % len(households)]
        payload = {"household": household, "obs": obs[i].tolist()}
        token = token_fn(household) if token_fn is not None else None
        rng = random.Random((retry_seed << 20) ^ i)
        ctx = root_context(trace_seed, i) if trace_seed is not None else None
        t_send = time.perf_counter()
        t_send_epoch = time.time()
        deadline = t_send + (retry.deadline_s if retry else timeout_s)
        tries = 0
        while True:
            # In retry mode the per-request deadline caps every attempt's
            # socket timeout too — one hung attempt must not overrun the
            # policy's wall budget by the full transport timeout.
            attempt_timeout = timeout_s if retry is None else max(
                0.05, min(timeout_s, deadline - time.perf_counter())
            )
            status, doc, headers = await attempt(
                payload, attempt_timeout, token,
                trace=ctx.encode() if ctx is not None else None,
            )
            tries += 1
            # A 200 whose payload failed to parse is a corrupt answer —
            # retryable, never reported as success.
            corrupt = status == 200 and doc is None
            ok = status == 200 and not corrupt
            # 401/403 join the terminal set: retrying a rejected
            # credential cannot succeed and must not consume the retry
            # machinery honest failures depend on.
            terminal_client_err = status in (400, 401, 403, 404, 405, 413)
            if corrupt:
                status = -1
            if (
                retry is None or ok or terminal_client_err
                or tries >= retry.max_attempts
            ):
                gave_up[i] = retry is not None and tries > 1 and not ok
                break
            # Past here the failure is retryable (shed/5xx/transport/
            # corrupt) and attempts remain — back off unless the sleep
            # itself would overrun the request deadline.
            backoff = retry.backoff_s(
                tries - 1, rng, _retry_after_s(headers)
            )
            if time.perf_counter() + backoff >= deadline:
                gave_up[i] = True
                break
            retries[i] += 1
            await asyncio.sleep(backoff)
        latencies[i] = time.perf_counter() - t_send
        statuses[i] = status
        hashes[i] = (doc or {}).get("config_hash")
        if actions_out is not None:
            actions_out[i] = (doc or {}).get("actions")
        if ctx is not None and trace_telemetry is not None:
            record_span(
                trace_telemetry, ctx, "client.request",
                t_send_epoch, float(latencies[i]),
                status=int(status), retries=int(retries[i]),
            )
            trace_telemetry.histogram(
                "client.latency_ms", float(latencies[i]) * 1e3,
                trace_id=ctx.trace_id,
            )

    async def run() -> float:
        t0 = time.perf_counter()
        try:
            await asyncio.gather(*(one(i, t0) for i in range(n)))
        finally:
            if pool_box[0] is not None:
                await pool_box[0].close()
        return time.perf_counter() - t0

    makespan = asyncio.run(run())
    pool = pool_box[0]
    return NetworkLoadgenResult(
        latencies_s=latencies,
        statuses=statuses,
        config_hashes=hashes,
        makespan_s=makespan,
        retries=retries,
        gave_up=gave_up,
        transport=transport,
        wire_connects=pool.connects if pool is not None else 0,
        wire_reconnects=pool.reconnects if pool is not None else 0,
        wire_replays=pool.replays if pool is not None else 0,
        actions=actions_out,
    )


def serve_bench_network(
    host: str,
    port: int,
    n_agents: int,
    rate_hz: float = 256.0,
    n_requests: int = 1024,
    n_households: int = 16,
    seed: int = 0,
    slo_ms: float = 100.0,
    timeout_s: float = 30.0,
    emit: Optional[Callable[[dict], None]] = None,
    extra_headline: Optional[dict] = None,
    retry: Optional[RetryPolicy] = None,
    transport: str = "http",
    ssl=None,
    token_fn=None,
    burst_factor: float = 1.0,
    burst_dwell_s: float = 0.25,
) -> List[dict]:
    """Wire-level SLO benchmark: the serve-bench schedule over real sockets.

    Same row contract as ``serve_bench`` (metric rows, headline LAST), with
    wire percentiles and the admission-control shed rate. ``vs_baseline``:
    SLO headroom for latency rows, served/offered for throughput, and the
    served fraction (1 - shed_rate) for the shed row. With ``retry`` the
    client retries sheds/transients (see ``run_network_loadgen``) and the
    headline grows ``retry_rate``/``n_gave_up``. ``transport``/``ssl``/
    ``token_fn`` select the wire (see ``run_network_loadgen``); with
    ``transport="mux"``, ``port`` is the gateway's MUX port.
    """
    arrivals, burst_config = make_arrivals(
        rate_hz, n_requests, seed=seed,
        burst_factor=burst_factor, burst_dwell_s=burst_dwell_s,
    )
    obs = synthetic_obs(n_requests, n_agents, seed=seed)
    households = [f"house-{i:04d}" for i in range(n_households)]
    result = run_network_loadgen(
        host, port, obs, arrivals, households, timeout_s=timeout_s,
        retry=retry, retry_seed=seed,
        transport=transport, ssl=ssl, token_fn=token_fn,
    )
    p50, p95, p99 = (result.latency_ms(q) for q in (50, 95, 99))
    rows = [
        {
            "metric": f"serve_gateway_latency_ms_p{q}",
            "value": round(v, 3),
            "unit": "ms",
            "vs_baseline": round(slo_ms / v, 2) if v > 0 else 0.0,
        }
        for q, v in (("50", p50), ("95", p95), ("99", p99))
    ]
    rows.append(
        {
            "metric": "serve_gateway_throughput_rps",
            "value": round(result.throughput_rps, 1),
            "unit": "requests/sec",
            "vs_baseline": round(result.throughput_rps / rate_hz, 3),
        }
    )
    rows.append(
        {
            "metric": "serve_gateway_shed_rate",
            "value": round(result.shed_rate, 4),
            "unit": "fraction",
            "vs_baseline": round(1.0 - result.shed_rate, 4),
        }
    )
    served_hashes = sorted(
        {h for h in result.config_hashes if h is not None}
    )
    rows.append(
        {
            "metric": "serve_bench_network",
            "value": round(p99, 3),
            "unit": "ms",
            "vs_baseline": round(slo_ms / p99, 2) if p99 > 0 else 0.0,
            "p50_ms": round(p50, 3),
            "p95_ms": round(p95, 3),
            "p99_ms": round(p99, 3),
            "throughput_rps": round(result.throughput_rps, 1),
            "shed_rate": round(result.shed_rate, 4),
            "n_requests": n_requests,
            "n_ok": result.n_ok,
            "n_shed": result.n_shed,
            "n_errors": result.n_errors,
            "retry_rate": round(result.retry_rate, 4),
            "n_gave_up": result.n_gave_up,
            "retry_enabled": retry is not None,
            "transport": transport,
            "tls": ssl is not None,
            "auth": token_fn is not None,
            "wire_connects": result.wire_connects,
            "wire_reconnects": result.wire_reconnects,
            "n_households": n_households,
            "offered_rate_rps": rate_hz,
            "slo_ms": slo_ms,
            "burst_config": burst_config,
            "served_config_hashes": served_hashes,
            **(extra_headline or {}),
        }
    )
    if emit is not None:
        for row in rows:
            emit(row)
    return rows


def serve_bench_wire_compare(
    host: str,
    http_port: int,
    mux_port: int,
    n_agents: int,
    rate_hz: float = 256.0,
    n_requests: int = 512,
    n_households: int = 16,
    seed: int = 0,
    timeout_s: float = 30.0,
    ssl=None,
    token_fn=None,
    emit: Optional[Callable[[dict], None]] = None,
) -> dict:
    """The per-request-connection client vs the persistent multiplexed
    wire, SAME open-loop schedule and observations, one ``wire_comparison``
    row: per-transport p50/p95/p99 and the mux/http speedups. This is the
    acceptance measurement for the persistent wire — the committed
    ``FLEET_PROC_*`` captures carry it next to the chaos headline."""
    arrivals = poisson_arrivals(rate_hz, n_requests, seed=seed)
    obs = synthetic_obs(n_requests, n_agents, seed=seed)
    households = [f"house-{i:04d}" for i in range(n_households)]
    results = {}
    for transport, port in (("http", http_port), ("mux", mux_port)):
        results[transport] = run_network_loadgen(
            host, port, obs, arrivals, households, timeout_s=timeout_s,
            transport=transport, ssl=ssl, token_fn=token_fn,
        )
    http_r, mux_r = results["http"], results["mux"]
    p95_http, p95_mux = http_r.latency_ms(95), mux_r.latency_ms(95)
    row = {
        "metric": "wire_comparison",
        "value": round(p95_http / p95_mux, 3) if p95_mux > 0 else 0.0,
        "unit": "x_p95_speedup",
        # >= 1.0 means the persistent wire beats per-request connections
        # on p95 — the acceptance bar.
        "vs_baseline": round(p95_http / p95_mux, 3) if p95_mux > 0 else 0.0,
        "n_requests": n_requests,
        "offered_rate_rps": rate_hz,
        "tls": ssl is not None,
        "auth": token_fn is not None,
        "http_p50_ms": round(http_r.latency_ms(50), 3),
        "http_p95_ms": round(p95_http, 3),
        "http_p99_ms": round(http_r.latency_ms(99), 3),
        "http_n_ok": http_r.n_ok,
        "mux_p50_ms": round(mux_r.latency_ms(50), 3),
        "mux_p95_ms": round(p95_mux, 3),
        "mux_p99_ms": round(mux_r.latency_ms(99), 3),
        "mux_n_ok": mux_r.n_ok,
        "mux_connections": mux_r.wire_connects,
    }
    if emit is not None:
        emit(row)
    return row


def serve_bench(
    engine,
    rate_hz: float = 256.0,
    n_requests: int = 2048,
    max_batch: Optional[int] = None,
    max_wait_s: float = 0.002,
    seed: int = 0,
    slo_ms: float = 100.0,
    emit: Optional[Callable[[dict], None]] = None,
    service_time_fn: Optional[Callable[[int, int], float]] = None,
    burst_factor: float = 1.0,
    burst_dwell_s: float = 0.25,
) -> List[dict]:
    """Drive ``engine`` with an open-loop Poisson stream; report SLO metrics.

    Emits (and returns) metric rows in the bench schema. ``vs_baseline``
    semantics per row: latency rows report SLO headroom (``slo_ms / pXX`` —
    > 1 means inside budget); throughput reports achieved/offered;
    padding-waste reports the useful-row fraction (1 - waste). The LAST row
    is the headline, carrying all stats plus compile/execute span timings.
    """
    from p2pmicrogrid_tpu.telemetry import current, phase_timings

    max_batch = min(max_batch or engine.max_batch, engine.max_batch)
    arrivals, burst_config = make_arrivals(
        rate_hz, n_requests, seed=seed,
        burst_factor=burst_factor, burst_dwell_s=burst_dwell_s,
    )
    obs = synthetic_obs(n_requests, engine.n_agents, seed=seed)

    tel = current()
    with tel.span("compile:serve", max_batch=max_batch):
        # Pre-compile every bucket the planner can hit: tail latency must
        # measure the queue + device, not one-off XLA compiles. The limit is
        # the bucket a full max_batch PADS to — with a non-power-of-two
        # max_batch, batches between the last smaller bucket and max_batch
        # land in bucket_for(max_batch), which must be warm too.
        limit = engine.bucket_for(max_batch)
        # include_step=False: this benchmark only drives act(); compiling
        # the session-step executables would double compile_s for nothing.
        engine.warmup(
            [b for b in engine.buckets if b <= limit], include_step=False
        )

    if service_time_fn is None:

        def service_time_fn(i, j):
            t0 = time.perf_counter()
            engine.act(obs[i:j])
            return time.perf_counter() - t0

    with tel.span("execute:serve", n_requests=n_requests, rate_hz=rate_hz):
        result = plan_open_loop(
            arrivals,
            service_time_fn,
            max_batch=max_batch,
            max_wait_s=max_wait_s,
            bucket_fn=engine.bucket_for,
        )

    if tel.sinks:
        # Per-request trace records through the run's sinks (the SQLite
        # warehouse when serve-bench got --results-db): every request's
        # enqueue->dispatch wait, its batch's bucket/padding and the shared
        # service span — the raw rows behind the percentile summary, SQL-
        # queryable next to training telemetry. Skipped sink-less: the
        # records would go nowhere.
        _emit_request_traces(tel, arrivals, result)

    p50, p95, p99 = (result.latency_ms(q) for q in (50, 95, 99))
    waste = result.padding_waste
    rows = [
        {
            "metric": f"serve_latency_ms_p{q}",
            "value": round(v, 3),
            "unit": "ms",
            "vs_baseline": round(slo_ms / v, 2) if v > 0 else 0.0,
        }
        for q, v in (("50", p50), ("95", p95), ("99", p99))
    ]
    rows.append(
        {
            "metric": "serve_throughput_rps",
            "value": round(result.throughput_rps, 1),
            "unit": "requests/sec",
            "vs_baseline": round(result.throughput_rps / rate_hz, 3),
        }
    )
    rows.append(
        {
            "metric": "serve_padding_waste",
            "value": round(waste, 4),
            "unit": "fraction",
            "vs_baseline": round(1.0 - waste, 4),
        }
    )
    rows.append(
        {
            "metric": "serve_bench",
            "value": round(p99, 3),
            "unit": "ms",
            "vs_baseline": round(slo_ms / p99, 2) if p99 > 0 else 0.0,
            "p50_ms": round(p50, 3),
            "p95_ms": round(p95, 3),
            "p99_ms": round(p99, 3),
            "throughput_rps": round(result.throughput_rps, 1),
            "padding_waste": round(waste, 4),
            "n_requests": n_requests,
            "offered_rate_rps": rate_hz,
            "max_batch": max_batch,
            "max_wait_ms": round(max_wait_s * 1e3, 3),
            "slo_ms": slo_ms,
            "n_batches": len(result.batch_sizes),
            "burst_config": burst_config,
            "implementation": engine.manifest.get("implementation"),
            "n_agents": engine.n_agents,
            "config_hash": engine.manifest.get("config_hash"),
            **phase_timings("serve"),
        }
    )
    if emit is not None:
        for row in rows:
            emit(row)
    return rows
