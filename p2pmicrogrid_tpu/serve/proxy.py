"""Standalone router proxy: the fleet front as its own process.

Until now the ``FleetRouter`` lived inside the client (serve-bench's
loadgen imported it as a library) — fine for benching, wrong for trust:
untrusted households cannot be handed a routing table, health state and
the fleet's admin credentials. ``serve-router`` runs the router as a
PROXY process instead:

    households ──TLS+token──> serve-router ──mux──> replica processes

* The proxy terminates TLS and per-household bearer auth at its own
  socket (the replicas can then live on a trusted segment), exposing the
  same ``/v1/act`` contract as a gateway — single-row or batched obs —
  plus ``/healthz``, ``/readyz`` (ready while ANY replica is healthy,
  body carries the fleet ``config_hash``), ``/stats`` (the aggregated
  ``fleet_stats`` snapshot; operator wildcard token) and ``/admin/swap``
  (two-phase fleet-wide swap; wildcard token).
* Toward the replicas it speaks the persistent multiplexed wire
  (serve/wire.py) with the router's retry/failover/health discipline —
  one pool per replica, reconnect + health-ejection on failure.
* A mux listener (``mux_port``) serves framed clients next to the HTTP
  front, sharing one routing path, so persistent-wire households can
  keep their connection through the proxy too.

``ProxyServer`` is the daemon-thread facade (the ``GatewayServer``
pattern) for tests and the CLI.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Optional, Tuple

import numpy as np

from p2pmicrogrid_tpu.serve.gateway import (
    _HttpError,
    bearer_token,
    enforce_auth,
    read_http_request,
    route_safely,
    send_http_response,
)
from p2pmicrogrid_tpu.serve.router import FleetRouter, FleetSwapError
from p2pmicrogrid_tpu.serve.wire import serve_mux_connection
from p2pmicrogrid_tpu.telemetry.tracing import TRACE_HEADER, record_span
from p2pmicrogrid_tpu.telemetry.tracing import decode as decode_trace


class RouterProxy:
    """Asyncio HTTP(S) + mux front over a ``FleetRouter``."""

    def __init__(
        self,
        router: FleetRouter,
        host: str = "127.0.0.1",
        port: int = 0,
        mux_port: Optional[int] = None,
        tls=None,
        authenticator=None,
        request_timeout_s: float = 30.0,
        max_body_bytes: int = 1 << 20,
        max_request_rows: int = 64,
    ):
        self.router = router
        self.host = host
        self.port = port
        self.mux_port = mux_port
        self.tls = tls
        self.authenticator = authenticator
        self.request_timeout_s = request_timeout_s
        self.max_body_bytes = max_body_bytes
        self.max_request_rows = max_request_rows
        self._t0 = time.monotonic()
        self._server: Optional[asyncio.AbstractServer] = None
        self._mux_server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()
        self.stats = {
            "requests": 0, "act_requests": 0, "act_ok": 0,
            "auth_401": 0, "auth_403": 0, "http_errors": 0,
            "mux_connections": 0,
        }

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> Tuple[str, int]:
        self._server = await asyncio.start_server(
            self._handle_http, self.host, self.port, ssl=self.tls
        )
        self.port = self._server.sockets[0].getsockname()[1]
        if self.mux_port is not None:
            self._mux_server = await asyncio.start_server(
                self._handle_mux, self.host, self.mux_port, ssl=self.tls
            )
            self.mux_port = self._mux_server.sockets[0].getsockname()[1]
        return self.host, self.port

    async def stop(self) -> None:
        for attr in ("_server", "_mux_server"):
            server = getattr(self, attr)
            if server is not None:
                server.close()
                await server.wait_closed()
                setattr(self, attr, None)
        await self.router.close_pools()
        for writer in list(self._conns):
            writer.close()

    # -- auth ----------------------------------------------------------------

    def _check_act(self, token, household):
        """Returns the effective household — a field-less request with a
        non-wildcard token routes as the token's household (gateway
        semantics: the token IS the identity)."""
        if self.authenticator is None:
            return household
        claims = enforce_auth(
            lambda: self.authenticator.check(token, household),
            self.stats,
        )
        from p2pmicrogrid_tpu.serve.auth import WILDCARD_HOUSEHOLD

        claimed = claims.get("household")
        if household is None and claimed != WILDCARD_HOUSEHOLD:
            return claimed
        return household

    def _check_admin(self, token) -> None:
        if self.authenticator is not None:
            enforce_auth(
                lambda: self.authenticator.check_admin(token), self.stats
            )

    # -- routing -------------------------------------------------------------

    async def _route(self, method: str, path: str, doc, token, trace=None):
        if path == "/healthz":
            if method != "GET":
                raise _HttpError(405, "GET only")
            return 200, {
                "ok": True,
                "uptime_s": round(time.monotonic() - self._t0, 3),
            }, []
        if path == "/readyz":
            if method != "GET":
                raise _HttpError(405, "GET only")
            healthy = self.router.healthy_ids()
            body = {
                "ready": bool(healthy),
                "config_hash": self.router.fleet_config_hash,
                "n_healthy": len(healthy),
            }
            return (200 if healthy else 503), body, []
        if path == "/stats":
            if method != "GET":
                raise _HttpError(405, "GET only")
            self._check_admin(token)
            # fleet_stats fans out synchronous per-replica GETs — off the
            # event loop, or every in-flight act request stalls behind it.
            snapshot = await asyncio.get_running_loop().run_in_executor(
                None, self.router.fleet_stats
            )
            snapshot["proxy"] = dict(self.stats)
            return 200, snapshot, []
        if path == "/v1/act":
            if method != "POST":
                raise _HttpError(405, "POST only")
            return await self._act(doc, token, trace=trace)
        if path == "/admin/swap":
            if method != "POST":
                raise _HttpError(405, "POST only")
            self._check_admin(token)
            if not isinstance(doc, dict) or not isinstance(
                doc.get("config_hash"), str
            ):
                raise _HttpError(400, "pass a string 'config_hash'")
            try:
                outcome = await self.router.swap_fleet(doc["config_hash"])
            except FleetSwapError as err:
                raise _HttpError(502, str(err)) from None
            return 200, outcome, []
        raise _HttpError(404, f"no route {path}")

    async def _act(self, doc, token, trace=None):
        self.stats["act_requests"] += 1
        ctx = decode_trace(trace)
        p_ctx = ctx.child("proxy.act") if ctx is not None else None
        t0 = time.monotonic()
        t0_epoch = time.time()
        if not isinstance(doc, dict):
            raise _HttpError(400, "body must be a JSON object")
        household = doc.get("household")
        if household is not None and not isinstance(household, str):
            raise _HttpError(400, "household must be a string")
        household = self._check_act(token, household)
        if "obs" not in doc:
            raise _HttpError(400, "missing 'obs'")
        try:
            # host-sync: caller-supplied JSON observations, not device values.
            obs = np.asarray(doc["obs"], dtype=np.float32)
        except (TypeError, ValueError) as err:
            raise _HttpError(400, f"obs is not numeric: {err}") from None
        batched = obs.ndim == 3
        if obs.ndim == 2:
            obs = obs[None]
        if obs.ndim != 3:
            raise _HttpError(400, "obs must be [A, 4] or [B, A, 4]")
        if obs.shape[0] > self.max_request_rows:
            raise _HttpError(
                413,
                f"batch of {obs.shape[0]} exceeds the "
                f"{self.max_request_rows}-row request limit",
            )
        results = await asyncio.gather(*(
            self.router.act(
                household, row, deadline_s=self.request_timeout_s,
                trace=(p_ctx.child(f"row{i}") if p_ctx is not None else None),
            )
            for i, row in enumerate(obs)
        ))
        worst = next((r for r in results if not r.ok), None)

        def finish(status: int):
            if p_ctx is not None:
                record_span(
                    self.router.telemetry, p_ctx, "proxy.act",
                    t0_epoch, time.monotonic() - t0,
                    status=status, n_rows=len(obs), hop=ctx.hop,
                )

        if worst is not None:
            extra = (
                [("Retry-After", f"{worst.retry_after_s:g}")]
                if worst.retry_after_s is not None else []
            )
            status = worst.status if worst.status > 0 else 502
            finish(status)
            return status, {"error": worst.error or "replica failure"}, extra
        actions = [r.actions for r in results]
        self.stats["act_ok"] += 1
        finish(200)
        return 200, {
            "actions": actions if batched else actions[0],
            "config_hash": results[0].config_hash,
            "replica_id": results[0].replica_id,
        }, []

    # -- fronts --------------------------------------------------------------

    async def _route_bytes(self, method, path, body: bytes, token, trace=None):
        import json as _json

        doc = None
        if body:
            try:
                doc = _json.loads(body.decode())
            except (UnicodeDecodeError, _json.JSONDecodeError) as err:
                raise _HttpError(
                    400, f"body is not valid JSON: {err}"
                ) from None
        return await self._route(method, path, doc, token, trace=trace)

    async def _handle_http(self, reader, writer) -> None:
        self._conns.add(writer)
        try:
            while True:
                try:
                    request = await asyncio.wait_for(
                        read_http_request(reader, self.max_body_bytes),
                        self.request_timeout_s,
                    )
                except asyncio.TimeoutError:
                    break
                except _HttpError as err:
                    self.stats["requests"] += 1
                    self.stats["http_errors"] += 1
                    await send_http_response(
                        writer, err.status, err.payload, [], False
                    )
                    break
                if request is None:
                    break
                method, path, headers, body = request
                self.stats["requests"] += 1
                status, payload, extra = await route_safely(
                    self._route_bytes(
                        method, path, body, bearer_token(headers),
                        trace=headers.get(TRACE_HEADER),
                    ),
                    self.stats,
                )
                keep_alive = headers.get("connection", "").lower() != "close"
                await send_http_response(
                    writer, status, payload, extra, keep_alive
                )
                if not keep_alive:
                    break
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            self._conns.discard(writer)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _mux_route(self, method, path, body_doc, token, trace=None):
        self.stats["requests"] += 1
        return await route_safely(
            self._route(method, path, body_doc, token, trace=trace),
            self.stats,
        )

    async def _handle_mux(self, reader, writer) -> None:
        self._conns.add(writer)
        self.stats["mux_connections"] += 1
        try:
            await serve_mux_connection(
                reader, writer, self._mux_route,
                max_frame_bytes=self.max_body_bytes,
            )
        finally:
            self._conns.discard(writer)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass


class ProxyServer:
    """Run a ``RouterProxy`` on a daemon thread with its own loop."""

    def __init__(self, proxy: RouterProxy):
        self.proxy = proxy
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_lock = threading.Lock()

    def start(self, timeout_s: float = 60.0) -> Tuple[str, int]:
        started = threading.Event()
        failure: list = []

        def run() -> None:
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            try:
                loop.run_until_complete(self.proxy.start())
            except Exception as err:  # noqa: BLE001 — surface to start()
                failure.append(err)
                loop.close()
                started.set()
                return
            self._loop = loop
            started.set()
            try:
                loop.run_forever()
            finally:
                loop.close()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        if not started.wait(timeout_s):
            raise TimeoutError("router proxy did not start in time")
        if failure:
            self._thread.join(timeout=5.0)
            self._thread = None
            raise failure[0]
        return self.proxy.host, self.proxy.port

    def stop(self, timeout_s: float = 30.0) -> None:
        async def teardown() -> None:
            await self.proxy.stop()
            tasks = [
                t for t in asyncio.all_tasks()
                if t is not asyncio.current_task()
            ]
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

        with self._stop_lock:
            loop = self._loop
            if loop is None:
                return
            future = asyncio.run_coroutine_threadsafe(teardown(), loop)
            try:
                future.result(timeout=timeout_s)
            finally:
                loop.call_soon_threadsafe(loop.stop)
                if self._thread is not None:
                    self._thread.join(timeout=10.0)
                self._loop = None
                self._thread = None

    def __enter__(self) -> "ProxyServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
